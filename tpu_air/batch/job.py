"""airbatch: the elastic offline batch-inference lane.

A :class:`BatchJob` streams every row of a :class:`tpu_air.data.Dataset`
through an already-deployed serve route — the SAME engines, admission
controller, journal, and preemption watcher the interactive lane uses —
at ``best_effort`` priority, so offline throughput soaks whatever the
online SLO leaves on the table and never competes with it:

* **One admission path.**  Every row is admitted through the route's
  :class:`~tpu_air.serve.admission.AdmissionController` exactly like an
  HTTP client; under interactive pressure the controller sheds
  ``best_effort`` first and the runner backs off.  There is no second
  queue to tune and no way for batch to starve interactive.
* **Checkpointable sharded readers** (:mod:`tpu_air.batch.reader`):
  deterministic ``(seed, cursor)``-addressed row streams.  Outputs land
  in the object store as immutable chunk objects with DETERMINISTIC ids,
  cursors are journaled as checkpoint objects, and the commit order is
  chunk-then-checkpoint — so a driver killed at ANY point resumes with
  zero dropped and zero duplicated rows: an already-present chunk id is
  skipped, an absent one is recomputed from the same row stream.
* **Elastic chip borrowing.**  When the route is idle (admission gauges
  low, autoscaler idle-ticking, free chips in the pool) the runner
  borrows a replica via ``scale_up`` and widens its in-flight window;
  when interactive load returns it hands the replica back THROUGH the
  preemption path (``borrow_return`` delivers a lease revocation notice;
  the :class:`~tpu_air.serve.supervisor.PreemptionWatcher` drains and
  migrates in-flight streams, skipping the autoscaler backfill because
  the capacity is leaving on purpose).
* **Observability.**  Work is billed to airwatch tenant
  ``batch:<job_id>`` (CostLedger splits batch vs interactive
  chip-seconds), progress gauges surface on ``/-/stats`` → ``batch`` /
  the dashboard's ``/api/batch`` / ``tpu_air_batch_*`` prometheus
  families, and each run emits a ``batch.job`` → ``batch.chunk`` span
  tree.

This is the serve-lane complement to
:class:`tpu_air.predict.BatchPredictor`, which owns its own actor pool
and chips; see that module's docstring for the boundary.

Chaos: the runner exposes the ``batch.runner`` fault site at every
chunk-commit boundary — a ``kill`` spec raises :class:`BatchJobKilled`
after the chunk object is durable but before the cursor checkpoint, the
hardest resume case (tests/test_batch.py proves exactly-once across it).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tpu_air.batch.reader import ShardCursor, ShardedReader
from tpu_air.core.runtime import RemoteError, TpuAirError, get_runtime
from tpu_air.faults import plan as _faults
from tpu_air.faults.retry import Backoff
from tpu_air.observability import tracing as _tracing
from tpu_air.observability import watch as _watch
from tpu_air.serve.admission import AdmissionShedError
from tpu_air.serve.deployment import NoLiveReplicasError, ReplicaGoneError
from tpu_air.serve.supervisor import journaled_poll


class BatchJobKilled(TpuAirError):
    """The job driver died mid-epoch (chaos ``batch.runner`` kill spec).

    Raised at a chunk-commit boundary to simulate driver preemption; a
    fresh :class:`BatchJob` with the same ``job_id`` resumes from the
    journaled cursors and committed chunks."""


@dataclass
class BatchJobConfig:
    """Knobs for one batch job.  The determinism fingerprint — ``seed``,
    ``num_shards``, ``chunk_rows``, ``input_column`` — is frozen into the
    first checkpoint; a resume with different values is refused rather
    than silently re-sharding mid-epoch."""

    route_prefix: str = "/"
    input_column: str = "prompt"
    max_new_tokens: int = 16
    #: admission class for every row; ``best_effort`` is the point of the
    #: lane (shed first under pressure) but ``batch`` is accepted too
    priority: str = "best_effort"
    num_shards: int = 2
    seed: int = 0
    #: rows per commit unit — one object-store chunk + fault-site hit
    chunk_rows: int = 32
    #: base in-flight window (driver worker threads); widened by borrowing
    window: int = 8
    checkpoint_every_chunks: int = 1
    row_timeout_s: float = 120.0
    submit_timeout_s: float = 60.0
    poll_interval_s: float = 0.01
    shed_backoff_s: float = 0.05
    shed_backoff_cap_s: float = 1.0
    # -- elastic chip borrowing ------------------------------------------
    borrow: bool = False
    #: autoscaler idle ticks required before soaking (skipped when the
    #: route runs without an autoscaler — the depth gate still applies)
    borrow_idle_ticks: int = 3
    #: queue depth per replica at/below which the route counts as a trough
    borrow_depth_low: float = 0.5
    #: depth at/above which borrowed capacity is handed back immediately
    borrow_depth_high: float = 2.0
    borrow_max_replicas: int = 1
    borrow_notice_s: float = 5.0
    borrow_spawn_timeout_s: float = 120.0


class BatchJob:
    """One resumable batch-inference job over a dataset.

    ``run()`` drives the whole epoch and returns :meth:`stats`; outputs
    are keyed by GLOBAL row index via :meth:`results`.  Re-running the
    same ``job_id`` after a crash resumes; re-running after completion is
    a no-op that re-reads the committed chunks.

    ``row_fn`` swaps the engine round-trip for a local function
    ``prompt -> tokens`` — the checkpoint/chunk machinery is identical,
    which is how the unit tests prove resume exactness without a serve
    stack.

    Thread model: ``run()`` is single-driver; ``_process_chunk`` fans the
    chunk's rows over ``window`` worker threads.  ``self._lock`` is the
    ONLY lock this class takes (no ordering to invert) and nothing
    blocking runs under it.
    """

    def __init__(self, dataset, job_id: Optional[str] = None,
                 config: Optional[BatchJobConfig] = None, *,
                 row_fn: Optional[Callable[[List[int]], Sequence[int]]] = None):
        self.dataset = dataset
        self.job_id = str(job_id) if job_id else f"job-{uuid.uuid4().hex[:8]}"
        self.cfg = config or BatchJobConfig()
        if self.cfg.priority not in ("batch", "best_effort"):
            raise ValueError(
                "batch lane priority must be 'batch' or 'best_effort', got "
                f"{self.cfg.priority!r} — interactive is the lane it yields to")
        self.tenant = f"batch:{self.job_id}"
        self._row_fn = row_fn
        self._lock = threading.Lock()
        # -- all fields below are guarded by _lock ------------------------
        self._state = "created"
        self._started = 0.0
        self._elapsed = 0.0
        self.rows_total = 0
        self.rows_processed = 0   # actually ran through the engine THIS run
        self.rows_resumed = 0     # skipped: committed by a previous run
        self.chunks_done = 0
        self.chunks_resumed = 0
        self.checkpoints = 0
        self.resumes = 0          # 1 when this run started from a checkpoint
        self.inflight = 0
        self.shed_retries = 0
        self.submit_retries = 0
        self.borrows = 0
        self.borrow_returns = 0
        self._borrowed: set = set()   # replica tags currently on loan to us
        self._window_live = int(self.cfg.window)
        self._next_ckpt_seq = 0

    # -- deterministic object-store addressing ---------------------------
    def _chunk_id(self, shard: int, chunk: int) -> str:
        return f"airbatch-{self.job_id}-s{shard:03d}-c{chunk:06d}"

    def _ckpt_id(self, seq: int) -> str:
        return f"airbatch-{self.job_id}-ckpt-{seq:06d}"

    def _fingerprint(self, counts: Sequence[int]) -> Dict[str, Any]:
        return {
            "seed": int(self.cfg.seed),
            "num_shards": int(self.cfg.num_shards),
            "chunk_rows": int(self.cfg.chunk_rows),
            "input_column": str(self.cfg.input_column),
            "counts": [int(c) for c in counts],
        }

    # -- public API ------------------------------------------------------
    def run(self) -> Dict[str, Any]:
        """Drive the epoch to completion (or resume it), returning
        :meth:`stats`.  Raises :class:`BatchJobKilled` when a chaos plan
        kills the driver — rerun to resume."""
        register_job(self)
        with self._lock:
            self._state = "running"
            self._started = time.monotonic()
        ctl = None
        if self._row_fn is None:
            from tpu_air.serve.proxy import route_control
            ctl = route_control(self.cfg.route_prefix)
        try:
            self._run_inner(ctl)
            # graceful end-of-epoch: hand back any loan BEFORE the final
            # snapshot so the returned stats show nothing outstanding
            self._return_all_borrowed(ctl)
            with self._lock:
                self._state = "done"
                self._elapsed = time.monotonic() - self._started
            return self.stats()
        except BaseException:  # noqa: BLE001 — state bookkeeping only, re-raised unchanged
            with self._lock:
                self._state = "failed"
                self._elapsed = time.monotonic() - self._started
            raise
        finally:
            # never strand borrowed chips, even on a crash path — the
            # interactive lane gets its capacity back through the same
            # drain it would see on a graceful return
            self._return_all_borrowed(ctl)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            elapsed = (self._elapsed if self._state in ("done", "failed")
                       else (time.monotonic() - self._started
                             if self._started else 0.0))
            return {
                "job_id": self.job_id,
                "tenant": self.tenant,
                "state": self._state,
                "priority": self.cfg.priority,
                "rows_total": self.rows_total,
                "rows_processed": self.rows_processed,
                "rows_resumed": self.rows_resumed,
                "rows_done": self.rows_processed + self.rows_resumed,
                "rows_per_s": (self.rows_processed / elapsed
                               if elapsed > 0 else 0.0),
                "chunks_done": self.chunks_done,
                "chunks_resumed": self.chunks_resumed,
                "checkpoints": self.checkpoints,
                "resumes": self.resumes,
                "inflight": self.inflight,
                "window": self._window_live,
                "borrowed_replicas": len(self._borrowed),
                "borrows": self.borrows,
                "borrow_returns": self.borrow_returns,
                "shed_retries": self.shed_retries,
                "submit_retries": self.submit_retries,
                "elapsed_s": elapsed,
            }

    def results(self) -> Dict[int, List[int]]:
        """Union of every committed chunk, keyed by global row index.
        Complete exactly when the job has finished one epoch."""
        store = get_runtime().store
        counts = [int(c) for c in self.dataset._row_counts()]
        out: Dict[int, List[int]] = {}
        for s in range(self.cfg.num_shards):
            total = ShardedReader(self.dataset, s, self.cfg.num_shards,
                                  self.cfg.seed, counts=counts).total_rows()
            nchunks = (total + self.cfg.chunk_rows - 1) // self.cfg.chunk_rows
            for c in range(nchunks):
                cid = self._chunk_id(s, c)
                if not store.contains(cid):
                    continue
                for gi, toks in store.get(cid)["rows"].items():
                    out[int(gi)] = list(toks)
        return out

    # -- the epoch loop --------------------------------------------------
    def _run_inner(self, ctl: Optional[Dict[str, Any]]) -> None:
        cfg = self.cfg
        store = get_runtime().store
        counts = [int(c) for c in self.dataset._row_counts()]
        readers = [ShardedReader(self.dataset, s, cfg.num_shards, cfg.seed,
                                 counts=counts)
                   for s in range(cfg.num_shards)]
        totals = [r.total_rows() for r in readers]
        cursors = self._load_cursors(store, counts)
        with self._lock:
            self.rows_total = sum(totals)
            resumed = self.resumes
        # per-shard live row iterator + its stream position, so sequential
        # chunks don't refetch the block (rebuilt whenever a resume skip
        # moves the cursor away from the iterator)
        iters: List[Tuple[Optional[Any], int]] = [(None, -1)] * cfg.num_shards
        chunks_since_ckpt = 0
        with _tracing.span("batch.job", attrs={
                "job_id": self.job_id, "tenant": self.tenant,
                "rows": sum(totals), "num_shards": cfg.num_shards,
                "seed": cfg.seed, "resumed": resumed}):
            while any(cursors[s].rows_done < totals[s]
                      for s in range(cfg.num_shards)):
                for s in range(cfg.num_shards):
                    done = cursors[s].rows_done
                    if done >= totals[s]:
                        continue
                    chunk = done // cfg.chunk_rows
                    n = min(cfg.chunk_rows, totals[s] - done)
                    cid = self._chunk_id(s, chunk)
                    if store.contains(cid):
                        # committed by a previous incarnation (possibly
                        # AFTER its last checkpoint): skip, never re-emit
                        cursors[s].rows_done = done + n
                        with self._lock:
                            self.chunks_resumed += 1
                            self.rows_resumed += n
                    else:
                        if ctl is not None:
                            self._maybe_borrow(ctl)
                        items = self._take(readers, iters, s, done, n)
                        with _tracing.span("batch.chunk", attrs={
                                "job_id": self.job_id, "shard": s,
                                "chunk": chunk, "rows": n}):
                            outputs = self._process_chunk(items, ctl)
                        # aircrash: data batch-chunk
                        store.put({"job_id": self.job_id, "shard": s,
                                   "chunk": chunk, "rows": outputs},
                                  object_id=cid)
                        cursors[s].rows_done = done + n
                        with self._lock:
                            self.chunks_done += 1
                    # chaos hook at the commit boundary: the chunk object
                    # is durable, the cursor checkpoint is not — a kill
                    # here is the hardest resume case (the chunk must be
                    # SKIPPED next run, not recomputed and double-emitted)
                    if _faults.enabled():
                        spec = _faults.perturb("batch.runner",
                                               key=self.job_id)
                        if spec is not None and spec.action == "kill":
                            raise BatchJobKilled(
                                f"fault plan killed batch driver {self.job_id}"
                                f" at shard {s} chunk {chunk}")
                    chunks_since_ckpt += 1
                    if chunks_since_ckpt >= cfg.checkpoint_every_chunks:
                        self._write_checkpoint(store, counts, cursors)
                        chunks_since_ckpt = 0
            self._write_checkpoint(store, counts, cursors)

    def _take(self, readers, iters, s: int, start: int,
              n: int) -> List[Tuple[int, Dict[str, Any]]]:
        it, pos = iters[s]
        if it is None or pos != start:
            it = readers[s].rows(start)
            pos = start
        out = []
        for _ in range(n):
            out.append(next(it))
            pos += 1
        iters[s] = (it, pos)
        return out

    # -- checkpoints -----------------------------------------------------
    def _load_cursors(self, store, counts) -> List[ShardCursor]:
        latest = None
        seq = 0
        while store.contains(self._ckpt_id(seq)):
            latest = store.get(self._ckpt_id(seq))
            seq += 1
        self._next_ckpt_seq = seq
        if latest is None:
            return [ShardCursor(shard=s) for s in range(self.cfg.num_shards)]
        if latest.get("fingerprint") != self._fingerprint(counts):
            raise ValueError(
                f"batch job {self.job_id!r} checkpoint was written with a "
                "different (seed, num_shards, chunk_rows, input_column, "
                "dataset) — resuming would re-shard mid-epoch and break "
                "exactly-once; use a fresh job_id")
        cursors = [ShardCursor.from_dict(d) for d in latest["cursors"]]
        with self._lock:
            self.resumes = 1
            # rows behind the checkpointed cursors were committed by a
            # previous incarnation; chunks committed AFTER the checkpoint
            # add to this via the contains-skip path in the loop
            self.rows_resumed += sum(c.rows_done for c in cursors)
        w = _watch.current()
        if w is not None:
            w.note("batch.resume", job=self.job_id,
                   rows_done=sum(c.rows_done for c in cursors))
        return cursors

    def _write_checkpoint(self, store, counts,
                          cursors: List[ShardCursor]) -> None:
        # aircrash: commits batch-chunk
        store.put({
            "job_id": self.job_id,
            "seq": self._next_ckpt_seq,
            "fingerprint": self._fingerprint(counts),
            "cursors": [c.to_dict() for c in cursors],
        }, object_id=self._ckpt_id(self._next_ckpt_seq))
        self._next_ckpt_seq += 1
        with self._lock:
            self.checkpoints += 1

    # -- chunk fan-out ---------------------------------------------------
    def _process_chunk(self, items, ctl) -> Dict[int, List[int]]:
        with self._lock:
            window = self._window_live
        nthreads = max(1, min(window, len(items)))
        outputs: Dict[int, List[int]] = {}
        failures: List[BaseException] = []
        next_idx = [0]

        def worker() -> None:
            while True:
                with self._lock:
                    if failures or next_idx[0] >= len(items):
                        return
                    gi, row = items[next_idx[0]]
                    next_idx[0] += 1
                    self.inflight += 1
                try:
                    toks = self._run_row(gi, row, ctl)
                except BaseException as e:  # noqa: BLE001 — surfaced below, chunk fails atomically
                    with self._lock:
                        failures.append(e)
                        self.inflight -= 1
                    return
                with self._lock:
                    outputs[gi] = list(toks)
                    self.rows_processed += 1
                    self.inflight -= 1

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"airbatch-{self.job_id}-w{i}")
                   for i in range(nthreads)]
        for t in threads:
            t.start()
        # a surge must preempt the loan back NOW, not at the next chunk
        # boundary — under interactive pressure best_effort rows crawl,
        # so the boundary could be many seconds out
        while True:
            alive = [t for t in threads if t.is_alive()]
            if not alive:
                break
            alive[0].join(timeout=0.25)
            self._surge_return(ctl)
        if failures:
            raise failures[0]
        return outputs

    def _surge_return(self, ctl) -> None:
        """Mid-chunk fast path of :meth:`_maybe_borrow`: hand the loan
        back (and narrow the window) the moment interactive depth climbs.
        Never borrows — loans are only taken at chunk boundaries."""
        if ctl is None:
            return
        admission = ctl.get("admission")
        if admission is None:
            return
        with self._lock:
            holding = len(self._borrowed)
        if not holding:
            return
        depth = float(admission.gauges().get("depth_per_replica") or 0.0)
        if depth < self.cfg.borrow_depth_high:
            return
        with self._lock:
            self._window_live = max(1, self.cfg.window // 2)
        self._return_all_borrowed(ctl)

    def _run_row(self, gi: int, row: Dict[str, Any],
                 ctl: Optional[Dict[str, Any]]) -> List[int]:
        prompt = [int(t) for t in row[self.cfg.input_column]]
        if self._row_fn is not None:
            return list(self._row_fn(prompt))
        cfg = self.cfg
        handle = ctl["handle"]
        admission = ctl["admission"]
        journal = ctl["journal"]
        mnt = int(cfg.max_new_tokens)
        if admission is not None:
            clamped = admission.policy.clamp_budget(cfg.priority, mnt, None)
            if clamped is not None:
                mnt = int(clamped)
        # seeded per-row backoff: chaos runs replay the same delay sequence
        backoff = Backoff(base=cfg.shed_backoff_s, cap=cfg.shed_backoff_cap_s,
                          seed=cfg.seed * 100003 + gi)
        deadline = time.monotonic() + cfg.row_timeout_s
        body = json.dumps({"action": "submit", "prompt": prompt,
                           "max_new_tokens": mnt, "priority": cfg.priority,
                           "tenant": self.tenant}).encode()
        attempt = 0
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"batch row {gi} gave up after {cfg.row_timeout_s:g}s of "
                    "admission/submit retries")
            try:
                if admission is not None:
                    # the ONE admission path: best_effort sheds first under
                    # interactive pressure, and we back off instead of queue
                    admission.admit(cfg.priority, tenant=self.tenant)
                result, tag = handle.call_http_sync_tagged(
                    body, timeout=cfg.submit_timeout_s)
                rid = int(result["request_id"])
                break
            except AdmissionShedError as e:
                attempt += 1
                with self._lock:
                    self.shed_retries += 1
                time.sleep(max(float(e.retry_after_s or 0.0) * 0.1,
                               backoff.next_delay(attempt)))
            except (NoLiveReplicasError, ReplicaGoneError):
                # replicas mid-respawn (e.g. right after a borrow return)
                attempt += 1
                with self._lock:
                    self.submit_retries += 1
                time.sleep(backoff.next_delay(attempt))
            except RemoteError as e:
                if not e.cause_repr.startswith(("EngineOverloadedError",
                                                "EngineDrainingError")):
                    raise
                attempt += 1
                with self._lock:
                    self.submit_retries += 1
                time.sleep(backoff.next_delay(attempt))
        # journal with an EXPLICIT budget so the stream is replayable and
        # migratable — the batch lane gets preemption recovery for free
        journal.record_submit(cfg.route_prefix, tag, rid, prompt=prompt,
                              max_new_tokens=mnt, priority=cfg.priority,
                              deadline_ms=None, tenant=self.tenant)
        cursor = 0
        toks: List[int] = []
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"batch row {gi} stream stalled past {cfg.row_timeout_s:g}s")
            try:
                result, _ = journaled_poll(
                    journal, handle, cfg.route_prefix,
                    {"request_id": rid, "cursor": cursor}, tag,
                    timeout=cfg.submit_timeout_s)
            except (NoLiveReplicasError, ReplicaGoneError):
                # survivor mid-respawn while our pinned replica is gone —
                # the journal entry survives, so the next poll replays
                attempt += 1
                with self._lock:
                    self.submit_retries += 1
                time.sleep(backoff.next_delay(attempt))
                continue
            except RemoteError as e:
                # a pinned-replica death mid-stream replays through the
                # journal INSIDE journaled_poll; when the survivor's
                # queue is full (a returned borrow halved capacity under
                # surge) the replay submit overloads — back off and let
                # the journal retry, don't kill the epoch
                if not e.cause_repr.startswith(("EngineOverloadedError",
                                                "EngineDrainingError")):
                    raise
                attempt += 1
                with self._lock:
                    self.submit_retries += 1
                time.sleep(backoff.next_delay(attempt))
                continue
            new = list(result.get("tokens") or [])
            toks.extend(new)
            cursor += len(new)
            if result.get("done"):
                return toks
            if not new:
                time.sleep(cfg.poll_interval_s)

    # -- elastic chip borrowing ------------------------------------------
    def _maybe_borrow(self, ctl: Dict[str, Any]) -> None:
        """Between chunks (driver thread only): soak a replica when the
        route is in a trough, hand everything back the moment interactive
        depth climbs.  Window sizing rides the same gauges — wider while
        borrowing, halved under a surge we can't shed capacity for."""
        cfg = self.cfg
        admission = ctl.get("admission")
        if admission is None:
            return
        gauges = admission.gauges()
        depth = float(gauges.get("depth_per_replica") or 0.0)
        with self._lock:
            holding = len(self._borrowed)
            if holding:
                self._window_live = cfg.window * (1 + holding)
            elif depth >= cfg.borrow_depth_high:
                self._window_live = max(1, cfg.window // 2)
            else:
                self._window_live = cfg.window
        if holding and depth >= cfg.borrow_depth_high:
            # interactive is back: return the loan NOW, through the
            # drain path, before finishing the epoch on base capacity
            self._return_all_borrowed(ctl)
            return
        if not cfg.borrow or holding >= cfg.borrow_max_replicas:
            return
        if depth > cfg.borrow_depth_low:
            return
        autoscaler = ctl.get("autoscaler")
        if (autoscaler is not None and
                int(autoscaler.stats().get("idle_ticks") or 0)
                < cfg.borrow_idle_ticks):
            return
        if float(get_runtime().avail.get("chip", 0.0)) < 1.0:
            return  # no free chips: borrowing would steal, not soak
        handle = ctl["handle"]
        with handle._lock:
            before = {r._actor_id for r in handle._replicas}
        if not handle.scale_up(timeout=cfg.borrow_spawn_timeout_s):
            return
        with handle._lock:
            new = {r._actor_id for r in handle._replicas} - before
        with self._lock:
            self._borrowed.update(new)
            self.borrows += len(new)
            self._window_live = cfg.window * (1 + len(self._borrowed))
        w = _watch.current()
        if w is not None:
            for tag in new:
                w.note("batch.borrow", job=self.job_id, replica=tag)

    def _return_all_borrowed(self, ctl: Optional[Dict[str, Any]]) -> None:
        if ctl is None:
            return
        with self._lock:
            tags = list(self._borrowed)
            self._borrowed.clear()
            self._window_live = self.cfg.window
        if not tags:
            return
        from tpu_air.core import api as core_api

        handle = ctl["handle"]
        watcher = ctl.get("watcher")
        for tag in tags:
            # the loan raised the deployment's replica target by one;
            # lower it back or the restart controller respawns the
            # replica we are about to drain away
            handle.shrink_target()
            if watcher is not None:
                # flag FIRST so the watcher never mistakes this voluntary
                # return for a real preemption (no autoscaler backfill)
                watcher.mark_borrowed(tag)
            with handle._lock:
                replica = next((r for r in handle._replicas
                                if r._actor_id == tag), None)
            if replica is not None:
                try:
                    core_api.get(replica.handle.remote(
                        "borrow_return", (self.cfg.borrow_notice_s,), {}),
                        timeout=30.0)
                except Exception:  # noqa: BLE001 — a replica that died on loan is already returned
                    pass
            with self._lock:
                self.borrow_returns += 1
            w = _watch.current()
            if w is not None:
                w.note("batch.borrow_return", job=self.job_id, replica=tag)


# -- job registry (observability surface) ---------------------------------
# jobs stay registered after completion so /api/batch and the prometheus
# families can show terminal state; a re-run of the same job_id replaces
# its entry (latest incarnation wins)
_registry_lock = threading.Lock()
_registry: Dict[str, BatchJob] = {}


def register_job(job: BatchJob) -> None:
    with _registry_lock:
        _registry[job.job_id] = job


def get_job(job_id: str) -> Optional[BatchJob]:
    with _registry_lock:
        return _registry.get(str(job_id))


def jobs_stats() -> Dict[str, Dict[str, Any]]:
    """Snapshot of every registered job's :meth:`BatchJob.stats` — the
    payload behind ``/-/stats`` → ``batch``, the dashboard's
    ``/api/batch``, and the ``tpu_air_batch_*`` prometheus families."""
    with _registry_lock:
        jobs = list(_registry.values())
    return {j.job_id: j.stats() for j in jobs}


def clear_registry() -> None:
    """Test hook: forget completed jobs between cases."""
    with _registry_lock:
        _registry.clear()
