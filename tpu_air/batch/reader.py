"""Checkpointable sharded readers over :class:`tpu_air.data.Dataset`.

The batch lane's input layer follows the t5x/seqio determinism design
(PAPERS.md, arXiv:2203.17189): the input iterator is a pure function of
``(dataset blocks, seed, cursor)``, so a preempted job that journaled its
cursors resumes mid-epoch with the *byte-identical* remaining row stream
— no re-shuffle drift, no dropped or duplicated rows.

Three pieces:

* :func:`shard_plan` — deterministic assignment of dataset blocks to
  shards: a seeded permutation of the block list, greedily placed on the
  least-loaded shard (ties break to the lowest shard index).  Same
  ``(row counts, num_shards, seed)`` ⇒ same plan, on any process.
* :class:`ShardCursor` — one shard's resume point: how many rows of its
  stream have been consumed.  JSON-trivial, journaled by the batch job.
* :class:`ShardedReader` — iterates one shard's row stream from a
  cursor, yielding ``(global_row_index, row_dict)``.  The global index
  is the row's position in the WHOLE dataset (block offset + local
  index), so outputs keyed by it union losslessly across shards — the
  exactly-once invariant the chaos tests assert.

Blocks wholly behind the cursor are skipped without fetching them from
the object store, so resuming deep into an epoch costs reads only for
the first live block.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from tpu_air.data import block as B


def shard_plan(block_rows: Sequence[int], num_shards: int,
               seed: int) -> List[List[int]]:
    """Assign block indices to ``num_shards`` shards, deterministically.

    A seeded permutation decorrelates block order from ingest order (the
    seqio shuffle-then-shard idea at block granularity); greedy
    least-loaded placement keeps shard row totals balanced even when
    block sizes are skewed.  Pure: no global RNG state is touched."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    order = list(range(len(block_rows)))
    random.Random(int(seed)).shuffle(order)
    plans: List[List[int]] = [[] for _ in range(num_shards)]
    loads = [0] * num_shards
    for b in order:
        s = min(range(num_shards), key=lambda i: (loads[i], i))
        plans[s].append(b)
        loads[s] += int(block_rows[b])
    return plans


@dataclass
class ShardCursor:
    """One shard's resume point: ``rows_done`` rows of its deterministic
    stream are already consumed (and their outputs committed)."""

    shard: int
    rows_done: int = 0

    def to_dict(self) -> Dict[str, int]:
        return {"shard": int(self.shard), "rows_done": int(self.rows_done)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ShardCursor":
        return cls(shard=int(d["shard"]), rows_done=int(d["rows_done"]))


class ShardedReader:
    """Deterministic row stream for one shard of a dataset.

    Construction needs the per-block row counts; pass ``counts`` when the
    caller already paid for them (BatchJob computes them once for every
    shard) or let the reader ask the dataset.  The reader never mutates
    the dataset and holds no open state between :meth:`rows` calls — it
    is safe to rebuild from scratch on resume, which is the point."""

    def __init__(self, dataset, shard: int, num_shards: int, seed: int, *,
                 counts: Optional[Sequence[int]] = None):
        if not 0 <= shard < num_shards:
            raise ValueError(
                f"shard {shard} out of range for num_shards={num_shards}")
        self._refs = dataset.get_internal_block_refs()
        self._counts = ([int(c) for c in counts] if counts is not None
                        else [int(c) for c in dataset._row_counts()])
        if len(self._counts) != len(self._refs):
            raise ValueError(
                f"{len(self._counts)} row counts for {len(self._refs)} blocks")
        self.shard = int(shard)
        self.num_shards = int(num_shards)
        self.seed = int(seed)
        self.plan = shard_plan(self._counts, num_shards, seed)[self.shard]
        # global row index base per block: block b's row i is row
        # offsets[b] + i of the whole dataset — unique across shards
        self._offsets = [0] * len(self._counts)
        acc = 0
        for i, c in enumerate(self._counts):
            self._offsets[i] = acc
            acc += c

    def total_rows(self) -> int:
        return sum(self._counts[b] for b in self.plan)

    def rows(self, start: int = 0) -> Iterator[Tuple[int, Dict[str, Any]]]:
        """Yield ``(global_row_index, row_dict)`` from position ``start``
        of this shard's stream (``start`` = a journaled cursor's
        ``rows_done``).  Blocks wholly behind the cursor are skipped
        without an object-store fetch."""
        from tpu_air.core.api import get

        if start < 0:
            raise ValueError(f"start must be >= 0, got {start}")
        seen = 0
        for b in self.plan:
            n = self._counts[b]
            if start >= seen + n:
                seen += n
                continue  # fully consumed: skip without fetching
            df = B.block_to_pandas(get(self._refs[b]))
            local = max(0, start - seen)
            for i in range(local, n):
                yield self._offsets[b] + i, df.iloc[i].to_dict()
            seen += n

    def describe(self) -> Dict[str, Any]:
        return {
            "shard": self.shard,
            "num_shards": self.num_shards,
            "seed": self.seed,
            "blocks": list(self.plan),
            "total_rows": self.total_rows(),
        }
