"""airbatch — the elastic offline batch-inference lane (docs/SERVING.md
"Batch lane").  Public surface:

* :class:`BatchJob` / :class:`BatchJobConfig` — run a resumable epoch of
  a dataset through a deployed serve route at ``best_effort`` priority.
* :mod:`tpu_air.batch.reader` — deterministic sharded readers
  (:func:`shard_plan`, :class:`ShardedReader`, :class:`ShardCursor`).
* :func:`jobs_stats` — the observability snapshot behind ``/-/stats`` →
  ``batch``, the dashboard's ``/api/batch``, and ``tpu_air_batch_*``.
"""

from tpu_air.batch.job import (
    BatchJob,
    BatchJobConfig,
    BatchJobKilled,
    clear_registry,
    get_job,
    jobs_stats,
    register_job,
)
from tpu_air.batch.reader import ShardCursor, ShardedReader, shard_plan

__all__ = [
    "BatchJob",
    "BatchJobConfig",
    "BatchJobKilled",
    "ShardCursor",
    "ShardedReader",
    "shard_plan",
    "clear_registry",
    "get_job",
    "jobs_stats",
    "register_job",
]
