"""Predictor — single-process inference over a Checkpoint.

Parity surface (SURVEY.md §1-L5): ``ray.train.predictor.Predictor`` with
user-overridable ``_predict_numpy`` (reference predictor.py:74) /
``_predict_pandas`` (Scaling_batch_inference.ipynb:cc-73) and classmethod
``from_checkpoint``.  The key contract: ``predict()`` first applies the
checkpoint's *fitted preprocessor* to the incoming batch ("we get already
tokenized text here because we have the tokenizer as an AIR preprocessor",
reference predictor.py:93), then dispatches to whichever ``_predict_*`` the
subclass implements, converting the batch format as needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Type, Union

import numpy as np
import pandas as pd

DataBatchType = Union[pd.DataFrame, np.ndarray, Dict[str, np.ndarray]]


def _batch_to_pandas(data: DataBatchType) -> pd.DataFrame:
    if isinstance(data, pd.DataFrame):
        return data
    if isinstance(data, dict):
        return pd.DataFrame({k: list(v) for k, v in data.items()})
    if isinstance(data, np.ndarray):
        if data.ndim == 1:
            return pd.DataFrame({"__value__": data})
        return pd.DataFrame({"__value__": list(data)})
    raise TypeError(f"unsupported batch type {type(data)}")


def _batch_to_numpy(data: DataBatchType) -> Dict[str, np.ndarray]:
    if isinstance(data, dict):
        return {k: np.asarray(v) for k, v in data.items()}
    if isinstance(data, np.ndarray):
        return {"__value__": data}
    if isinstance(data, pd.DataFrame):
        out = {}
        for col in data.columns:
            vals = data[col].to_numpy()
            # column of fixed-length sequences (e.g. input_ids lists) → 2-D
            if len(vals) and isinstance(vals[0], (list, tuple, np.ndarray)):
                out[col] = np.stack([np.asarray(v) for v in vals])
            else:
                out[col] = vals
        return out
    raise TypeError(f"unsupported batch type {type(data)}")


class PredictorNotSerializableException(RuntimeError):
    pass


class Predictor:
    """Base class.  Subclasses implement ``from_checkpoint`` and one of
    ``_predict_numpy`` / ``_predict_pandas``."""

    def __init__(self, preprocessor=None):
        self._preprocessor = preprocessor

    @classmethod
    def from_checkpoint(cls, checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    # -- preprocessor plumbing ---------------------------------------------
    def get_preprocessor(self):
        return self._preprocessor

    def set_preprocessor(self, preprocessor) -> None:
        self._preprocessor = preprocessor

    # -- the public entry point --------------------------------------------
    def predict(self, data: DataBatchType, **kwargs) -> DataBatchType:
        if self._preprocessor is not None:
            data = self._preprocessor.transform_batch(data)
        has_pandas = type(self)._predict_pandas is not Predictor._predict_pandas
        has_numpy = type(self)._predict_numpy is not Predictor._predict_numpy
        if has_pandas:
            return self._predict_pandas(_batch_to_pandas(data), **kwargs)
        if has_numpy:
            return self._predict_numpy(_batch_to_numpy(data), **kwargs)
        raise NotImplementedError(
            f"{type(self).__name__} implements neither _predict_pandas nor _predict_numpy"
        )

    # -- subclass surface ---------------------------------------------------
    def _predict_pandas(self, data: pd.DataFrame, **kwargs) -> pd.DataFrame:
        raise NotImplementedError

    def _predict_numpy(self, data: Dict[str, np.ndarray], **kwargs) -> DataBatchType:
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(preprocessor={self._preprocessor!r})"
