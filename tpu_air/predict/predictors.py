"""Built-in predictors.

* ``T5GenerativePredictor`` — the generative-inference predictor of the
  primary workload (the ``HuggingFaceModelPredictor`` analog, reference
  predictor.py:14-106): pulls model/tokenizer/preprocessor from a Checkpoint,
  runs the jit-compiled autoregressive ``generate`` on device, decodes to a
  ``generated_output`` column.  TPU-first: inputs go through a single
  host→HBM transfer, decode runs as a compiled ``lax.scan`` with a KV cache
  (no per-token Python), and dtype morphing (bf16) happens at param load.
* ``JaxPredictor`` — generic forward-pass predictor for any Flax model
  (``TorchPredictor`` analog, Scaling_batch_inference.ipynb:cc-71).
* ``GBDTPredictor`` — the ``XGBoostPredictor`` analog
  (Introduction_to_Ray_AI_Runtime.ipynb:cc-57) over the host-side sklearn
  gradient-boosting model produced by ``GBDTTrainer``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pandas as pd

from tpu_air.predict.predictor import Predictor


class T5GenerativePredictor(Predictor):
    """Batched text generation from a T5 checkpoint (predictor.py:14-106 analog)."""

    def __init__(self, model, params, tokenizer=None, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model
        self.params = params
        self.tokenizer = tokenizer

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint,
        *,
        model_cls=None,
        tokenizer=None,
        dtype: Optional[str] = None,
        sharding=None,
        use_tpu: bool = True,
        **_: Any,
    ) -> "T5GenerativePredictor":
        """Build from a Checkpoint.  ``dtype="bfloat16"`` is the TPU analog of
        the reference's fp16 load (Model_finetuning…ipynb:cc-64); ``sharding``
        is the ``device_map="auto"`` analog — an explicit jax.sharding spec."""
        model, params = checkpoint.get_model(model_cls=model_cls, dtype=dtype, sharding=sharding)
        if dtype:
            import jax
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.dtype(dtype)) if hasattr(x, "astype") else x, params
            )
        tok = tokenizer
        if tok is None or isinstance(tok, type):
            loaded = checkpoint.get_tokenizer(tok if isinstance(tok, type) else None)
            tok = loaded
        return cls(model, params, tok, checkpoint.get_preprocessor())

    def _predict_numpy(
        self,
        data: Dict[str, np.ndarray],
        feature_columns: Optional[List[str]] = None,
        max_new_tokens: int = 128,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        **_: Any,
    ) -> pd.DataFrame:
        from tpu_air.models.t5.generate import generate

        if feature_columns:
            data = {k: v for k, v in data.items() if k in feature_columns}
        input_ids = np.asarray(data["input_ids"])
        mask = data.get("attention_mask")
        seqs = generate(
            self.model,
            self.params,
            input_ids,
            attention_mask=mask,
            max_new_tokens=max_new_tokens,
            do_sample=do_sample,
            temperature=temperature,
            top_k=top_k,
        )
        seqs = np.asarray(seqs)
        if self.tokenizer is not None:
            texts = self.tokenizer.batch_decode(seqs, skip_special_tokens=True)
        else:
            texts = [" ".join(map(str, row)) for row in seqs]
        return pd.DataFrame({"generated_output": texts})


class LMGenerativePredictor(Predictor):
    """Batched text generation from a causal-LM checkpoint (LMTrainer
    output) — the decoder-only sibling of :class:`T5GenerativePredictor`,
    so LM checkpoints compose with BatchPredictor / serve unchanged."""

    def __init__(self, model, params, tokenizer=None, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model
        self.params = params
        self.tokenizer = tokenizer

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint,
        *,
        tokenizer=None,
        dtype: Optional[str] = None,
        **_: Any,
    ) -> "LMGenerativePredictor":
        model, params = checkpoint.get_model(dtype=dtype)
        if dtype:
            import jax
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.dtype(dtype)) if hasattr(x, "astype") else x,
                params,
            )
        tok = tokenizer
        if tok is None or isinstance(tok, type):
            try:
                tok = checkpoint.get_tokenizer(tok if isinstance(tok, type) else None)
            except FileNotFoundError:
                # token-id corpora (LMTrainer's input) train without a
                # tokenizer; generation then returns id strings
                tok = None
        return cls(model, params, tok, checkpoint.get_preprocessor())

    def _predict_numpy(
        self,
        data: Dict[str, np.ndarray],
        feature_columns: Optional[List[str]] = None,
        max_new_tokens: int = 64,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        seed: int = 0,
        **_: Any,
    ) -> pd.DataFrame:
        import jax

        from tpu_air.models.lm import generate

        if feature_columns:
            data = {k: v for k, v in data.items() if k in feature_columns}
        try:
            input_ids = np.asarray(
                np.stack([np.asarray(r) for r in data["input_ids"]])
            )
        except ValueError as e:
            raise ValueError(
                "LMGenerativePredictor needs EQUAL-LENGTH prompts per batch "
                "(the decode cache is positional): bucket rows by length "
                f"before predict ({e})"
            ) from None
        if (input_ids == self.model.config.pad_token_id).any():
            # padded prompts would feed pad tokens as real context and
            # sample the first token from a pad position's logits
            raise ValueError(
                "LMGenerativePredictor prompts must be un-padded; strip pad "
                "tokens and bucket rows to equal lengths"
            )
        # vary sampling noise across batches deterministically: fold a
        # per-predictor call counter into the seed
        self._calls = getattr(self, "_calls", 0) + 1
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), self._calls)
        toks = np.asarray(generate(
            self.model, self.params, input_ids,
            max_new_tokens=max_new_tokens, do_sample=do_sample,
            temperature=temperature, top_k=top_k,
            eos_token_id=getattr(self.model.config, "eos_token_id", None),
            rng=rng,
        ))
        if self.tokenizer is not None:
            texts = self.tokenizer.batch_decode(toks, skip_special_tokens=True)
        else:
            texts = [" ".join(map(str, row)) for row in toks]
        return pd.DataFrame({"generated_output": texts})


class JaxPredictor(Predictor):
    """Generic forward-pass predictor: ``apply_fn(params, **features)``."""

    def __init__(self, apply_fn: Callable, params, preprocessor=None, output_column: str = "predictions"):
        super().__init__(preprocessor)
        self.apply_fn = apply_fn
        self.params = params
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint, *, apply_fn: Callable, dtype=None, **_: Any) -> "JaxPredictor":
        params = checkpoint.get_params(dtype=dtype)
        return cls(apply_fn, params, checkpoint.get_preprocessor())

    def _predict_numpy(self, data: Dict[str, np.ndarray], **kwargs) -> pd.DataFrame:
        out = self.apply_fn(self.params, **data, **kwargs)
        out = np.asarray(out)
        if out.ndim > 1 and out.shape[-1] == 1:
            out = out[..., 0]
        col = list(out) if out.ndim > 1 else out
        return pd.DataFrame({self.output_column: col})


class SemanticSegmentationPredictor(Predictor):
    """SegFormer batch-inference predictor (the reference's custom
    ``SemanticSegmentationPredictor`` analog,
    Scaling_batch_inference.ipynb:cc-73): feature-extract → jit forward →
    ``post_process_semantic_segmentation`` → per-image class maps.

    TPU-first: the forward pass is jit-compiled once per batch shape and runs
    NHWC on device; pre/post-processing stays host-side.
    """

    def __init__(self, model, params, batch_stats=None, feature_extractor=None,
                 preprocessor=None, output_column: str = "predicted_mask"):
        super().__init__(preprocessor)
        self.model = model
        self.params = params
        self.batch_stats = batch_stats or {}
        self.feature_extractor = feature_extractor
        self.output_column = output_column
        self._jit_forward = None

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint,
        *,
        model_cls=None,
        feature_extractor=None,
        dtype: Optional[str] = None,
        **_: Any,
    ) -> "SemanticSegmentationPredictor":
        model, params = checkpoint.get_model(model_cls=model_cls, dtype=dtype)
        # _load_extras returns None for missing files; real load errors
        # (corrupt pickle etc.) must propagate — silently dropping
        # batch_stats would surface later as a confusing flax
        # missing-collection error inside the decode head's BatchNorm.
        extras = checkpoint._load_extras() or {}
        if feature_extractor is None:
            feature_extractor = extras.get("feature_extractor")
        if feature_extractor is None:
            from tpu_air.models.segformer import SegformerImageProcessor

            feature_extractor = SegformerImageProcessor()
        # NB: deliberately does NOT attach the checkpoint's train-time
        # preprocessor — the reference's segmentation predictor consumes raw
        # images and applies its feature extractor inside _predict_pandas
        # (Scaling_batch_inference.ipynb:cc-73); the fitted-preprocessor
        # auto-apply contract belongs to the tabular/text predictors.
        return cls(
            model,
            params,
            batch_stats=extras.get("batch_stats"),
            feature_extractor=feature_extractor,
        )

    def _forward(self, px):
        import jax
        import jax.numpy as jnp

        if self._jit_forward is None:
            variables = {"params": self.params}
            if self.batch_stats:
                variables["batch_stats"] = self.batch_stats
            # airlint: disable=JX003 — guarded by the None check above: the
            # lambda is created and jitted once, then memoized on self
            self._jit_forward = jax.jit(
                lambda x: self.model.apply(variables, x)
            )
        return self._jit_forward(jnp.asarray(px))

    def _predict_pandas(self, data: pd.DataFrame, **_: Any) -> pd.DataFrame:
        from tpu_air.models.segformer.image_processor import (
            _to_numpy_image,
            collate_pixel_batch,
        )

        if "pixel_values" in data.columns:
            px = collate_pixel_batch(data["pixel_values"])
            sizes = [tuple(px.shape[1:3])] * len(px)
        else:
            col = "image" if "image" in data.columns else data.columns[0]
            # normalize layout first — raw CHW arrays would otherwise yield
            # (channels, height) target sizes
            images = [_to_numpy_image(im) for im in data[col]]
            sizes = [im.shape[:2] for im in images]
            px = self.feature_extractor(images)["pixel_values"]
        logits = np.asarray(self._forward(px), np.float32)
        maps = self.feature_extractor.post_process_semantic_segmentation(
            logits, target_sizes=sizes
        )
        return pd.DataFrame({self.output_column: [m for m in maps]})


class GBDTPredictor(Predictor):
    """XGBoostPredictor analog: host-side GBDT scoring (Introduction…ipynb:cc-57)."""

    def __init__(self, model, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model

    @classmethod
    def from_checkpoint(cls, checkpoint, **_: Any) -> "GBDTPredictor":
        model = checkpoint.get_model()
        if isinstance(model, tuple):  # (flax_model, params) — wrong checkpoint kind
            raise TypeError("checkpoint does not contain a GBDT/sklearn model")
        return cls(model, checkpoint.get_preprocessor())

    def _predict_pandas(self, data: pd.DataFrame, **_: Any) -> pd.DataFrame:
        X = data.to_numpy(dtype=np.float32)
        if hasattr(self.model, "predict_proba"):
            preds = self.model.predict_proba(X)[:, 1]
        else:
            preds = self.model.predict(X)
        return pd.DataFrame({"predictions": preds})


class SklearnPredictor(GBDTPredictor):
    """Alias family for generic sklearn estimators stored in checkpoints."""


#: Drop-in alias matching the reference import name (Introduction…ipynb:cc-57)
XGBoostPredictor = GBDTPredictor
