"""Built-in predictors.

* ``T5GenerativePredictor`` — the generative-inference predictor of the
  primary workload (the ``HuggingFaceModelPredictor`` analog, reference
  predictor.py:14-106): pulls model/tokenizer/preprocessor from a Checkpoint,
  runs the jit-compiled autoregressive ``generate`` on device, decodes to a
  ``generated_output`` column.  TPU-first: inputs go through a single
  host→HBM transfer, decode runs as a compiled ``lax.scan`` with a KV cache
  (no per-token Python), and dtype morphing (bf16) happens at param load.
* ``JaxPredictor`` — generic forward-pass predictor for any Flax model
  (``TorchPredictor`` analog, Scaling_batch_inference.ipynb:cc-71).
* ``GBDTPredictor`` — the ``XGBoostPredictor`` analog
  (Introduction_to_Ray_AI_Runtime.ipynb:cc-57) over the host-side sklearn
  gradient-boosting model produced by ``GBDTTrainer``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pandas as pd

from tpu_air.predict.predictor import Predictor


class T5GenerativePredictor(Predictor):
    """Batched text generation from a T5 checkpoint (predictor.py:14-106 analog)."""

    def __init__(self, model, params, tokenizer=None, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model
        self.params = params
        self.tokenizer = tokenizer

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint,
        *,
        model_cls=None,
        tokenizer=None,
        dtype: Optional[str] = None,
        sharding=None,
        use_tpu: bool = True,
        **_: Any,
    ) -> "T5GenerativePredictor":
        """Build from a Checkpoint.  ``dtype="bfloat16"`` is the TPU analog of
        the reference's fp16 load (Model_finetuning…ipynb:cc-64); ``sharding``
        is the ``device_map="auto"`` analog — an explicit jax.sharding spec."""
        model, params = checkpoint.get_model(model_cls=model_cls, dtype=dtype, sharding=sharding)
        if dtype:
            import jax
            import jax.numpy as jnp

            params = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.dtype(dtype)) if hasattr(x, "astype") else x, params
            )
        tok = tokenizer
        if tok is None or isinstance(tok, type):
            loaded = checkpoint.get_tokenizer(tok if isinstance(tok, type) else None)
            tok = loaded
        return cls(model, params, tok, checkpoint.get_preprocessor())

    def _predict_numpy(
        self,
        data: Dict[str, np.ndarray],
        feature_columns: Optional[List[str]] = None,
        max_new_tokens: int = 128,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_k: int = 0,
        **_: Any,
    ) -> pd.DataFrame:
        from tpu_air.models.t5.generate import generate

        if feature_columns:
            data = {k: v for k, v in data.items() if k in feature_columns}
        input_ids = np.asarray(data["input_ids"])
        mask = data.get("attention_mask")
        seqs = generate(
            self.model,
            self.params,
            input_ids,
            attention_mask=mask,
            max_new_tokens=max_new_tokens,
            do_sample=do_sample,
            temperature=temperature,
            top_k=top_k,
        )
        seqs = np.asarray(seqs)
        if self.tokenizer is not None:
            texts = self.tokenizer.batch_decode(seqs, skip_special_tokens=True)
        else:
            texts = [" ".join(map(str, row)) for row in seqs]
        return pd.DataFrame({"generated_output": texts})


class JaxPredictor(Predictor):
    """Generic forward-pass predictor: ``apply_fn(params, **features)``."""

    def __init__(self, apply_fn: Callable, params, preprocessor=None, output_column: str = "predictions"):
        super().__init__(preprocessor)
        self.apply_fn = apply_fn
        self.params = params
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint, *, apply_fn: Callable, dtype=None, **_: Any) -> "JaxPredictor":
        params = checkpoint.get_params(dtype=dtype)
        return cls(apply_fn, params, checkpoint.get_preprocessor())

    def _predict_numpy(self, data: Dict[str, np.ndarray], **kwargs) -> pd.DataFrame:
        out = self.apply_fn(self.params, **data, **kwargs)
        out = np.asarray(out)
        if out.ndim > 1 and out.shape[-1] == 1:
            out = out[..., 0]
        col = list(out) if out.ndim > 1 else out
        return pd.DataFrame({self.output_column: col})


class GBDTPredictor(Predictor):
    """XGBoostPredictor analog: host-side GBDT scoring (Introduction…ipynb:cc-57)."""

    def __init__(self, model, preprocessor=None):
        super().__init__(preprocessor)
        self.model = model

    @classmethod
    def from_checkpoint(cls, checkpoint, **_: Any) -> "GBDTPredictor":
        model = checkpoint.get_model()
        if isinstance(model, tuple):  # (flax_model, params) — wrong checkpoint kind
            raise TypeError("checkpoint does not contain a GBDT/sklearn model")
        return cls(model, checkpoint.get_preprocessor())

    def _predict_pandas(self, data: pd.DataFrame, **_: Any) -> pd.DataFrame:
        X = data.to_numpy(dtype=np.float32)
        if hasattr(self.model, "predict_proba"):
            preds = self.model.predict_proba(X)[:, 1]
        else:
            preds = self.model.predict(X)
        return pd.DataFrame({"predictions": preds})


class SklearnPredictor(GBDTPredictor):
    """Alias family for generic sklearn estimators stored in checkpoints."""
