"""tpu_air.predict — batch/offline inference layer (SURVEY.md §1-L5).

``Predictor`` (+ built-ins) and ``BatchPredictor`` over the Dataset/actor-pool
substrate.  See reference call stack §3.3.
"""

from tpu_air.predict.batch_predictor import BatchPredictor
from tpu_air.predict.predictor import Predictor
from tpu_air.predict.predictors import (
    GBDTPredictor,
    JaxPredictor,
    SemanticSegmentationPredictor,
    SklearnPredictor,
    XGBoostPredictor,
    LMGenerativePredictor,
    T5GenerativePredictor,
)

__all__ = [
    "BatchPredictor",
    "Predictor",
    "GBDTPredictor",
    "JaxPredictor",
    "SemanticSegmentationPredictor",
    "SklearnPredictor",
    "XGBoostPredictor",
    "LMGenerativePredictor",
    "T5GenerativePredictor",
]
