"""BatchPredictor — distributed batch inference over a Dataset.

Parity surface (SURVEY.md §3.3): ``BatchPredictor.from_checkpoint(checkpoint,
predictor_cls, **predictor_kwargs)`` and ``.predict(dataset,
num_chips_per_worker=…, batch_size=…, **predict_kwargs)`` which fans blocks
across an internally-managed actor pool (Model_finetuning…ipynb:cc-64,67;
Scaling_batch_inference.ipynb:cc-76).

TPU-native shape: each scoring actor constructs the predictor once (params
land in HBM once, the generate fn compiles once) and then maps over Arrow
blocks pulled from the host object store — the reference's "autoscaling actor
pool" (Scaling_batch_inference.ipynb:cc-4) becomes a fixed-size pool of
chip-leasing actors sized by ``min/max_scoring_workers``.

Boundary vs :class:`tpu_air.batch.BatchJob` (the airbatch serve lane):
this module OWNS its compute — a dedicated actor pool leases its own
chips, maps whole blocks, and releases everything when ``predict``
returns; throughput is bounded by the pool and nothing is shared with
serving.  ``BatchJob`` instead rides an already-deployed serve route at
``best_effort`` priority: it owns no chips (it borrows idle serve
capacity and is preempted back), goes through the SAME admission
controller as interactive traffic, and is checkpoint-resumable
row-by-row.  Rule of thumb: dedicated offline cluster time → this
module; trickle millions of rows through a live serving fleet without
touching its SLO → ``tpu_air.batch``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Type

import pandas as pd

from tpu_air.data.dataset import ActorPoolStrategy, Dataset
from tpu_air.predict.predictor import Predictor


class _ScoringWrapper:
    """Callable class instantiated once per pool actor; holds the predictor."""

    def __init__(self, checkpoint_payload, predictor_cls, predictor_kwargs,
                 feature_columns, keep_columns, predict_kwargs):
        from tpu_air.train.checkpoint import Checkpoint

        if isinstance(checkpoint_payload, str):
            ckpt = Checkpoint.from_directory(checkpoint_payload)
        elif isinstance(checkpoint_payload, Checkpoint):
            ckpt = checkpoint_payload
        else:
            ckpt = Checkpoint.from_dict(checkpoint_payload)
        self.predictor: Predictor = predictor_cls.from_checkpoint(ckpt, **predictor_kwargs)
        self.feature_columns = feature_columns
        self.keep_columns = keep_columns
        self.predict_kwargs = predict_kwargs

    def __call__(self, batch: pd.DataFrame) -> pd.DataFrame:
        inputs = batch
        if self.feature_columns:
            cols = [c for c in self.feature_columns if c in batch.columns]
            inputs = batch[cols] if cols else batch
        kwargs = dict(self.predict_kwargs)
        # predictors that filter internally get the column list too
        if self.feature_columns and type(self.predictor)._predict_numpy is not Predictor._predict_numpy:
            kwargs.setdefault("feature_columns", self.feature_columns)
        out = self.predictor.predict(inputs, **kwargs)
        if not isinstance(out, pd.DataFrame):
            out = pd.DataFrame(out)
        if self.keep_columns:
            out = out.reset_index(drop=True)
            for c in self.keep_columns:
                if c in batch.columns:
                    out[c] = batch[c].reset_index(drop=True)
        return out


class BatchPredictor:
    def __init__(self, checkpoint, predictor_cls: Type[Predictor], **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint, predictor_cls: Type[Predictor],
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def get_preprocessor(self):
        return self._checkpoint.get_preprocessor()

    def _checkpoint_payload(self):
        """Ship directory checkpoints by path (cheap; workers re-open), dict
        checkpoints by value."""
        path = self._checkpoint.path
        return path if path else self._checkpoint.to_dict()

    def predict(
        self,
        data: Dataset,
        *,
        feature_columns: Optional[List[str]] = None,
        keep_columns: Optional[List[str]] = None,
        batch_size: int = 4096,
        min_scoring_workers: int = 1,
        max_scoring_workers: Optional[int] = None,
        num_chips_per_worker: float = 0,
        num_gpus_per_worker: float = 0,  # reference-API alias → chips
        separate_preprocessor: bool = False,
        **predict_kwargs: Any,
    ) -> Dataset:
        chips = num_chips_per_worker or num_gpus_per_worker
        strategy = ActorPoolStrategy(
            min_size=min_scoring_workers,
            max_size=max_scoring_workers or max(data.num_blocks(), 1),
            num_chips=chips,
        )
        return data.map_batches(
            _ScoringWrapper,
            batch_size=batch_size,
            batch_format="pandas",
            compute=strategy,
            fn_constructor_args=(
                self._checkpoint_payload(),
                self._predictor_cls,
                self._predictor_kwargs,
                feature_columns,
                keep_columns,
                predict_kwargs,
            ),
        )

    def __repr__(self):
        return (f"BatchPredictor(checkpoint={self._checkpoint!r}, "
                f"predictor_cls={self._predictor_cls.__name__})")
