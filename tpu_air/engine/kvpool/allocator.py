"""Refcounted page allocator over the engine's device-resident KV pool.

The device side is a set of per-layer arrays ``[num_pages, page_len, h*d]``
owned (and donated through every jitted step) by the engine; this class is
the host-side authority over which of those ``num_pages`` rows are free,
and how many holders each allocated row has.  Holders are block-table
entries of live slots plus (at most) one residency reference from the
:class:`~tpu_air.engine.kvpool.prefix.PrefixCache`.

Page 0 is the NULL page: permanently pinned, never handed out.  Block
table entries of free slots and not-yet-reached positions all point at it,
so the fixed-shape decode step always has a legal (masked, don't-care)
gather/scatter target without per-step host fixups.
"""

from __future__ import annotations

from typing import List

from tpu_air.core.runtime import TpuAirError

NULL_PAGE = 0


class KVPoolOOMError(TpuAirError):
    """No free page in the KV pool.  The engine never lets this escape to
    callers — admission capacity-checks (with prefix-cache eviction
    headroom) before allocating — so reaching it means an accounting bug
    or direct allocator misuse."""


class BlockAllocator:
    """Free-list + refcounts over ``num_pages`` physical KV pages."""

    def __init__(self, num_pages: int, page_len: int):
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is the null page), "
                f"got {num_pages}"
            )
        if page_len < 1:
            raise ValueError(f"page_len must be >= 1, got {page_len}")
        self.num_pages = num_pages
        self.page_len = page_len
        self._ref: List[int] = [0] * num_pages
        self._ref[NULL_PAGE] = 1  # pinned forever
        # pop() takes from the end: keep descending so alloc hands out the
        # lowest free id first (deterministic page placement, mirroring the
        # slot manager's lowest-row-first discipline)
        self._free: List[int] = list(range(1, num_pages))[::-1]

    # -- capacity ------------------------------------------------------------
    def free_count(self) -> int:
        return len(self._free)

    def used_count(self) -> int:
        """Allocated pages, excluding the pinned null page."""
        return self.num_pages - 1 - len(self._free)

    # -- lifecycle -----------------------------------------------------------
    def alloc(self) -> int:
        """Hand out a free page with refcount 1."""
        if not self._free:
            raise KVPoolOOMError(
                f"KV pool exhausted ({self.num_pages - 1} pages, 0 free)"
            )
        page = self._free.pop()
        assert self._ref[page] == 0, "free-list page with live refs"
        self._ref[page] = 1
        return page

    def incref(self, page: int) -> None:
        if not 0 < page < self.num_pages:
            raise ValueError(f"bad page id {page}")
        if self._ref[page] == 0:
            raise ValueError(f"incref on free page {page}")
        self._ref[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one reference; returns True when the page went back to the
        free list.  No device-side zeroing — stale bytes in a reused page
        are masked until overwritten (the slab engine's r5 discipline)."""
        if not 0 < page < self.num_pages:
            raise ValueError(f"bad page id {page}")
        if self._ref[page] <= 0:
            raise ValueError(f"decref on free page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            # keep descending so the next alloc still hands out lowest-first
            self._free.append(page)
            self._free.sort(reverse=True)
            return True
        return False

    def refcount(self, page: int) -> int:
        return self._ref[page]
