"""PagedKVPool — host-side orchestration of block tables over the pool.

One instance per paged engine.  Owns the :class:`BlockAllocator`, the
optional :class:`PrefixCache`, and the authoritative block table
``[num_slots, pages_per_slot]`` (int32 page ids; NULL_PAGE marks entries
not yet reached — the fixed-shape decode step masks them).  The device
arrays themselves live in the engine's donated cache; everything here is
numpy/host bookkeeping decided BETWEEN device steps.

Admission policy (worst-case reservation): a request's pages — uncovered
prompt chunks plus its whole decode budget — are allocated up front, so a
request that admits can never hit OOM mid-flight; there is no preemption
path to need.  Prefix-shared pages are referenced, not copied, so a hit
admits with only the uncovered suffix's pages.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# chunk-content hashes remembered for reprefill detection (bounded: the
# set answers "was this page's content ever resident?", not residency)
_SEEN_CHUNK_CAP = 4096

import numpy as np

from .allocator import NULL_PAGE, BlockAllocator
from .prefix import PrefixCache


@dataclass
class AdmitPlan:
    """What is left to compute for an admitted request.

    ``chunk_starts`` — page-aligned positions whose chunk still needs a
    prefill pass, in order.  For a fully-covered prompt this is just the
    final chunk (its pass only produces the first token's logits), run
    with ``null_target=True``: the chunk K/V is written to the null page —
    a scratch target — because every real page is shared; the gather
    inside the same jitted call reads the freshly written null page, so
    the logits are exact while the shared pages stay untouched.
    """

    prompt_len: int
    budget: int
    chunk_starts: List[int] = field(default_factory=list)
    null_target: bool = False
    prefix_tokens: int = 0       # tokens covered by the prefix cache
    shared_tail: bool = False    # tail page shared -> CoW before 1st append
    chunks_done: int = 0
    # tokens in chunk_starts whose content the prefix cache HELD at some
    # point and has since evicted: prefill work the machine repeats (the
    # perf ledger's "reprefill_cache_miss" goodput category)
    reprefill_tokens: int = 0

    @property
    def done(self) -> bool:
        return self.chunks_done >= len(self.chunk_starts)

    @property
    def chunks_left(self) -> int:
        return len(self.chunk_starts) - self.chunks_done

    @property
    def next_start(self) -> int:
        return self.chunk_starts[self.chunks_done]


class PagedKVPool:
    """Block tables + refcounts + prefix residency for one engine."""

    def __init__(self, num_pages: int, page_len: int, num_slots: int,
                 pages_per_slot: int, prefix_cache: bool = True):
        self.page_len = page_len
        self.pages_per_slot = pages_per_slot
        self.allocator = BlockAllocator(num_pages, page_len)
        self.prefix: Optional[PrefixCache] = (
            PrefixCache(self.allocator, page_len) if prefix_cache else None
        )
        self.block_table = np.zeros((num_slots, pages_per_slot), np.int32)
        # pages each slot holds a reference on (table entries + CoW reserve)
        self._held: List[List[int]] = [[] for _ in range(num_slots)]
        self._cow_reserve: List[Optional[int]] = [None] * num_slots
        self._plans: List[Optional[AdmitPlan]] = [None] * num_slots
        self.cow_copies = 0
        # LRU set of page-content hashes ever published to the prefix cache
        # (register); admit consults it to flag re-prefilled chunks
        self._seen_chunks: "OrderedDict[int, None]" = OrderedDict()
        self.reprefill_tokens = 0

    # -- capacity ------------------------------------------------------------
    def _total_pages(self, prompt_len: int, budget: int) -> int:
        # last written position: prompt end (prefill) plus budget-1 decode
        # scatters (the final emitted token is computed, never written)
        last_write = prompt_len + budget - 2 if budget > 1 else prompt_len - 1
        return last_write // self.page_len + 1

    def worst_case_pages(self, prompt_len: int, budget: int) -> int:
        """Pages the request needs with ZERO prefix sharing — the engine's
        admission reservation (a prior admit in the same round can both
        evict a probe-time match and pin previously evictable pages, so
        the no-sharing bound is exactly what one round can consume)."""
        return self._total_pages(prompt_len, budget)

    def pages_needed(self, prompt, budget: int) -> int:
        """Private pages a request would allocate NOW (read-only probe —
        no LRU/stat side effects)."""
        total = self._total_pages(len(prompt), budget)
        covered = 0
        if self.prefix is not None:
            covered = len(self.prefix.match(prompt, touch=False).pages)
        return max(0, total - covered)

    def capacity(self) -> int:
        """Pages obtainable right now: free + immediately evictable."""
        cap = self.allocator.free_count()
        if self.prefix is not None:
            cap += self.prefix.evictable_count()
        return cap

    # -- admission -----------------------------------------------------------
    def admit(self, slot: int, prompt, budget: int,
              share: bool = True) -> AdmitPlan:
        """Reserve every page the request can touch, share what the prefix
        cache covers, and return the chunk work list.

        ``share=False`` skips prefix matching and allocates every page
        fresh — the disaggregated handoff path (engine/dist/): shipped KV
        pages are about to be WRITTEN into this slot's pages, and a write
        must never land on a page other holders read."""
        C = self.page_len
        n = len(prompt)
        total = self._total_pages(n, budget)
        assert total <= self.pages_per_slot, (total, self.pages_per_slot)

        match = (self.prefix.match(prompt)
                 if share and self.prefix is not None else None)
        shared = list(match.pages) if match else []
        tail_page = match.tail_page if match else None
        prefix_tokens = match.matched_tokens if match else 0

        need = total - len(shared)
        free = self.allocator.free_count()
        if need > free and self.prefix is not None:
            self.prefix.evict(need - free)
        fresh = [self.allocator.alloc() for _ in range(need)]

        row = self.block_table[slot]
        held = self._held[slot]
        k = len(shared)
        for idx, page in enumerate(shared):
            self.allocator.incref(page)
            row[idx] = page
            held.append(page)
        if tail_page is not None:
            # partial-tail share: the tail entry points at the shared page;
            # one fresh page is set aside as the copy-on-write destination
            # for the first divergent append (never placed until then)
            self.allocator.incref(tail_page)
            row[k] = tail_page
            held.append(tail_page)
            self._cow_reserve[slot] = fresh[0]
            held.append(fresh[0])
            rest = fresh[1:]
            start = k + 1
        else:
            rest = fresh
            start = k
        for off, page in enumerate(rest):
            row[start + off] = page
            held.append(page)

        full_cover = prefix_tokens >= n or (k * C >= n)
        if full_cover:
            chunk_starts = [((n - 1) // C) * C]
        else:
            chunk_starts = list(range(k * C, n, C))
        # reprefill detection: a full chunk about to be computed whose
        # content hash register() once published means the cache HAD this
        # K/V and evicted it — repeated work, not a cold miss.  (full_cover
        # plans re-run one chunk for logits only; that is inherent, not
        # waste.  share=False is the disagg handoff — no local compute.)
        reprefill = 0
        if share and self.prefix is not None and not full_cover:
            for start in chunk_starts:
                if (start + C <= n
                        and self._chunk_key(prompt, start)
                        in self._seen_chunks):
                    reprefill += C
        self.reprefill_tokens += reprefill
        plan = AdmitPlan(
            prompt_len=n, budget=budget, chunk_starts=chunk_starts,
            null_target=full_cover, prefix_tokens=prefix_tokens,
            shared_tail=tail_page is not None, reprefill_tokens=reprefill,
        )
        self._plans[slot] = plan
        return plan

    def _chunk_key(self, prompt, start: int) -> int:
        """Content key for the page-aligned chunk at ``start``: the hash
        covers the WHOLE prefix through the chunk's end, because a chunk's
        K/V depends on everything before it."""
        return hash(tuple(prompt[:start + self.page_len]))

    # -- prefill support -----------------------------------------------------
    def chunk_row(self, slot: int, start: int, null_target: bool) -> np.ndarray:
        """The block-table row a prefill chunk call should see.  With
        ``null_target`` the chunk's own entry is redirected to the null
        page (scratch write; shared pages stay pristine) — a COPY, the
        authoritative table is untouched."""
        row = self.block_table[slot]
        if not null_target:
            return row.copy()
        tmp = row.copy()
        tmp[start // self.page_len] = NULL_PAGE
        return tmp

    def prompt_page_ids(self, slot: int, n_tokens: int) -> List[int]:
        """The page ids holding the first ``n_tokens`` positions of the
        slot's context — the pages a disaggregated KV handoff ships/fills
        (engine/dist/kv_transfer.py)."""
        n_pages = -(-n_tokens // self.page_len)
        return [int(p) for p in self.block_table[slot][:n_pages]]

    def register(self, slot: int, prompt) -> int:
        """Publish the slot's full prompt chunks to the prefix cache (after
        its prefill completed — the pages now hold the prompt's K/V)."""
        if self.prefix is None:
            return 0
        C = self.page_len
        full = len(prompt) // C
        for i in range(full):
            key = self._chunk_key(prompt, i * C)
            self._seen_chunks[key] = None
            self._seen_chunks.move_to_end(key)
        while len(self._seen_chunks) > _SEEN_CHUNK_CAP:
            self._seen_chunks.popitem(last=False)
        return self.prefix.insert(prompt, list(self.block_table[slot][:full]))

    def resolve_cow(self, slot: int) -> Optional[Tuple[int, int]]:
        """Copy-on-write before the slot's first decode append: if the tail
        page is shared, repoint the table at the reserved private page and
        return ``(dst, src)`` for the device-side page copy (caller runs
        it).  The shared source keeps its other holders."""
        plan = self._plans[slot]
        if plan is None or not plan.shared_tail:
            return None
        tail_idx = plan.prompt_len // self.page_len
        src = int(self.block_table[slot][tail_idx])
        dst = self._cow_reserve[slot]
        assert dst is not None
        self.block_table[slot][tail_idx] = dst
        self._cow_reserve[slot] = None
        # the slot no longer references the shared source
        self._held[slot].remove(src)
        self.allocator.decref(src)
        plan.shared_tail = False
        self.cow_copies += 1
        return dst, src

    # -- retirement ----------------------------------------------------------
    def release(self, slot: int) -> None:
        for page in self._held[slot]:
            self.allocator.decref(page)
        self._held[slot] = []
        self._cow_reserve[slot] = None
        self._plans[slot] = None
        self.block_table[slot].fill(NULL_PAGE)

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        out = {
            "pages_total": self.allocator.num_pages - 1,
            "pages_free": self.allocator.free_count(),
            "pages_used": self.allocator.used_count(),
            "page_len": self.page_len,
            "cow_copies": self.cow_copies,
            "reprefill_tokens": self.reprefill_tokens,
        }
        if self.prefix is not None:
            p = self.prefix
            looked = p.hits + p.misses
            out.update(
                prefix_resident_pages=p.resident_pages(),
                prefix_hits=p.hits,
                prefix_misses=p.misses,
                prefix_partial_hits=p.partial_hits,
                prefix_tokens_reused=p.tokens_reused,
                prefix_evictions=p.evictions,
                prefix_hit_rate=(p.hits / looked) if looked else 0.0,
            )
        return out
