"""tpu_air.engine.kvpool — block-table-paged KV cache for the engine.

Replaces the per-slot slab pool (one `[S, slot_len, h*d]` row per slot)
with a pool of fixed-size KV *pages* `[P, page_len, h*d]` per layer plus a
host-side block table mapping each slot's logical positions onto physical
pages.  Three pieces:

* :class:`BlockAllocator` — refcounted page ids over the device pool, with
  free-list reuse (host bookkeeping; the device arrays live in the engine's
  donated cache and never move).
* :class:`PrefixCache` — a radix-over-page-chunks index so prompts sharing
  a prefix (system prompts, few-shot templates) map their leading block
  table entries to the SAME physical pages; copy-on-write on the first
  divergent append into a shared page.
* :class:`PagedKVPool` — the per-engine orchestration: block tables,
  admission planning (which chunks still need prefill after prefix hits),
  CoW resolution and retirement refcounting.

Device-side companions (paged cache init, the paged decode step, the
chunked-prefill unit, the CoW page copy) live in
``tpu_air/models/lm/generate.py`` next to the slab entry points they
generalize; the page layout keeps the flat ``[*, page_len, h*d]``
last-two-dims contract that won the round-5 roofline study
(docs/ANALYSIS.md) — ``page_len`` is a multiple of 8 so every page is
whole (8, 128) tiles.
"""

from .allocator import BlockAllocator, KVPoolOOMError
from .pool import AdmitPlan, PagedKVPool
from .prefix import PrefixCache, PrefixMatch

__all__ = [
    "AdmitPlan",
    "BlockAllocator",
    "KVPoolOOMError",
    "PagedKVPool",
    "PrefixCache",
    "PrefixMatch",
]
