"""Prefix cache: radix tree over page-sized token chunks → physical pages.

Prompts are split into ``page_len``-token chunks; each radix edge is one
chunk labelled by its exact token ids and carrying the physical page that
holds that chunk's K/V.  A lookup walks full chunks from the root, so two
prompts sharing a system-prompt prefix resolve their leading block-table
entries to the SAME pages — admission then prefills only the uncovered
suffix.

Sharing granularity:

* **full chunks** — an edge matches iff all ``page_len`` tokens match.
* **partial tail** — when every full chunk matched and the prompt's final
  partial chunk is a PREFIX of some child edge's tokens, that edge's page
  is shared too (the extra positions are masked by the per-slot validity
  mask, so they are invisible).  The first append into such a page — the
  request's first decode token — diverges from the cached content, so the
  engine copies the page first: copy-on-write, resolved host-side by
  :class:`~tpu_air.engine.kvpool.pool.PagedKVPool`.

Residency: the cache holds ONE allocator reference per resident page, so
pages of retired requests survive for future hits.  When the pool runs
dry, :meth:`evict` drops least-recently-used *leaf* edges whose page has
no other holder (refcount 1 — the cache itself); interior edges only
become evictable once their subtree is gone, keeping every cached path
walkable from the root.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .allocator import BlockAllocator


class _Node:
    __slots__ = ("children",)

    def __init__(self):
        # chunk token-tuple -> _Edge; insertion-ordered (dict), LRU decided
        # by edge ticks, not ordering
        self.children: Dict[Tuple[int, ...], "_Edge"] = {}


class _Edge:
    __slots__ = ("page", "child", "tick")

    def __init__(self, page: int, tick: int):
        self.page = page
        self.child = _Node()
        self.tick = tick


@dataclass
class PrefixMatch:
    """Result of one lookup.

    ``pages`` — physical pages for the matched FULL chunks, in block-table
    order.  ``tail_page`` — a shared partial-tail page (or None); when set,
    the whole prompt is covered and the engine owes a copy-on-write before
    the first decode append.  ``matched_tokens`` counts full-chunk tokens
    plus the partial tail.  The caller owns taking refs (via
    ``BlockAllocator.incref``) on any page it actually uses.
    """

    pages: List[int] = field(default_factory=list)
    matched_tokens: int = 0
    tail_page: Optional[int] = None


class PrefixCache:
    """Radix-over-chunks prefix index bound to one :class:`BlockAllocator`."""

    def __init__(self, allocator: BlockAllocator, page_len: int):
        self.allocator = allocator
        self.page_len = page_len
        self._root = _Node()
        self._tick = 0
        self._resident = 0  # edges (== cache-held pages)
        # stats (host counters; surfaced through EngineMetrics)
        self.hits = 0
        self.misses = 0
        self.partial_hits = 0
        self.tokens_reused = 0
        self.evictions = 0

    # -- lookup --------------------------------------------------------------
    def match(self, tokens, touch: bool = True) -> PrefixMatch:
        """Longest shared prefix of ``tokens``; read-only when ``touch`` is
        False (admission capacity probes must not bump LRU or stats)."""
        C = self.page_len
        tokens = list(tokens)
        n = len(tokens)
        out = PrefixMatch()
        if touch:
            self._tick += 1
        node = self._root
        full = n // C
        i = 0
        while i < full:
            chunk = tuple(tokens[i * C:(i + 1) * C])
            edge = node.children.get(chunk)
            if edge is None:
                break
            out.pages.append(edge.page)
            if touch:
                edge.tick = self._tick
            node = edge.child
            i += 1
        out.matched_tokens = i * C
        # partial tail: only meaningful when it covers the prompt's end —
        # every full chunk matched and the remainder is shorter than a page
        rem = tokens[i * C:]
        if i == full and 0 < len(rem) < C:
            rt = tuple(rem)
            for chunk, edge in node.children.items():
                if chunk[: len(rt)] == rt:
                    out.tail_page = edge.page
                    out.matched_tokens += len(rem)
                    if touch:
                        edge.tick = self._tick
                    break
        if touch:
            if out.matched_tokens:
                self.hits += 1
                self.tokens_reused += out.matched_tokens
                if out.tail_page is not None:
                    self.partial_hits += 1
            else:
                self.misses += 1
        return out

    # -- residency -----------------------------------------------------------
    def insert(self, tokens, pages: List[int]) -> int:
        """Register ``tokens``'s full chunks as resident, chunk ``k`` held
        by ``pages[k]``.  Existing edges win (first writer published; the
        duplicate page stays private to its slot and is freed at
        retirement).  Takes one allocator ref per NEWLY inserted page;
        returns how many were inserted."""
        C = self.page_len
        tokens = list(tokens)
        full = len(tokens) // C
        if len(pages) < full:
            raise ValueError(
                f"need {full} pages for {len(tokens)} tokens, got {len(pages)}"
            )
        self._tick += 1
        node, added = self._root, 0
        for k in range(full):
            chunk = tuple(tokens[k * C:(k + 1) * C])
            edge = node.children.get(chunk)
            if edge is None:
                edge = _Edge(pages[k], self._tick)
                self.allocator.incref(pages[k])
                node.children[chunk] = edge
                self._resident += 1
                added += 1
            else:
                edge.tick = self._tick
            node = edge.child
        return added

    def resident_pages(self) -> int:
        return self._resident

    # -- eviction ------------------------------------------------------------
    def _evictable(self, node: _Node, out: List[Tuple[int, _Node, Tuple]]):
        for chunk, edge in node.children.items():
            if edge.child.children:
                self._evictable(edge.child, out)
            elif self.allocator.refcount(edge.page) == 1:
                # leaf + only the cache holds it -> reclaimable
                out.append((edge.tick, node, chunk))

    def evictable_count(self) -> int:
        """Pages reclaimable RIGHT NOW (unreferenced leaves).  A lower
        bound on total reclaimable: evicting leaves exposes parents."""
        out: List[Tuple[int, _Node, Tuple]] = []
        self._evictable(self._root, out)
        return len(out)

    def evict(self, need: int) -> int:
        """Free at least ``need`` pages by dropping LRU unreferenced leaf
        edges, re-scanning as parents become leaves.  Returns pages freed
        (may be < ``need`` when live references pin the rest)."""
        freed = 0
        while freed < need:
            cands: List[Tuple[int, _Node, Tuple]] = []
            self._evictable(self._root, cands)
            if not cands:
                break
            cands.sort(key=lambda t: t[0])
            for tick, parent, chunk in cands:
                if freed >= need:
                    break
                edge = parent.children.pop(chunk)
                self.allocator.decref(edge.page)
                self._resident -= 1
                self.evictions += 1
                freed += 1
        return freed
