"""Request/response types and config for the online inference engine.

The engine's unit of work is a :class:`Request` (one prompt + decode
budget); its unit of delivery is a :class:`ResponseStream` — emitted token
ids land on the stream the same engine step they are decoded, so callers
see time-to-first-token, not time-to-last-token.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence, Tuple, Union

from tpu_air.core.runtime import TpuAirError


#: SLO priority classes, highest first.  Admission pops classes in this
#: order every engine step (iteration-granularity priority — the Orca
#: observation applied to admission, not just batching), and the serve
#: plane's admission controller sheds/queues the tail classes first under
#: overload (serve/admission.py).
PRIORITIES = ("interactive", "batch", "best_effort")


class EngineOverloadedError(TpuAirError):
    """Admission queue is full — backpressure, not failure.  The serve
    proxy maps this to HTTP 503 (the NoLiveReplicasError semantics): the
    client should retry, nothing is broken."""


class EngineDrainingError(EngineOverloadedError):
    """The engine is draining (zero-downtime rollout / scale-down): new
    submissions are refused while already-admitted work retires.  Same
    retry contract as overload — the proxy maps it to 503 and the router
    has already stopped sending new traffic here."""


class EngineClosedError(TpuAirError):
    """The engine was shut down with this request still queued/in flight."""


class RequestValidationError(ValueError, TpuAirError):
    """The request itself is malformed (unknown ``adapter_id``): the
    client's fault, not the server's.  A ValueError subclass so local
    callers can keep catching ValueError, but distinct across the actor
    boundary — the proxy maps THIS name to HTTP 400 while an application
    ValueError raised inside a replica stays a 500 (it signals a server
    bug, and must not be retried as if resubmitting could fix it)."""


@dataclass
class EngineConfig:
    """Dials for the KV pool and admission policy.

    * ``num_slots`` — S, the fixed decode batch width.  One persistent
      compiled step serves the whole engine lifetime; a slot is one
      in-flight sequence.
    * ``slot_len`` — L, max positions per sequence.  Admission requires
      ``len(prompt) + max_new_tokens <= slot_len``.
    * ``max_new_tokens`` — default per-request decode budget.
    * ``max_queue`` — queued (not yet admitted) request cap; beyond it
      ``submit`` raises :class:`EngineOverloadedError`.
    * ``kv_mode`` — ``"paged"`` (default): block-table-paged KV pool with
      prefix sharing and chunked prefill (``tpu_air/engine/kvpool/``);
      ``"slab"``: the PR 1 fixed per-slot slabs ``[S, slot_len, h*d]``
      (kept as the bench baseline and the mode the T5 window engine uses).
    * ``page_len`` — paged mode: positions per KV page.  Multiples of 8
      keep every page whole (8, 128) TPU tiles in the flat ``h*d`` layout.
    * ``num_pages`` — paged mode: physical pages in the pool (page 0 is
      the pinned null page).  ``None`` → slab-equivalent capacity,
      ``num_slots * ceil(slot_len / page_len) + 1`` — same HBM as the
      slab pool; prefix sharing turns the saved pages into headroom.
    * ``prefix_cache`` — paged mode: keep retired prompts' pages resident
      (radix over page chunks) so later prompts sharing a prefix skip
      that prefill and share the physical pages.
    * ``prefill_chunks_per_step`` — paged mode: prefill chunks run per
      engine step, interleaved between pool decode steps.  1 (default)
      bounds how long any prefill work can delay in-flight decodes, so a
      long prompt streams in page-sized pieces while short requests keep
      decoding (flat TTFT under long-prompt arrival).
    * ``reorder_window`` — admission may look this many queue entries past
      a request that does not currently fit (no free KV pages) and admit
      later ones that do.  0 restores strict FIFO.
    * ``reserved_interactive_slots`` — keep this many FREE slots that only
      ``interactive``-class requests may take: a burst of batch/best-effort
      decodes can then never occupy the whole pool, so an arriving
      interactive request admits (and reaches its first token) without
      waiting for a lower-class slot to retire.  0 (default) disables the
      reserve — all classes compete for all slots.
    * ``queue_shares`` — fraction of ``max_queue`` each priority class may
      see the TOTAL queue grow to before its submits are rejected
      (engine-side shed).  Defaults: interactive 1.0, batch 0.85,
      best_effort 0.5 — as the queue fills, best-effort sheds first,
      then batch, and interactive keeps the full ``max_queue``.
    * ``prefill_buckets`` — slab mode: prompt-length buckets (ascending);
      prompts right-pad to the smallest fitting bucket so prefill
      compiles once per bucket.  ``None`` → powers of two up to
      ``slot_len``.  Paged mode needs no buckets: every prompt length
      runs through one compiled page-sized chunk program.
    * ``eos_token_id`` — ``"model"`` (default): use the model config's
      ``eos_token_id``; ``None``: never early-stop (budget-only
      retirement); an int: that id.
    * ``adapter_slots`` — multi-tenant LoRA: rows in the resident adapter
      bank (0 disables adapters; paged single-chip engines only).  Row 0
      is the pinned zero adapter, so the bank holds ``adapter_slots``
      loadable tenants on top of it.  Per-request selection rides
      ``Request.adapter_id``; the decode step gathers each slot's delta
      the way it gathers the block table.
    * ``adapter_rank`` — LoRA rank r of the bank rows ``[d, r] x [r, V]``.
      Lower-rank adapters zero-pad into the bank; higher ranks are
      rejected at load.
    """

    num_slots: int = 8
    slot_len: int = 256
    max_new_tokens: int = 64
    max_queue: int = 256
    kv_mode: str = "paged"
    page_len: int = 16
    num_pages: Optional[int] = None
    prefix_cache: bool = True
    prefill_chunks_per_step: int = 1
    reorder_window: int = 4
    reserved_interactive_slots: int = 0
    queue_shares: Optional[dict] = None
    prefill_buckets: Optional[Tuple[int, ...]] = None
    eos_token_id: Union[int, None, str] = "model"
    adapter_slots: int = 0
    adapter_rank: int = 4

    _DEFAULT_QUEUE_SHARES = {
        "interactive": 1.0, "batch": 0.85, "best_effort": 0.5,
    }

    def queue_cap(self, priority: str) -> int:
        """Total queue depth at which ``priority``-class submits shed."""
        shares = self.queue_shares or self._DEFAULT_QUEUE_SHARES
        return int(self.max_queue * float(shares.get(priority, 1.0)))

    def pages_per_slot(self) -> int:
        return -(-self.slot_len // self.page_len)

    def pool_pages(self) -> int:
        if self.num_pages is not None:
            return self.num_pages
        return self.num_slots * self.pages_per_slot() + 1

    def buckets(self) -> Tuple[int, ...]:
        if self.prefill_buckets is not None:
            return tuple(sorted(self.prefill_buckets))
        out, b = [], 1
        while b < self.slot_len:
            out.append(b)
            b *= 2
        out.append(self.slot_len)
        return tuple(out)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets():
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds the largest prefill bucket "
            f"{self.buckets()[-1]} (slot_len={self.slot_len})"
        )


_DONE = object()


class ResponseStream:
    """Per-request token stream.

    The engine appends ids as they are decoded; callers either iterate
    (``for tok in stream: ...`` — blocks until each token arrives, ends at
    retirement) or join (``stream.result()`` — the full id list, raising if
    the request failed).  Emitted tokens INCLUDE the EOS id when early-stop
    triggered, matching offline ``generate`` (which emits EOS then pads).
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    # -- engine side ---------------------------------------------------------
    def _emit(self, token: int) -> None:
        self._tokens.append(token)
        self._q.put(token)

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self._error = error
        self._done.set()
        self._q.put(_DONE)

    # -- caller side ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def tokens_so_far(self) -> List[int]:
        return list(self._tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()


@dataclass
class Request:
    """One admitted unit of work (internal; callers hold the stream)."""

    request_id: int
    prompt: Sequence[int]
    max_new_tokens: int
    stream: ResponseStream
    # SLO class (one of PRIORITIES): admission pops interactive first each
    # step, and the scheduler sheds the tail classes at lower queue depths
    priority: str = "interactive"
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    # airtrace: carrier captured at submit + wall-clock stamps (ns) for the
    # retirement-time span emission (engine.py _emit_request_spans).  All
    # zero/None when tracing is off — the hot loop never touches them.
    trace_ctx: Optional[dict] = None
    t_submit_ns: int = 0
    t_admit_ns: int = 0
    t_first_ns: int = 0
    # disaggregated serving (engine/dist/): a request whose prefill ran on
    # a PrefillWorker replica arrives with its first token and the prompt's
    # KV pages ({"first_token": int, "pages": {layer_path: {"k", "v"}}});
    # admission inserts the pages and goes straight to decode.  None for
    # the normal (engine-prefills) path.
    prefilled: Optional[dict] = None
    # preemption migration (engine ``migrate_out`` → ``submit_migrated``):
    # a stream that was already DECODING on a preempted replica arrives
    # with every client-visible token it emitted there plus the KV pages
    # covering its context ({"streamed": [int], "pages": {...},
    # "client_prompt_len": int}); admission inserts the pages, force-emits
    # the streamed tokens, and resumes decode at the exact cursor — zero
    # prefill chunks.  None for every other path.
    migrated: Optional[dict] = None
    # end-to-end deadline as ABSOLUTE unix-epoch milliseconds (a relative
    # budget would silently re-extend at every hop).  The proxy converts
    # the client's relative budget at admission; the scheduler expires
    # still-queued requests past it (DeadlineExceededError → HTTP 504)
    # rather than letting them occupy a slot they can no longer use.
    deadline_ms: Optional[float] = None
    # multi-tenant LoRA: the tenant adapter this request decodes under
    # (None = base model).  Validated against the loaded-adapter table at
    # submit (fail fast) AND re-resolved at admission (the adapter may
    # have been evicted while the request sat queued); ``adapter_row`` is
    # the resolved bank row the slot gathers each step (0 = zero adapter).
    adapter_id: Optional[str] = None
    adapter_row: int = 0
    # cost-attribution label (airwatch CostLedger): who to BILL this
    # request's tokens/chip-seconds to when that differs from the LoRA
    # tenant — the batch lane stamps ``batch:<job_id>`` here so offline
    # work never folds into the interactive "default" tenant.  Unlike
    # ``adapter_id`` it is never validated (a pure label, not a bank row);
    # billing uses ``tenant or adapter_id``.
    tenant: Optional[str] = None
