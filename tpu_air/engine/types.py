"""Request/response types and config for the online inference engine.

The engine's unit of work is a :class:`Request` (one prompt + decode
budget); its unit of delivery is a :class:`ResponseStream` — emitted token
ids land on the stream the same engine step they are decoded, so callers
see time-to-first-token, not time-to-last-token.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional, Sequence, Tuple, Union

from tpu_air.core.runtime import TpuAirError


class EngineOverloadedError(TpuAirError):
    """Admission queue is full — backpressure, not failure.  The serve
    proxy maps this to HTTP 503 (the NoLiveReplicasError semantics): the
    client should retry, nothing is broken."""


class EngineClosedError(TpuAirError):
    """The engine was shut down with this request still queued/in flight."""


@dataclass
class EngineConfig:
    """Dials for the slot pool and admission policy.

    * ``num_slots`` — S, the fixed decode batch width.  One persistent
      compiled step serves the whole engine lifetime; a slot is one
      in-flight sequence.
    * ``slot_len`` — L, positions per slot (the flat KV slab is
      ``[S, L, h*d]`` per layer).  Admission requires
      ``len(prompt) + max_new_tokens <= slot_len``.
    * ``max_new_tokens`` — default per-request decode budget.
    * ``max_queue`` — queued (not yet admitted) request cap; beyond it
      ``submit`` raises :class:`EngineOverloadedError`.
    * ``prefill_buckets`` — prompt-length buckets (ascending).  Prompts are
      right-padded to the smallest fitting bucket so prefill compiles once
      per bucket, not once per length.  ``None`` → powers of two up to
      ``slot_len``.
    * ``eos_token_id`` — ``"model"`` (default): use the model config's
      ``eos_token_id``; ``None``: never early-stop (budget-only
      retirement); an int: that id.
    """

    num_slots: int = 8
    slot_len: int = 256
    max_new_tokens: int = 64
    max_queue: int = 256
    prefill_buckets: Optional[Tuple[int, ...]] = None
    eos_token_id: Union[int, None, str] = "model"

    def buckets(self) -> Tuple[int, ...]:
        if self.prefill_buckets is not None:
            return tuple(sorted(self.prefill_buckets))
        out, b = [], 1
        while b < self.slot_len:
            out.append(b)
            b *= 2
        out.append(self.slot_len)
        return tuple(out)

    def bucket_for(self, n: int) -> int:
        for b in self.buckets():
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds the largest prefill bucket "
            f"{self.buckets()[-1]} (slot_len={self.slot_len})"
        )


_DONE = object()


class ResponseStream:
    """Per-request token stream.

    The engine appends ids as they are decoded; callers either iterate
    (``for tok in stream: ...`` — blocks until each token arrives, ends at
    retirement) or join (``stream.result()`` — the full id list, raising if
    the request failed).  Emitted tokens INCLUDE the EOS id when early-stop
    triggered, matching offline ``generate`` (which emits EOS then pads).
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._q: "queue.Queue" = queue.Queue()
        self._tokens: List[int] = []
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    # -- engine side ---------------------------------------------------------
    def _emit(self, token: int) -> None:
        self._tokens.append(token)
        self._q.put(token)

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self._error = error
        self._done.set()
        self._q.put(_DONE)

    # -- caller side ---------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        while True:
            item = self._q.get()
            if item is _DONE:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished after {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def tokens_so_far(self) -> List[int]:
        return list(self._tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()


@dataclass
class Request:
    """One admitted unit of work (internal; callers hold the stream)."""

    request_id: int
    prompt: Sequence[int]
    max_new_tokens: int
    stream: ResponseStream
    submitted_at: float = field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    # airtrace: carrier captured at submit + wall-clock stamps (ns) for the
    # retirement-time span emission (engine.py _emit_request_spans).  All
    # zero/None when tracing is off — the hot loop never touches them.
    trace_ctx: Optional[dict] = None
    t_submit_ns: int = 0
    t_admit_ns: int = 0
    t_first_ns: int = 0
