"""Engine gauges — queue depth, slot occupancy, tokens/s, TTFT, step
latency — exported through the existing observability dashboard.

Process-local registry: ``EngineMetrics`` instances self-register by engine
name at construction; ``observability/dashboard.py`` folds
:func:`snapshot_all` into ``/metrics`` (prometheus text) and serves it as
``/api/engines``.  The dashboard runs in the driver process, so it sees the
engines of THAT process — a driver-embedded engine, or the test/bench
harness.  Engines inside serve replica workers expose the same snapshot
over the deployment's ``stats`` method instead (serve/engine_deployment.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict

from .types import PRIORITIES

_WINDOW = 256          # samples kept for the latency distributions
_RATE_WINDOW_S = 10.0  # tokens/s horizon


def _dist(samples) -> Dict[str, float]:
    xs = sorted(samples)
    if not xs:
        return {"count": 0}
    return {
        "count": len(xs),
        "mean": sum(xs) / len(xs),
        "p50": xs[len(xs) // 2],
        "p95": xs[min(len(xs) - 1, int(len(xs) * 0.95))],
        "p99": xs[min(len(xs) - 1, int(len(xs) * 0.99))],
        "max": xs[-1],
    }


class EngineMetrics:
    """Thread-safe gauges/counters for one engine instance."""

    def __init__(self, name: str = "engine", num_slots: int = 0):
        self.name = name
        self.num_slots = num_slots
        self._lock = threading.Lock()
        # gauges (set whole each observation)
        self.queue_depth = 0
        self.slot_occupancy = 0
        # counters
        self.requests_submitted = 0
        self.requests_rejected = 0
        self.requests_completed = 0
        self.tokens_emitted = 0
        # per-priority-class breakdowns (SLO-aware serving): submits/sheds
        # by class plus a per-class TTFT window, so the interactive p99 the
        # admission controller and autoscaler steer on is visible directly
        self.submitted_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.rejected_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._ttft_by_class: Dict[str, Deque[float]] = {
            p: deque(maxlen=_WINDOW) for p in PRIORITIES
        }
        self.queue_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.draining = False
        # distributions / rates
        self._ttft_s: Deque[float] = deque(maxlen=_WINDOW)
        self._step_s: Deque[float] = deque(maxlen=_WINDOW)
        self._token_stamps: Deque[Any] = deque()  # (t, n) for tokens/s
        # paged-KV gauges (empty for slab engines — snapshot shape is then
        # unchanged from the slab era)
        self.kvpool: Dict[str, Any] = {}
        self.reordered_admits = 0
        self.prefill_chunks = 0
        # mesh/lease/role metadata (empty for single-chip engines)
        self.topology: Dict[str, Any] = {}
        register(self)

    def set_topology(self, **kw: Any) -> None:
        """Attach placement metadata (lease id, mesh shape, role, replica
        counts).  String values surface as an info-line's labels; numeric
        values as gauges.  Set once at engine construction."""
        with self._lock:
            self.topology.update(kw)

    # -- engine-side recording ----------------------------------------------
    def observe_gauges(self, queue_depth: int, slot_occupancy: int,
                       kvpool: Dict[str, Any] = None,
                       reordered_admits: int = None,
                       prefill_chunks: int = None,
                       queue_by_class: Dict[str, int] = None,
                       draining: bool = None) -> None:
        with self._lock:
            self.queue_depth = queue_depth
            self.slot_occupancy = slot_occupancy
            if kvpool is not None:
                self.kvpool = dict(kvpool)
            if reordered_admits is not None:
                self.reordered_admits = reordered_admits
            if prefill_chunks is not None:
                self.prefill_chunks = prefill_chunks
            if queue_by_class is not None:
                self.queue_by_class = dict(queue_by_class)
            if draining is not None:
                self.draining = bool(draining)

    def record_submit(self, priority: str = "interactive") -> None:
        with self._lock:
            self.requests_submitted += 1
            if priority in self.submitted_by_class:
                self.submitted_by_class[priority] += 1

    def record_reject(self, priority: str = "interactive") -> None:
        with self._lock:
            self.requests_rejected += 1
            if priority in self.rejected_by_class:
                self.rejected_by_class[priority] += 1

    def record_complete(self) -> None:
        with self._lock:
            self.requests_completed += 1

    def record_ttft(self, seconds: float,
                    priority: str = "interactive") -> None:
        with self._lock:
            self._ttft_s.append(seconds)
            if priority in self._ttft_by_class:
                self._ttft_by_class[priority].append(seconds)

    def record_tokens(self, tokens: int) -> None:
        """Count emitted tokens outside a pool step (prefill's first token)."""
        now = time.monotonic()
        with self._lock:
            self.tokens_emitted += tokens
            self._token_stamps.append((now, tokens))
            self._trim_stamps(now)

    def record_step(self, seconds: float, tokens: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._step_s.append(seconds)
            self.tokens_emitted += tokens
            self._token_stamps.append((now, tokens))
            self._trim_stamps(now)

    def _trim_stamps(self, now: float) -> None:
        horizon = now - _RATE_WINDOW_S
        while self._token_stamps and self._token_stamps[0][0] < horizon:
            self._token_stamps.popleft()

    def reset_window(self) -> None:
        """Clear the latency windows and rate stamps (counters stay).  For
        benches that warm jit caches through the engine and then measure a
        clean steady-state window."""
        with self._lock:
            self._ttft_s.clear()
            self._step_s.clear()
            self._token_stamps.clear()
            for q in self._ttft_by_class.values():
                q.clear()

    # -- dashboard-side ------------------------------------------------------
    def tokens_per_s(self) -> float:
        now = time.monotonic()
        with self._lock:
            stamps = [(t, n) for t, n in self._token_stamps
                      if t >= now - _RATE_WINDOW_S]
            if not stamps:
                return 0.0
            span = max(now - stamps[0][0], 1e-6)
            return sum(n for _, n in stamps) / span

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "name": self.name,
                "num_slots": self.num_slots,
                "queue_depth": self.queue_depth,
                "slot_occupancy": self.slot_occupancy,
                "requests_submitted": self.requests_submitted,
                "requests_rejected": self.requests_rejected,
                "requests_completed": self.requests_completed,
                "tokens_emitted": self.tokens_emitted,
                "ttft_s": _dist(self._ttft_s),
                "step_latency_s": _dist(self._step_s),
                "draining": self.draining,
                "priority": {
                    p: {
                        "submitted": self.submitted_by_class[p],
                        "shed": self.rejected_by_class[p],
                        "queue_depth": self.queue_by_class.get(p, 0),
                        "ttft_s": _dist(self._ttft_by_class[p]),
                    }
                    for p in PRIORITIES
                },
            }
            if self.kvpool:
                out["kvpool"] = dict(self.kvpool)
                out["reordered_admits"] = self.reordered_admits
                out["prefill_chunks"] = self.prefill_chunks
            if self.topology:
                out["topology"] = dict(self.topology)
        out["tokens_per_s"] = self.tokens_per_s()
        return out


_registry: Dict[str, EngineMetrics] = {}
_registry_lock = threading.Lock()


def register(metrics: EngineMetrics) -> None:
    """Last registration wins per name (an engine restarted under the same
    name replaces its predecessor's gauges)."""
    with _registry_lock:
        _registry[metrics.name] = metrics


def unregister(name: str) -> None:
    with _registry_lock:
        _registry.pop(name, None)


def snapshot_all() -> Dict[str, Dict[str, Any]]:
    with _registry_lock:
        engines = list(_registry.values())
    return {m.name: m.snapshot() for m in engines}


def prometheus_lines(snapshots: Dict[str, Dict[str, Any]] = None) -> list:
    """Engine gauges in prometheus text form (dashboard /metrics).

    ``snapshots`` defaults to this process's registry; the dashboard passes
    a merged dict that also folds in serve-replica snapshots (keys there are
    ``deployment/replica/engine`` paths — label values, so any charset is
    fine after quote-escaping)."""
    if snapshots is None:
        snapshots = snapshot_all()
    lines = []
    for name, snap in sorted(snapshots.items()):
        if not snap:
            continue
        label = name.replace("\\", "\\\\").replace('"', '\\"')
        tag = f'{{engine="{label}"}}'
        for key in ("queue_depth", "slot_occupancy", "requests_submitted",
                    "requests_rejected", "requests_completed",
                    "tokens_emitted"):
            if key in snap:
                lines.append(f"tpu_air_engine_{key}{tag} {snap[key]}")
        if "tokens_per_s" in snap:
            lines.append(f"tpu_air_engine_tokens_per_s{tag} "
                         f"{snap['tokens_per_s']:.3f}")
        for dist_key in ("ttft_s", "step_latency_s"):
            d = snap.get(dist_key) or {}
            if d.get("count"):
                lines.append(
                    f"tpu_air_engine_{dist_key}_p50{tag} {d['p50']:.6f}"
                )
                lines.append(
                    f"tpu_air_engine_{dist_key}_p95{tag} {d['p95']:.6f}"
                )
        # paged-KV pool gauges (absent on slab engines)
        for key, val in sorted((snap.get("kvpool") or {}).items()):
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            lines.append(f"tpu_air_engine_kvpool_{key}{tag} {val:g}")
        for key in ("reordered_admits", "prefill_chunks"):
            if key in snap:
                lines.append(f"tpu_air_engine_{key}{tag} {snap[key]}")
        if "draining" in snap:
            lines.append(
                f"tpu_air_engine_draining{tag} {int(bool(snap['draining']))}")
        # per-priority-class counters/gauges ({engine=...,priority=...})
        for prio, pc in sorted((snap.get("priority") or {}).items()):
            ptag = f'{{engine="{label}",priority="{prio}"}}'
            for key in ("submitted", "shed", "queue_depth"):
                if key in pc:
                    lines.append(
                        f"tpu_air_engine_priority_{key}{ptag} {pc[key]}")
            d = pc.get("ttft_s") or {}
            if d.get("count"):
                lines.append(
                    f"tpu_air_engine_priority_ttft_s_p50{ptag} "
                    f"{d['p50']:.6f}")
                lines.append(
                    f"tpu_air_engine_priority_ttft_s_p99{ptag} "
                    f"{d['p99']:.6f}")
        # topology: strings fold into one info line's labels, numbers
        # (replica counts, device counts) become gauges
        topo = snap.get("topology") or {}
        if topo:
            from tpu_air.utils.metrics import sanitize_metric_name

            info = [f'engine="{label}"']
            for key, val in sorted(topo.items()):
                # keys become metric-name / label-name fragments: sanitize.
                # values are label VALUES — any charset, quote-escape only
                skey = sanitize_metric_name(str(key))
                if isinstance(val, bool) or not isinstance(val, (int, float)):
                    sval = str(val).replace("\\", "\\\\").replace('"', '\\"')
                    info.append(f'{skey}="{sval}"')
                else:
                    lines.append(
                        f"tpu_air_engine_topology_{skey}{tag} {val:g}")
            lines.append(
                "tpu_air_engine_topology_info{" + ",".join(info) + "} 1")
    return lines
