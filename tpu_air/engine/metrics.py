"""Engine gauges — queue depth, slot occupancy, tokens/s, TTFT, step
latency — exported through the existing observability dashboard.

Process-local registry: ``EngineMetrics`` instances self-register by engine
name at construction; ``observability/dashboard.py`` folds
:func:`snapshot_all` into ``/metrics`` (prometheus text) and serves it as
``/api/engines``.  The dashboard runs in the driver process, so it sees the
engines of THAT process — a driver-embedded engine, or the test/bench
harness.  Engines inside serve replica workers expose the same snapshot
over the deployment's ``stats`` method instead (serve/engine_deployment.py).

Latency distributions are airscope :class:`~tpu_air.observability.perf.
Histogram` instances (log-bucketed, unwindowed, mergeable): the seed's
256-sample deques + sorted-index quantiles are gone, p50/p95/p99 cover the
engine's whole life, replica snapshots merge bucket-by-bucket
(:func:`merge_snapshots`), and TTFT samples recorded with a ``trace_id``
carry OpenMetrics exemplars that join a tail latency to its airtrace span
tree.  Each instance also owns a :class:`~tpu_air.observability.perf.
PerfLedger` the engine feeds per-program costs and goodput tokens into;
its roofline/goodput state rides along in :meth:`snapshot` as ``perf``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Deque, Dict, Optional

from collections import deque

from tpu_air.observability.perf import (
    Histogram,
    PerfLedger,
    ProgramCost,
    cumulative_from_summary,
    merge_ledger_snapshots,
    merge_summaries,
)
from tpu_air.utils.metrics import ExpositionBuilder, sanitize_metric_name

from .types import PRIORITIES

_RATE_WINDOW_S = 10.0  # tokens/s horizon


class EngineMetrics:
    """Thread-safe gauges/counters for one engine instance."""

    def __init__(self, name: str = "engine", num_slots: int = 0):
        self.name = name
        self.num_slots = num_slots
        self._lock = threading.Lock()
        # gauges (set whole each observation)
        self.queue_depth = 0
        self.slot_occupancy = 0
        # counters
        self.requests_submitted = 0
        self.requests_rejected = 0
        self.requests_completed = 0
        self.tokens_emitted = 0
        # per-priority-class breakdowns (SLO-aware serving): submits/sheds
        # by class plus a per-class TTFT histogram, so the interactive p99
        # the admission controller and autoscaler steer on is visible
        # directly
        self.submitted_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.rejected_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        # per-tenant-quota sheds by class (serve/admission.py tenant
        # budgets: the 429s, distinct from the overload 503s in ``shed``)
        self.quota_shed_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self._ttft_by_class: Dict[str, Histogram] = {
            p: Histogram() for p in PRIORITIES
        }
        self.queue_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        self.draining = False
        # queued requests swept past their absolute deadline_ms (the proxy
        # maps these to HTTP 504 — docs/RESILIENCE.md)
        self.deadline_expired = 0
        # distributions / rates
        self._ttft_h = Histogram()
        self._step_h = Histogram()
        self._token_stamps: Deque[Any] = deque()  # (t, n) for tokens/s
        # roofline + goodput accumulator (engine records program costs)
        self.ledger = PerfLedger()
        # paged-KV gauges (empty for slab engines — snapshot shape is then
        # unchanged from the slab era)
        self.kvpool: Dict[str, Any] = {}
        self.reordered_admits = 0
        self.prefill_chunks = 0
        # mesh/lease/role metadata (empty for single-chip engines)
        self.topology: Dict[str, Any] = {}
        # live-weight state (empty until the first swap/adapter load —
        # snapshot shape unchanged for engines that never hot-swap)
        self.weights: Dict[str, Any] = {}
        # preemption migration counters (empty until the first migrate —
        # same absent-until-used contract as ``weights``)
        self.migrations: Dict[str, Any] = {}
        # per-tenant usage counters keyed by adapter_id ("default" for the
        # base model), recorded at retirement — airwatch's cost-ledger feed
        # (same absent-until-used contract: empty until the first retire)
        self.tenants: Dict[str, Dict[str, float]] = {}
        register(self)

    def set_topology(self, **kw: Any) -> None:
        """Attach placement metadata (lease id, mesh shape, role, replica
        counts).  String values surface as an info-line's labels; numeric
        values as gauges.  Set once at engine construction."""
        with self._lock:
            self.topology.update(kw)

    # -- engine-side recording ----------------------------------------------
    def observe_gauges(self, queue_depth: int, slot_occupancy: int,
                       kvpool: Dict[str, Any] = None,
                       reordered_admits: int = None,
                       prefill_chunks: int = None,
                       queue_by_class: Dict[str, int] = None,
                       draining: bool = None,
                       deadline_expired: int = None) -> None:
        with self._lock:
            self.queue_depth = queue_depth
            self.slot_occupancy = slot_occupancy
            if kvpool is not None:
                self.kvpool = dict(kvpool)
            if reordered_admits is not None:
                self.reordered_admits = reordered_admits
            if prefill_chunks is not None:
                self.prefill_chunks = prefill_chunks
            if queue_by_class is not None:
                self.queue_by_class = dict(queue_by_class)
            if draining is not None:
                self.draining = bool(draining)
            if deadline_expired is not None:
                self.deadline_expired = deadline_expired

    def record_submit(self, priority: str = "interactive") -> None:
        with self._lock:
            self.requests_submitted += 1
            if priority in self.submitted_by_class:
                self.submitted_by_class[priority] += 1

    def record_reject(self, priority: str = "interactive") -> None:
        with self._lock:
            self.requests_rejected += 1
            if priority in self.rejected_by_class:
                self.rejected_by_class[priority] += 1

    def record_complete(self) -> None:
        with self._lock:
            self.requests_completed += 1

    def record_quota_shed(self, priority: str = "interactive") -> None:
        """A request shed by a per-tenant quota (HTTP 429) — the tenant
        exceeded ITS budget while the engine had capacity, so it counts
        apart from the overload ``shed``."""
        with self._lock:
            if priority in self.quota_shed_by_class:
                self.quota_shed_by_class[priority] += 1

    def record_migration(self, direction: str, pages: int,
                         reprefill_chunks: int = 0) -> None:
        """One live-slot migration through this engine: ``direction`` is
        ``"out"`` (slot extracted off a preempting replica) or ``"in"``
        (payload landed here).  ``reprefill_chunks`` counts prefill chunk
        programs the landing still has to run — zero by construction, and
        the preemption chaos test pins it at zero."""
        key = "out" if direction == "out" else "in"
        with self._lock:
            mg = self.migrations
            mg[key] = int(mg.get(key, 0)) + 1
            mg[key + "_pages"] = int(mg.get(key + "_pages", 0)) + int(pages)
            if key == "in":
                mg["in_reprefill_chunks"] = (
                    int(mg.get("in_reprefill_chunks", 0))
                    + int(reprefill_chunks))

    def _tenant(self, adapter_id: Optional[str]) -> Dict[str, float]:
        """Per-tenant counter dict (call with ``self._lock`` held)."""
        key = adapter_id if adapter_id else "default"
        d = self.tenants.get(key)
        if d is None:
            d = {"tokens_prefilled": 0, "tokens_decoded": 0,
                 "requests_completed": 0, "kv_page_seconds": 0.0,
                 "migrated_pages": 0}
            self.tenants[key] = d
        return d

    def record_tenant_retire(self, adapter_id: Optional[str],
                             prefilled: int, decoded: int,
                             kv_page_seconds: float) -> None:
        """One stream retired: bill its prompt/decode tokens and the
        KV-page residency (pages held × seconds resident) to its tenant
        (``adapter_id``, or the base-model ``"default"`` tenant).  The
        airwatch cost ledger differences these cumulative counters per
        scrape interval (observability/watch.py)."""
        with self._lock:
            d = self._tenant(adapter_id)
            d["requests_completed"] += 1
            d["tokens_prefilled"] += int(prefilled)
            d["tokens_decoded"] += int(decoded)
            d["kv_page_seconds"] += max(0.0, float(kv_page_seconds))

    def record_tenant_migrated(self, adapter_id: Optional[str],
                               pages: int) -> None:
        """KV pages shipped on behalf of one tenant's live-slot migration
        (billed at the landing, where the page count is exact)."""
        with self._lock:
            self._tenant(adapter_id)["migrated_pages"] += int(pages)

    def record_ttft(self, seconds: float, priority: str = "interactive",
                    trace_id: Optional[str] = None) -> None:
        """A first-token latency sample.  ``trace_id`` (when the request
        was traced) becomes the histogram bucket's exemplar — the join key
        from a dashboard tail-latency number to ``/api/traces?trace_id=``."""
        with self._lock:
            self._ttft_h.observe(seconds, trace_id)
            if priority in self._ttft_by_class:
                self._ttft_by_class[priority].observe(seconds, trace_id)

    def record_tokens(self, tokens: int) -> None:
        """Count emitted tokens outside a pool step (prefill's first token)."""
        now = time.monotonic()
        with self._lock:
            self.tokens_emitted += tokens
            self._token_stamps.append((now, tokens))
            self._trim_stamps(now)

    def record_step(self, seconds: float, tokens: int) -> None:
        now = time.monotonic()
        with self._lock:
            self._step_h.observe(seconds)
            self.tokens_emitted += tokens
            self._token_stamps.append((now, tokens))
            self._trim_stamps(now)

    def record_program(self, kind: str, cost: ProgramCost,
                       seconds: float) -> None:
        """Ledger feed: one compiled-program execution's analytic cost and
        measured wall time (engine.py's step/chunk instrumentation)."""
        with self._lock:
            self.ledger.record_program(kind, cost, seconds)

    def record_weights_swap(self, version: Optional[int], stall_ms: float,
                            rollback: bool = False) -> None:
        """One live weight swap on this engine: the version now serving,
        and the decode-step gap it cost (lock wait + reshard + device_put
        — the honest ``swap_stall_ms`` the bench gates on).  ``rollback``
        marks swaps that restored the prior version."""
        with self._lock:
            w = self.weights
            if version is not None:
                w["version"] = int(version)
            w["swaps"] = int(w.get("swaps", 0)) + 1
            if rollback:
                w["rollbacks"] = int(w.get("rollbacks", 0)) + 1
            w["last_stall_ms"] = float(stall_ms)
            w["max_stall_ms"] = max(float(stall_ms),
                                    float(w.get("max_stall_ms", 0.0)))

    def set_adapters_loaded(self, n: int) -> None:
        with self._lock:
            self.weights["adapters_loaded"] = int(n)

    def record_goodput(self, category: str, n: int) -> None:
        """Ledger feed: ``n`` tokens attributed to ``category`` ("useful"
        or a wasted class — perf.WASTED_CATEGORIES)."""
        with self._lock:
            self.ledger.record_tokens(category, n)

    def _trim_stamps(self, now: float) -> None:
        horizon = now - _RATE_WINDOW_S
        while self._token_stamps and self._token_stamps[0][0] < horizon:
            self._token_stamps.popleft()

    def reset_window(self) -> None:
        """Clear the latency histograms, rate stamps and ledger (counters
        stay).  For benches that warm jit caches through the engine and
        then measure a clean steady-state window."""
        with self._lock:
            self._ttft_h.reset()
            self._step_h.reset()
            self._token_stamps.clear()
            for h in self._ttft_by_class.values():
                h.reset()
            self.ledger.reset()

    # -- dashboard-side ------------------------------------------------------
    def tokens_per_s(self) -> float:
        now = time.monotonic()
        with self._lock:
            stamps = [(t, n) for t, n in self._token_stamps
                      if t >= now - _RATE_WINDOW_S]
            if not stamps:
                return 0.0
            span = max(now - stamps[0][0], 1e-6)
            return sum(n for _, n in stamps) / span

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "name": self.name,
                "num_slots": self.num_slots,
                "queue_depth": self.queue_depth,
                "slot_occupancy": self.slot_occupancy,
                "requests_submitted": self.requests_submitted,
                "requests_rejected": self.requests_rejected,
                "requests_completed": self.requests_completed,
                "tokens_emitted": self.tokens_emitted,
                "ttft_s": self._ttft_h.summary(),
                "step_latency_s": self._step_h.summary(),
                "draining": self.draining,
                "deadline_expired": self.deadline_expired,
                "priority": {
                    p: {
                        "submitted": self.submitted_by_class[p],
                        "shed": self.rejected_by_class[p],
                        "quota_shed": self.quota_shed_by_class[p],
                        "queue_depth": self.queue_by_class.get(p, 0),
                        "ttft_s": self._ttft_by_class[p].summary(),
                    }
                    for p in PRIORITIES
                },
                "perf": self.ledger.snapshot(),
            }
            if self.kvpool:
                out["kvpool"] = dict(self.kvpool)
                out["reordered_admits"] = self.reordered_admits
                out["prefill_chunks"] = self.prefill_chunks
            if self.topology:
                out["topology"] = dict(self.topology)
            if self.weights:
                out["weights"] = dict(self.weights)
            if self.migrations:
                out["migrations"] = dict(self.migrations)
            if self.tenants:
                out["tenants"] = {t: dict(d)
                                  for t, d in self.tenants.items()}
        out["tokens_per_s"] = self.tokens_per_s()
        return out


_registry: Dict[str, EngineMetrics] = {}
_registry_lock = threading.Lock()


def register(metrics: EngineMetrics) -> None:
    """Last registration wins per name (an engine restarted under the same
    name replaces its predecessor's gauges)."""
    with _registry_lock:
        _registry[metrics.name] = metrics


def unregister(name: str) -> None:
    with _registry_lock:
        _registry.pop(name, None)


def snapshot_all() -> Dict[str, Dict[str, Any]]:
    with _registry_lock:
        engines = list(_registry.values())
    return {m.name: m.snapshot() for m in engines}


def merge_snapshots(snapshots: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet-level aggregate of engine snapshots (driver engines + serve
    replicas): counters sum, histograms merge bucket-by-bucket — the
    merged p99 is computed over EVERY replica's samples, not a max of
    per-replica quantiles — and ledgers sum into one roofline/goodput
    view.  Consumed by bench_serve's headline math and anything wanting
    one number for the fleet."""
    snaps = [s for s in snapshots.values() if s]
    out: Dict[str, Any] = {"engines": len(snaps)}
    for key in ("num_slots", "queue_depth", "slot_occupancy",
                "requests_submitted", "requests_rejected",
                "requests_completed", "tokens_emitted",
                "deadline_expired"):
        out[key] = sum(int(s.get(key, 0)) for s in snaps)
    out["tokens_per_s"] = sum(float(s.get("tokens_per_s", 0.0))
                              for s in snaps)
    out["ttft_s"] = merge_summaries([s.get("ttft_s") or {} for s in snaps])
    out["step_latency_s"] = merge_summaries(
        [s.get("step_latency_s") or {} for s in snaps])
    prio: Dict[str, Any] = {}
    for p in PRIORITIES:
        entries = [(s.get("priority") or {}).get(p) or {} for s in snaps]
        prio[p] = {
            "submitted": sum(int(e.get("submitted", 0)) for e in entries),
            "shed": sum(int(e.get("shed", 0)) for e in entries),
            "quota_shed": sum(int(e.get("quota_shed", 0))
                              for e in entries),
            "queue_depth": sum(int(e.get("queue_depth", 0))
                               for e in entries),
            "ttft_s": merge_summaries([e.get("ttft_s") or {}
                                       for e in entries]),
        }
    out["priority"] = prio
    perfs = [s.get("perf") for s in snaps if s.get("perf")]
    if perfs:
        out["perf"] = merge_ledger_snapshots(perfs)
    tens = [s.get("tenants") for s in snaps if s.get("tenants")]
    if tens:
        # fleet per-tenant usage: counters sum across replicas (the cost
        # ledger differences the merged cumulative view per interval)
        tenants: Dict[str, Dict[str, float]] = {}
        for t in tens:
            for tenant, counters in t.items():
                agg = tenants.setdefault(tenant, {})
                for k, v in counters.items():
                    agg[k] = agg.get(k, 0) + v
        out["tenants"] = tenants
    migs = [s.get("migrations") for s in snaps if s.get("migrations")]
    if migs:
        keys = sorted(set().union(*migs))
        out["migrations"] = {
            k: sum(int(m.get(k, 0)) for m in migs) for k in keys}
    ws = [s.get("weights") for s in snaps if s.get("weights")]
    if ws:
        # fleet view: swaps/rollbacks sum, the serving version is the max
        # (mid-promotion the fleet is legitimately mixed), stall is the
        # worst replica's worst swap — the number bench_serve headlines
        out["weights"] = {
            "version": max(int(w.get("version", 0)) for w in ws),
            "swaps": sum(int(w.get("swaps", 0)) for w in ws),
            "rollbacks": sum(int(w.get("rollbacks", 0)) for w in ws),
            "max_stall_ms": max(float(w.get("max_stall_ms", 0.0))
                                for w in ws),
            "adapters_loaded": sum(int(w.get("adapters_loaded", 0))
                                   for w in ws),
        }
    return out


# -- prometheus exposition ---------------------------------------------------

_FAMILIES = [
    # (family, type, help)
    ("tpu_air_engine_queue_depth", "gauge", "admission queue depth"),
    ("tpu_air_engine_slot_occupancy", "gauge", "occupied decode slots"),
    ("tpu_air_engine_requests_submitted", "counter", "requests accepted"),
    ("tpu_air_engine_requests_rejected", "counter",
     "requests shed under backpressure"),
    ("tpu_air_engine_requests_completed", "counter", "requests retired"),
    ("tpu_air_engine_deadline_expired", "counter",
     "queued requests swept past their absolute deadline (served as 504)"),
    ("tpu_air_engine_tokens_emitted", "counter", "tokens streamed out"),
    ("tpu_air_engine_tokens_per_s", "gauge",
     "emitted tokens/s over the rate window"),
    ("tpu_air_engine_ttft_s", "histogram",
     "time to first token, seconds (log buckets, trace exemplars)"),
    ("tpu_air_engine_ttft_s_p50", "gauge", "TTFT p50 seconds"),
    ("tpu_air_engine_ttft_s_p95", "gauge", "TTFT p95 seconds"),
    ("tpu_air_engine_ttft_s_p99", "gauge", "TTFT p99 seconds"),
    ("tpu_air_engine_step_latency_s", "histogram",
     "pool decode step wall time, seconds"),
    ("tpu_air_engine_step_latency_s_p50", "gauge",
     "decode step p50 seconds"),
    ("tpu_air_engine_step_latency_s_p95", "gauge",
     "decode step p95 seconds"),
    ("tpu_air_engine_draining", "gauge",
     "1 while the engine refuses new submissions"),
    ("tpu_air_engine_priority_submitted", "counter",
     "requests accepted per priority class"),
    ("tpu_air_engine_priority_shed", "counter",
     "requests shed per priority class"),
    ("tpu_air_engine_priority_quota_shed", "counter",
     "requests shed by per-tenant quotas per priority class (HTTP 429)"),
    ("tpu_air_engine_priority_queue_depth", "gauge",
     "queued requests per priority class"),
    ("tpu_air_engine_priority_ttft_s", "histogram",
     "per-priority-class TTFT seconds"),
    ("tpu_air_engine_priority_ttft_s_p50", "gauge",
     "per-class TTFT p50 seconds"),
    ("tpu_air_engine_priority_ttft_s_p99", "gauge",
     "per-class TTFT p99 seconds"),
    ("tpu_air_engine_reordered_admits", "counter",
     "admissions taken out of FIFO order"),
    ("tpu_air_engine_prefill_chunks", "counter",
     "prefill chunk programs executed"),
    ("tpu_air_engine_roofline_fraction", "gauge",
     "achieved fraction of the analytic roofline (perf ledger totals)"),
    ("tpu_air_engine_flops_per_s", "gauge",
     "achieved model flops/s (analytic cost over measured wall time)"),
    ("tpu_air_engine_hbm_bytes_per_s", "gauge",
     "achieved HBM bytes/s (analytic cost over measured wall time)"),
    ("tpu_air_engine_program_roofline_fraction", "gauge",
     "per compiled-program roofline fraction"),
    ("tpu_air_engine_goodput_ratio", "gauge",
     "useful / (useful + wasted) emitted tokens"),
    ("tpu_air_engine_tokens_useful", "counter",
     "tokens retired on streams that completed normally"),
    ("tpu_air_engine_tokens_wasted", "counter",
     "tokens whose work was wasted, by category"),
    # live-weight plane (serve/weights.py): absent until an engine swaps
    ("tpu_air_weights_version", "gauge",
     "weight-store version currently serving"),
    ("tpu_air_weights_swaps", "counter", "live weight swaps applied"),
    ("tpu_air_weights_rollbacks", "counter",
     "swaps that restored the prior version (canary gate failures)"),
    ("tpu_air_weights_swap_stall_ms", "gauge",
     "decode-step gap of the most recent swap, milliseconds"),
    ("tpu_air_weights_swap_stall_ms_max", "gauge",
     "worst decode-step gap across all swaps, milliseconds"),
    ("tpu_air_weights_adapters_loaded", "gauge",
     "tenant LoRA adapters resident in the bank"),
    # preemption migration plane: absent until an engine migrates
    ("tpu_air_engine_migrations", "counter",
     "live slots migrated, by direction (out = extracted off a "
     "preempting replica, in = landed here)"),
    ("tpu_air_engine_migrated_pages", "counter",
     "KV pages shipped by live-slot migration, by direction"),
    ("tpu_air_engine_migration_reprefill_chunks", "counter",
     "prefill chunk programs a migration landing had to re-run "
     "(zero-re-prefill contract: stays 0)"),
]


def prometheus_lines(snapshots: Dict[str, Dict[str, Any]] = None) -> list:
    """Engine gauges in prometheus text form (dashboard /metrics), one
    ``# HELP``/``# TYPE`` header per family, histogram families with full
    ``_bucket``/``_sum``/``_count`` series and OpenMetrics exemplars.

    ``snapshots`` defaults to this process's registry; the dashboard passes
    a merged dict that also folds in serve-replica snapshots (keys there are
    ``deployment/replica/engine`` paths — label values, so any charset is
    fine after quote-escaping)."""
    if snapshots is None:
        snapshots = snapshot_all()
    b = ExpositionBuilder()
    for fam, mtype, help_text in _FAMILIES:
        b.declare(fam, mtype, help_text)
    kvpool_declared = set()
    topo_declared = set()
    for name, snap in sorted(snapshots.items()):
        if not snap:
            continue
        label = name.replace("\\", "\\\\").replace('"', '\\"')
        tag = f'{{engine="{label}"}}'
        for key in ("queue_depth", "slot_occupancy", "requests_submitted",
                    "requests_rejected", "requests_completed",
                    "deadline_expired", "tokens_emitted"):
            if key in snap:
                b.raw(f"tpu_air_engine_{key}",
                      f"tpu_air_engine_{key}{tag} {snap[key]}")
        if "tokens_per_s" in snap:
            b.raw("tpu_air_engine_tokens_per_s",
                  f"tpu_air_engine_tokens_per_s{tag} "
                  f"{snap['tokens_per_s']:.3f}")
        for dist_key, quantiles in (("ttft_s", ("p50", "p95", "p99")),
                                    ("step_latency_s", ("p50", "p95"))):
            d = snap.get(dist_key) or {}
            if not d.get("count"):
                continue
            fam = f"tpu_air_engine_{dist_key}"
            for q in quantiles:
                if q in d:
                    b.raw(f"{fam}_{q}", f"{fam}_{q}{tag} {d[q]:.6f}")
            if d.get("buckets"):
                b.histogram(fam, {"engine": name},
                            cumulative_from_summary(d),
                            int(d["count"]), float(d.get("sum", 0.0)))
        # paged-KV pool gauges (absent on slab engines)
        for key, val in sorted((snap.get("kvpool") or {}).items()):
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            fam = f"tpu_air_engine_kvpool_{key}"
            if fam not in kvpool_declared:
                b.declare(fam, "gauge", f"paged KV pool: {key}")
                kvpool_declared.add(fam)
            b.raw(fam, f"{fam}{tag} {val:g}")
        for key in ("reordered_admits", "prefill_chunks"):
            if key in snap:
                b.raw(f"tpu_air_engine_{key}",
                      f"tpu_air_engine_{key}{tag} {snap[key]}")
        if "draining" in snap:
            b.raw("tpu_air_engine_draining",
                  f"tpu_air_engine_draining{tag} "
                  f"{int(bool(snap['draining']))}")
        # per-priority-class counters/gauges ({engine=...,priority=...})
        for prio, pc in sorted((snap.get("priority") or {}).items()):
            ptag = f'{{engine="{label}",priority="{prio}"}}'
            for key in ("submitted", "shed", "quota_shed", "queue_depth"):
                if key in pc:
                    b.raw(f"tpu_air_engine_priority_{key}",
                          f"tpu_air_engine_priority_{key}{ptag} {pc[key]}")
            d = pc.get("ttft_s") or {}
            if d.get("count"):
                b.raw("tpu_air_engine_priority_ttft_s_p50",
                      f"tpu_air_engine_priority_ttft_s_p50{ptag} "
                      f"{d['p50']:.6f}")
                b.raw("tpu_air_engine_priority_ttft_s_p99",
                      f"tpu_air_engine_priority_ttft_s_p99{ptag} "
                      f"{d['p99']:.6f}")
                if d.get("buckets"):
                    b.histogram("tpu_air_engine_priority_ttft_s",
                                {"engine": name, "priority": prio},
                                cumulative_from_summary(d),
                                int(d["count"]), float(d.get("sum", 0.0)))
        # perf ledger: roofline totals, per-program fractions, goodput
        perf = snap.get("perf") or {}
        totals = perf.get("totals") or {}
        if totals.get("seconds"):
            b.raw("tpu_air_engine_roofline_fraction",
                  f"tpu_air_engine_roofline_fraction{tag} "
                  f"{totals['roofline_fraction']:.6f}")
            b.raw("tpu_air_engine_flops_per_s",
                  f"tpu_air_engine_flops_per_s{tag} "
                  f"{totals['flops_per_s']:.6g}")
            b.raw("tpu_air_engine_hbm_bytes_per_s",
                  f"tpu_air_engine_hbm_bytes_per_s{tag} "
                  f"{totals['bytes_per_s']:.6g}")
            for kind, p in sorted((perf.get("programs") or {}).items()):
                b.raw("tpu_air_engine_program_roofline_fraction",
                      f"tpu_air_engine_program_roofline_fraction"
                      f'{{engine="{label}",program="{kind}"}} '
                      f"{p['roofline_fraction']:.6f}")
        goodput = perf.get("goodput") or {}
        if goodput.get("total"):
            b.raw("tpu_air_engine_goodput_ratio",
                  f"tpu_air_engine_goodput_ratio{tag} "
                  f"{goodput['goodput_ratio']:.6f}")
            b.raw("tpu_air_engine_tokens_useful",
                  f"tpu_air_engine_tokens_useful{tag} "
                  f"{goodput.get('useful', 0)}")
            for cat, n in sorted(goodput.items()):
                if cat in ("total", "wasted", "useful", "goodput_ratio"):
                    continue
                b.raw("tpu_air_engine_tokens_wasted",
                      f"tpu_air_engine_tokens_wasted"
                      f'{{engine="{label}",category="{cat}"}} {n}')
        # live-weight plane gauges (absent on engines that never swapped)
        w = snap.get("weights") or {}
        for skey, fam in (("version", "tpu_air_weights_version"),
                          ("swaps", "tpu_air_weights_swaps"),
                          ("rollbacks", "tpu_air_weights_rollbacks"),
                          ("adapters_loaded",
                           "tpu_air_weights_adapters_loaded")):
            if skey in w:
                b.raw(fam, f"{fam}{tag} {int(w[skey])}")
        if "last_stall_ms" in w:
            b.raw("tpu_air_weights_swap_stall_ms",
                  f"tpu_air_weights_swap_stall_ms{tag} "
                  f"{float(w['last_stall_ms']):.3f}")
        if "max_stall_ms" in w:
            b.raw("tpu_air_weights_swap_stall_ms_max",
                  f"tpu_air_weights_swap_stall_ms_max{tag} "
                  f"{float(w['max_stall_ms']):.3f}")
        # preemption migration counters (absent until an engine migrates)
        mg = snap.get("migrations") or {}
        for direction in ("out", "in"):
            if direction in mg:
                dtag = f'{{engine="{label}",direction="{direction}"}}'
                b.raw("tpu_air_engine_migrations",
                      f"tpu_air_engine_migrations{dtag} {int(mg[direction])}")
                b.raw("tpu_air_engine_migrated_pages",
                      f"tpu_air_engine_migrated_pages{dtag} "
                      f"{int(mg.get(direction + '_pages', 0))}")
        if "in_reprefill_chunks" in mg:
            b.raw("tpu_air_engine_migration_reprefill_chunks",
                  f"tpu_air_engine_migration_reprefill_chunks{tag} "
                  f"{int(mg['in_reprefill_chunks'])}")
        # topology: strings fold into one info line's labels, numbers
        # (replica counts, device counts) become gauges
        topo = snap.get("topology") or {}
        if topo:
            info = [f'engine="{label}"']
            for key, val in sorted(topo.items()):
                # keys become metric-name / label-name fragments: sanitize.
                # values are label VALUES — any charset, quote-escape only
                skey = sanitize_metric_name(str(key))
                if isinstance(val, bool) or not isinstance(val, (int, float)):
                    sval = str(val).replace("\\", "\\\\").replace('"', '\\"')
                    info.append(f'{skey}="{sval}"')
                else:
                    fam = f"tpu_air_engine_topology_{skey}"
                    if fam not in topo_declared:
                        b.declare(fam, "gauge", f"engine topology: {skey}")
                        topo_declared.add(fam)
                    b.raw(fam, f"{fam}{tag} {val:g}")
            fam = "tpu_air_engine_topology_info"
            if fam not in topo_declared:
                b.declare(fam, "gauge",
                          "engine placement metadata as labels")
                topo_declared.add(fam)
            b.raw(fam, "tpu_air_engine_topology_info{"
                  + ",".join(info) + "} 1")
    return b.lines()
