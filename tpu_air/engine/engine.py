"""The continuous-batching inference engine.

One :class:`InferenceEngine` owns a fixed pool of ``S`` sequence slots and
keeps a single persistent jit-compiled decode step alive over that pool for
its whole lifetime (the cache is donated — device KV updates in place,
never copied).  Two KV layouts share the host loop (``EngineConfig.kv_mode``):

* **paged** (default) — per-layer page pools ``[P, page_len, h*d]`` plus a
  host block table mapping slot positions onto refcounted pages
  (engine/kvpool/).  Prompts prefill in page-sized CHUNKS interleaved
  between decode steps (one compiled chunk program covers every prompt
  length); prompts sharing a cached prefix skip the covered chunks and
  share the physical pages, copy-on-write on the first divergent append.
* **slab** — the PR 1 layout: one private ``[slot_len]`` KV row per slot,
  whole-prompt bucketed prefill.  Kept as the bench baseline and for the
  T5 window engine.

Requests flow through three host-side phases BETWEEN device steps:

1. **admission** — FIFO from the scheduler queue (paged: gated on KV-page
   capacity with a bounded reorder window so a big blocked head can't
   starve small requests behind it).
2. **prefill** — slab: one bucketed B=1 prefill per request, grafted into
   the slab row; paged: up to ``prefill_chunks_per_step`` chunk calls per
   engine step, shortest-remaining-prompt first, so short-request TTFT
   stays flat while long prompts stream in.
3. **decode + retirement** — one fixed-shape step over all ``S`` rows;
   a row that emits EOS (inclusive) or exhausts its budget is released on
   the next host visit (paged: its private pages return to the free list;
   its prompt's pages stay resident in the prefix cache for future hits).

Correctness anchor: with greedy decoding the engine's emitted tokens are
token-identical to offline ``generate()`` on the same prompts — in BOTH
kv modes — tests/test_engine.py pins this on CPU for burst, staggered and
trickle arrival schedules.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from tpu_air.models.lm.generate import (
    init_paged_cache,
    init_slot_cache,
    make_lm_decode_step_fn,
    make_lm_paged_decode_step_fn,
    make_lm_prefill_chunk_fn,
    make_lm_prefill_fn,
    make_page_copy_fn,
)

from tpu_air.faults import plan as _faults
from tpu_air.observability import tracing as _tracing
from tpu_air.observability import perf as _perf

from .kvpool import PagedKVPool
from .metrics import EngineMetrics, unregister
from .scheduler import Scheduler
from .slots import Slot, SlotManager, make_insert_fn
from .types import (
    PRIORITIES,
    EngineClosedError,
    EngineConfig,
    EngineDrainingError,
    EngineOverloadedError,
    Request,
    RequestValidationError,
    ResponseStream,
)


class InferenceEngine:
    """Slot-pool online inference over a causal LM.

    ``submit`` is thread-safe and non-blocking (raises
    :class:`EngineOverloadedError` under backpressure); tokens stream back
    on the returned :class:`ResponseStream` as they are decoded.  With
    ``auto_start=True`` (the default) a daemon thread drives the step loop;
    ``auto_start=False`` hands the loop to the caller via :meth:`step` —
    the deterministic mode the parity tests drive.
    """

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 *, auto_start: bool = True, name: str = "engine"):
        self.model = model
        self.params = params
        self.config = config or EngineConfig()
        self.name = name
        cfg = self.config
        if cfg.eos_token_id == "model":
            self.eos_token_id = model.config.eos_token_id
        else:
            self.eos_token_id = cfg.eos_token_id
        if cfg.slot_len > model.config.max_seq_len:
            raise ValueError(
                f"slot_len {cfg.slot_len} exceeds the model's max_seq_len "
                f"{model.config.max_seq_len}"
            )
        if cfg.kv_mode not in ("paged", "slab"):
            raise ValueError(f"unknown kv_mode {cfg.kv_mode!r}")
        self.paged = cfg.kv_mode == "paged"
        self.adapters_enabled = cfg.adapter_slots > 0
        if self.adapters_enabled and not self.paged:
            raise ValueError(
                "adapter_slots requires the paged engine (kv_mode='paged')")

        # device side: the persistent donated KV pool + compiled phases
        # (subclasses override the builders — MeshEngine swaps in a sharded
        # pool/cache and pjit-wrapped step fns, same host loop)
        if self.paged:
            self._build_paged_state()
        else:
            self._build_slab_state()

        # host side: authoritative per-slot state the step args come from
        self._cur_tok = np.zeros((cfg.num_slots,), np.int32)
        self._pos = np.zeros((cfg.num_slots,), np.int32)
        self._round_reserved = 0   # pages promised during one admission round
        self._chunks_run = 0       # prefill chunk calls, engine lifetime

        self.scheduler = Scheduler(cfg)
        self.slots = SlotManager(cfg.num_slots)
        self.metrics = EngineMetrics(name=name, num_slots=cfg.num_slots)
        # airscope: analytic flops/bytes per compiled program, fed into the
        # metrics ledger with each program's measured wall time.  The
        # decode-step cost is a CONSTANT — the fixed-shape step attends the
        # full compiled context for every slot regardless of occupancy, so
        # it is priced once at the compiled shape (S rows × slot_len).
        # Geometry-gated: the decoder-only formulas only apply to configs
        # exposing the LM geometry (T5's window engine skips the ledger).
        mc = self.model.config
        if all(hasattr(mc, a) for a in ("d_model", "n_layers", "n_heads",
                                        "head_dim", "d_ff", "vocab_size")):
            self._cost_model: Optional[Any] = _perf.LMCostModel(mc)
            self._decode_cost = self._cost_model.decode_step_cost(
                cfg.num_slots, cfg.slot_len)
        else:
            self._cost_model = None
            self._decode_cost = None

        # live-weight swap state (serve/weights.py): the version currently
        # serving plus the PRIOR device tree — rollback never touches the
        # store, so it survives a corrupted/GC'd publish.  Doubles weight
        # memory while a prior version is retained — the price of instant
        # rollback, documented in docs/SERVING.md.
        self._weights_version: Optional[int] = None
        self._prev_params: Any = None
        self._prev_version: Optional[int] = None

        # multi-tenant LoRA: name -> bank row map (row 0 = zero adapter)
        # and the host per-slot row table the decode step gathers from.
        # Lock order: _step_lock OUTER, _adapter_lock inner.
        self._adapter_rows: Dict[str, int] = {}
        self._adapter_lock = threading.Lock()
        self._adapter_ids_host = np.zeros((cfg.num_slots,), np.int32)

        self._next_request_id = 0
        self._id_lock = threading.Lock()
        self._step_lock = threading.Lock()
        self._closed = False
        self._draining = False
        self._preempting = False
        self._round_admits = 0  # slots taken during one admission round
        self._thread: Optional[threading.Thread] = None
        if auto_start:
            self.start()

    # -- device-state builders (overridden by engine/dist MeshEngine) --------
    def _build_paged_state(self) -> None:
        cfg = self.config
        self.pool = PagedKVPool(
            cfg.pool_pages(), cfg.page_len, cfg.num_slots,
            cfg.pages_per_slot(), prefix_cache=cfg.prefix_cache,
        )
        self.cache = init_paged_cache(
            self.model, cfg.num_slots, cfg.pool_pages(), cfg.page_len,
            cfg.pages_per_slot(),
        )
        self._decode_step = make_lm_paged_decode_step_fn(
            self.model, cfg.slot_len, adapters=self.adapters_enabled)
        self._chunk_fn = make_lm_prefill_chunk_fn(
            self.model, cfg.page_len, cfg.slot_len,
            adapters=self.adapters_enabled)
        self._copy_fn = make_page_copy_fn()
        if self.adapters_enabled:
            mc = self.model.config
            A, r = cfg.adapter_slots, cfg.adapter_rank
            # resident LoRA bank: row 0 is the pinned zero adapter, so
            # base-model slots gather an exact-zero delta (greedy parity)
            self._adapter_a = jnp.zeros((A + 1, mc.d_model, r), jnp.float32)
            self._adapter_b = jnp.zeros((A + 1, r, mc.vocab_size),
                                        jnp.float32)

    def _build_slab_state(self) -> None:
        cfg = self.config
        self.pool = None
        self.cache = init_slot_cache(self.model, cfg.num_slots, cfg.slot_len)
        self._decode_step = make_lm_decode_step_fn(self.model, cfg.slot_len)
        self._insert = make_insert_fn()
        self._prefill_fns: Dict[int, Any] = {}  # bucket -> compiled

    # -- submission (any thread) ---------------------------------------------
    def _make_request(self, prompt, max_new_tokens, stream,
                      priority: str = "interactive", *,
                      admit_while_draining: bool = False,
                      deadline_ms: Optional[float] = None,
                      adapter_id: Optional[str] = None,
                      tenant: Optional[str] = None) -> Request:
        """Shared validation + Request construction for both submit paths.

        ``admit_while_draining`` is the disaggregated-handoff escape hatch:
        a ``submit_prefilled`` payload was ADMITTED at the router before the
        drain began — refusing it here would drop work the caller already
        streamed a first token for."""
        # airlint: disable=CC001 — _closed/_draining are GIL-atomic
        # monotonic bools (False→True once); a submit racing the flip is
        # indistinguishable from one that arrived a moment earlier
        if self._closed:
            raise EngineClosedError("engine is shut down")
        # airlint: disable=CC001 — same monotonic-flag discipline as _closed
        if self._draining and not admit_while_draining:
            raise EngineDrainingError(
                f"engine {self.name!r} is draining; submit elsewhere")
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r} (expected one of {PRIORITIES})"
            )
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        budget = (self.config.max_new_tokens if max_new_tokens is None
                  else int(max_new_tokens))
        if budget < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {budget}")
        if len(prompt) + budget > self.config.slot_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({budget}) "
                f"exceeds slot_len ({self.config.slot_len})"
            )
        if adapter_id is not None:
            # fail fast at submit (the proxy maps RequestValidationError to
            # HTTP 400, unlike a plain replica-side ValueError which stays
            # 500); admission re-resolves — the adapter may be evicted
            # meanwhile
            if not self.adapters_enabled:
                raise RequestValidationError(
                    "adapter_id requires EngineConfig.adapter_slots > 0")
            with self._adapter_lock:
                if adapter_id not in self._adapter_rows:
                    raise RequestValidationError(
                        f"unknown adapter {adapter_id!r}")
        with self._id_lock:
            rid = self._next_request_id
            self._next_request_id += 1
        return Request(request_id=rid, prompt=prompt, max_new_tokens=budget,
                       stream=stream if stream is not None
                       else ResponseStream(rid),
                       priority=priority,
                       deadline_ms=(None if deadline_ms is None
                                    else float(deadline_ms)),
                       adapter_id=adapter_id,
                       tenant=(str(tenant) if tenant else None))

    def _enqueue(self, req: Request) -> ResponseStream:
        try:
            self.scheduler.submit(req)
        except EngineOverloadedError:  # backpressure: count the 503, surface it
            self.metrics.record_reject(req.priority)
            raise
        self.metrics.record_submit(req.priority)
        return req.stream

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None, *,
               priority: str = "interactive",
               stream: Optional[ResponseStream] = None,
               deadline_ms: Optional[float] = None,
               adapter_id: Optional[str] = None,
               tenant: Optional[str] = None) -> ResponseStream:
        """Queue one prompt; returns its token stream immediately.

        ``priority`` is the request's SLO class (``types.PRIORITIES``):
        admission pops interactive first each step, and under backpressure
        best-effort sheds at half the queue depth interactive does.
        ``stream`` lets a front-end that already handed a stream to its
        caller (the disagg router's prefill-fallback path) have the engine
        emit onto it instead of minting a fresh one.  ``deadline_ms`` is
        the request's ABSOLUTE end-to-end deadline (unix-epoch ms): still
        queued past it, the request expires with
        :class:`~tpu_air.faults.retry.DeadlineExceededError` instead of
        occupying a slot it can no longer use.  ``adapter_id`` selects the
        tenant LoRA adapter the request decodes under (None = base model;
        unknown/unloaded names raise ValueError here).  ``tenant`` is the
        pure cost-attribution label (never validated): airwatch bills
        ``tenant or adapter_id`` — the batch lane stamps
        ``batch:<job_id>`` so offline rows never fold into "default"."""
        return self._enqueue(self._make_request(prompt, max_new_tokens,
                                                stream, priority,
                                                deadline_ms=deadline_ms,
                                                adapter_id=adapter_id,
                                                tenant=tenant))

    def submit_prefilled(self, prompt: Sequence[int], first_token: int,
                         kv_pages: Dict[str, Any],
                         max_new_tokens: Optional[int] = None, *,
                         priority: str = "interactive",
                         stream: Optional[ResponseStream] = None,
                         deadline_ms: Optional[float] = None
                         ) -> ResponseStream:
        """Queue a request whose prefill ALREADY RAN elsewhere (a
        PrefillWorker replica — engine/dist/): ``kv_pages`` is the
        extract_kv_pages payload covering the whole prompt and
        ``first_token`` the prefill's greedy first token.  Admission
        allocates unshared pages, inserts the shipped K/V, emits
        ``first_token`` and goes straight to decode — same capacity gate
        and deferral as a normal submit, so pool exhaustion queues the
        handoff instead of dropping it."""
        if not self.paged:
            raise ValueError(
                "submit_prefilled requires a paged engine (kv_mode='paged')")
        # a handoff rides through a drain: the router admitted it before the
        # drain started and its prefill already ran on another replica
        req = self._make_request(prompt, max_new_tokens, stream, priority,
                                 admit_while_draining=True,
                                 deadline_ms=deadline_ms)
        req.prefilled = {"first_token": int(first_token), "pages": kv_pages}
        return self._enqueue(req)

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[List[int]]:
        """Blocking convenience: submit every prompt, join every stream.
        In manual mode (no background thread) it drives :meth:`step`."""
        streams = [self.submit(p, max_new_tokens) for p in prompts]
        if self._thread is None:
            while not self.idle():
                self.step()
        return [s.result(timeout) for s in streams]

    # -- the engine loop -----------------------------------------------------
    def step(self) -> bool:
        """One deterministic engine iteration: admit into free slots, run
        the prefill quantum (paged), then one pool decode step if anything
        is decoding.  Returns True if any work happened (callers loop
        ``while engine.step(): ...`` to drain)."""
        with self._step_lock:
            worked = False
            self._begin_admission_round()
            self._round_admits = 0
            # airlint: disable=CC001 — _preempting is a GIL-atomic
            # monotonic bool (False→True once); an admission round racing
            # the flip just admits one last batch before the freeze
            if not self._preempting:
                for req in self.scheduler.pop_admissible(
                    self.slots.free_count(), self._admit_gate()
                ):
                    if self.paged:
                        self._admit_paged(req)
                    else:
                        self._admit(req)
                    worked = True
            if self.paged and self._prefill_quantum():
                worked = True
            if any(not s.prefilling for s in self.slots.active_slots()):
                self._decode_all()
                worked = True
            gauges: Dict[str, Any] = {}
            if self.paged:
                gauges = dict(
                    kvpool=self.pool.stats(),
                    reordered_admits=self.scheduler.reordered_admits,
                    prefill_chunks=self._chunks_run,
                )
            self.metrics.observe_gauges(
                self.scheduler.depth(), self.slots.occupancy(),
                queue_by_class=self.scheduler.depth_by_class(),
                draining=self._draining,
                deadline_expired=self.scheduler.deadline_expired,
                **gauges
            )
            return worked

    def idle(self) -> bool:
        return self.scheduler.depth() == 0 and self.slots.occupancy() == 0

    # -- draining (zero-downtime rollout / scale-down) ------------------------
    def drain(self) -> None:
        """Stop admitting NEW submissions; everything already queued or in a
        slot retires normally (streaming untouched).  The deployment calls
        this before swapping/killing a replica; :meth:`drained` answers when
        the swap may proceed.  Idempotent; :meth:`close` is still required
        to stop the loop."""
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        """True once draining AND no admitted work remains."""
        return self._draining and self.idle()

    # -- preemption (lease revoked with notice) -------------------------------
    def preempt(self) -> None:
        """A lease-revocation notice arrived: stop admitting ANYTHING.
        New submits shed (:class:`EngineDrainingError` — the proxy routes
        elsewhere) and the already-queued backlog STAYS queued — unlike a
        rollout drain, prefilling it here would burn the notice window on
        work this replica cannot finish; the journal replays it on a
        survivor once the replica goes away.  Idempotent."""
        self._draining = True
        # airlint: disable=CC001 — GIL-atomic monotonic flip, never unset
        self._preempting = True

    @property
    def preempting(self) -> bool:
        # airlint: disable=CC001 — GIL-atomic monotonic bool read
        return self._preempting

    def migrate_out(self) -> List[Dict[str, Any]]:
        """Preemption drain: freeze the loop between steps and pull every
        DECODING slot's live state into portable payloads for
        :meth:`submit_migrated` on a survivor.

        Each payload carries everything the destination needs to continue
        the stream exactly: the original prompt, every client-visible
        token emitted so far, the decode cursor, the remaining budget, the
        SLO class/deadline/tenant, and the KV pages covering positions
        ``0..pos-1`` (:func:`extract_kv_pages`).  Mid-prefill slots and
        the queued backlog are NOT shipped — their cheapest recovery is
        the journal-replay fallback, since little or none of their compute
        exists yet.  Migrated slots are released here (the destination
        owns the stream's future); their source streams are abandoned
        unfinished, and the proxy re-pins pollers at the destination.
        """
        if not self.paged:
            raise ValueError(
                "migrate_out requires a paged engine (kv_mode='paged')")
        self.preempt()
        from .dist.kv_transfer import extract_kv_pages  # lazy: avoids cycle

        payloads: List[Dict[str, Any]] = []
        with self._step_lock:
            for slot in list(self.slots.active_slots()):
                if slot.prefilling:
                    continue
                req = slot.request
                p = int(slot.pos)
                page_ids = self.pool.prompt_page_ids(slot.index, p)
                # airlint: disable=CC003 — the only sleep reachable here is
                # a test-only fault-injection delay; the loop is frozen by
                # design while live state is pulled
                pages = extract_kv_pages(self.cache, page_ids)
                payloads.append({
                    "request_id": req.request_id,
                    "prompt": [int(t) for t in req.prompt],
                    "streamed": req.stream.tokens_so_far(),
                    "pos": p,
                    "budget_left": int(slot.budget_left),
                    "priority": req.priority,
                    "deadline_ms": req.deadline_ms,
                    "adapter_id": req.adapter_id,
                    "tenant": req.tenant,
                    "pages": pages,
                })
                self.metrics.record_migration("out", len(page_ids))
                # every token this stream emitted stays useful — the
                # destination continues it, so nothing here is waste and
                # the slot is released without finishing the stream
                self.pool.release(slot.index)
                self.slots.release(slot)
                self._cur_tok[slot.index] = 0
                self._pos[slot.index] = 0
                self._adapter_ids_host[slot.index] = 0
        return payloads

    def submit_migrated(self, payload: Dict[str, Any], *,
                        stream: Optional[ResponseStream] = None
                        ) -> ResponseStream:
        """Land one :meth:`migrate_out` payload on this engine.

        Validates the shipped pages against this cache's geometry BEFORE
        queueing (:class:`~tpu_air.engine.dist.kv_transfer.KVTransferError`
        surfaces synchronously so the supervisor can fall back to replay),
        then admission allocates unshared pages, inserts the K/V, replays
        the already-delivered tokens onto the fresh stream, and decode
        continues from the exact cursor — zero prefill chunks run, and
        greedy continuations are token-identical to the stream never
        having moved."""
        if not self.paged:
            raise ValueError(
                "submit_migrated requires a paged engine (kv_mode='paged')")
        from .dist.kv_transfer import validate_kv_payload  # lazy: no cycle

        prompt = [int(t) for t in payload["prompt"]]
        streamed = [int(t) for t in payload["streamed"]]
        pos = int(payload["pos"])
        budget_left = int(payload["budget_left"])
        if not streamed or budget_left < 1 \
                or pos != len(prompt) + len(streamed) - 1:
            raise RequestValidationError(
                f"inconsistent migration payload: prompt={len(prompt)} "
                f"streamed={len(streamed)} pos={pos} "
                f"budget_left={budget_left}")
        n_pages = -(-pos // self.config.page_len)
        # airlint: disable=CC001 — geometry-only read; the cache is rebound
        # under _step_lock but every rebinding preserves layout, so a stale
        # reference validates identically
        validate_kv_payload(self.cache, range(n_pages), payload["pages"])
        # the cache-resident context is positions 0..pos-1: the prompt plus
        # every emitted token but the last (the cursor token is computed,
        # not yet written) — that context is the "prompt" the pool admits
        context = (prompt + streamed)[:pos]
        req = self._make_request(context, budget_left + 1, stream,
                                 payload.get("priority", "interactive"),
                                 admit_while_draining=True,
                                 deadline_ms=payload.get("deadline_ms"),
                                 adapter_id=payload.get("adapter_id"),
                                 tenant=payload.get("tenant"))
        req.migrated = {"streamed": streamed, "pages": payload["pages"],
                        "client_prompt_len": len(prompt)}
        return self._enqueue(req)

    def _admit_gate(self):
        """Per-round admission predicate handed to the scheduler.  Combines
        the paged page-capacity gate with the interactive slot reserve
        (``EngineConfig.reserved_interactive_slots``): a non-interactive
        request may only take a slot while MORE than ``reserved`` slots
        would stay free after this round's takes — so a lower-class burst
        can never occupy the whole pool and an arriving interactive request
        admits immediately.  Returns None (no gate — the scheduler's pure
        pop) when neither applies, preserving the slab fast path exactly."""
        page_gate = self._can_admit if self.paged else None
        reserved = self.config.reserved_interactive_slots
        if reserved <= 0:
            return page_gate

        def gate(req: Request) -> bool:
            if req.priority != "interactive" and (
                self.slots.free_count() - self._round_admits <= reserved
            ):
                return False
            if page_gate is not None and not page_gate(req):
                return False
            self._round_admits += 1
            return True

        return gate

    # -- paged admission -----------------------------------------------------
    def _begin_admission_round(self) -> None:
        """Reset per-round reservation state before ``pop_admissible``
        probes the queue (the MeshEngine override tracks reservations PER
        dp REPLICA, simulating which replica each admit will land in)."""
        self._round_reserved = 0

    def _can_admit(self, req: Request) -> bool:
        """Page-capacity gate for the scheduler: answers whether the pool
        can cover the request's WORST CASE (no prefix sharing — a prior
        admit's eviction may invalidate a probe-time match, and shared
        pages stop being evictable, so the conservative bound is exactly
        what one round can consume).  A True answer RESERVES the pages for
        the rest of the round."""
        need = self.pool.worst_case_pages(len(req.prompt), req.max_new_tokens)
        if self.slots.free_count() == 0:
            return False
        if self._round_reserved + need > self.pool.capacity():
            return False
        self._round_reserved += need
        return True

    def _admit_paged(self, req: Request) -> None:
        """Reserve pages + block-table row; actual compute happens in the
        chunked prefill quantum (no first token yet — TTFT lands when the
        final chunk runs).  A request carrying shipped KV pages skips the
        chunk phase entirely (prefill already ran on another replica)."""
        if not self._resolve_adapter(req):
            return
        slot = self.slots.acquire()
        slot.request = req
        self._adapter_ids_host[slot.index] = req.adapter_row
        if req.prefilled is not None:
            self._admit_prefilled(slot, req)
            return
        if req.migrated is not None:
            self._admit_migrated(slot, req)
            return
        slot.prefilling = True
        slot.plan = self.pool.admit(slot.index, req.prompt, req.max_new_tokens)
        # chunks about to be recomputed whose content the prefix cache held
        # before eviction: work the machine already did once (goodput waste)
        reprefill = getattr(slot.plan, "reprefill_tokens", 0)
        if reprefill:
            self.metrics.record_goodput("reprefill_cache_miss", reprefill)

    def _admit_prefilled(self, slot: Slot, req: Request) -> None:
        """Disaggregated handoff landing (engine/dist/): allocate UNSHARED
        pages (the shipped K/V is written into them — a write must never
        touch a prefix-shared page), insert the pages, emit the worker's
        first token, and hand the slot straight to decode.  ``register``
        then publishes the now-populated prompt pages to this engine's
        prefix cache, so later LOCAL submits share them normally."""
        n = len(req.prompt)
        slot.plan = self.pool.admit(
            slot.index, req.prompt, req.max_new_tokens, share=False)
        slot.plan.chunks_done = len(slot.plan.chunk_starts)  # nothing to run
        page_ids = self.pool.prompt_page_ids(slot.index, n)
        try:
            self.cache = self._insert_shipped_pages(
                self.cache, page_ids, req.prefilled["pages"])
        except ValueError as e:  # KVTransferError: payload does not fit
            self._fail_admission(slot, req, e)
            return
        first = int(req.prefilled["first_token"])
        req.first_token_at = time.monotonic()
        if req.t_submit_ns:
            # t_first == t_admit: the > guard in _emit_request_spans keeps
            # the (remote) prefill from double-reporting as a local span
            req.t_first_ns = req.t_admit_ns
        self.metrics.record_ttft(req.first_token_at - req.submitted_at,
                                 req.priority,
                                 trace_id=(req.trace_ctx or {}).get("trace_id"))
        req.stream._emit(first)
        self.metrics.record_tokens(1)
        self.pool.register(slot.index, req.prompt)
        slot.prefilling = False
        slot.pos = n
        slot.budget_left = req.max_new_tokens - 1
        self._cur_tok[slot.index] = first
        self._pos[slot.index] = n
        if slot.budget_left == 0 or (
            self.eos_token_id is not None and first == self.eos_token_id
        ):
            self._retire(slot)

    def _fail_admission(self, slot: Slot, req: Request,
                        error: BaseException) -> None:
        """Admission found the request unservable (bad shipped payload):
        give the slot back and fail the stream LOUDLY — the poller sees
        the typed error and the journal falls back to replay, instead of
        this engine decoding from corrupt pages."""
        self.pool.release(slot.index)
        self.slots.release(slot)
        self._cur_tok[slot.index] = 0
        self._pos[slot.index] = 0
        self._adapter_ids_host[slot.index] = 0
        req.stream._finish(error)

    def _admit_migrated(self, slot: Slot, req: Request) -> None:
        """Migration landing (:meth:`submit_migrated`): like
        :meth:`_admit_prefilled` but for a stream that was already
        DECODING elsewhere.  Allocates unshared pages sized for the whole
        remaining run, inserts the shipped K/V, replays the client-visible
        tokens onto the stream, and parks the cursor exactly where the
        source stopped — ``chunks_done`` covers the whole chunk list, so
        ZERO prefill chunks run (``migrations.in_reprefill_chunks`` stays
        0; the acceptance test pins it).  The pages are NOT registered
        with the prefix cache: the tail page is mid-append and the
        admitted "prompt" includes generated tokens — publishing it would
        let a future prompt share a page decode is still writing into."""
        m = req.migrated
        p = len(req.prompt)          # cache-resident positions 0..p-1
        slot.plan = self.pool.admit(
            slot.index, req.prompt, req.max_new_tokens, share=False)
        slot.plan.chunks_done = len(slot.plan.chunk_starts)  # nothing to run
        page_ids = self.pool.prompt_page_ids(slot.index, p)
        try:
            self.cache = self._insert_shipped_pages(
                self.cache, page_ids, m["pages"])
        except ValueError as e:  # KVTransferError: payload does not fit
            self._fail_admission(slot, req, e)
            return
        req.first_token_at = time.monotonic()
        if req.t_submit_ns:
            # t_first == t_admit: the > guard in _emit_request_spans keeps
            # the (source-replica) prefill from re-reporting here
            req.t_first_ns = req.t_admit_ns
        streamed = m["streamed"]
        for tok in streamed:
            # already counted and TTFT-stamped on the source — replayed
            # onto the fresh stream so it carries the FULL client-visible
            # list (the proxy re-pins pollers with offset 0)
            req.stream._emit(tok)
        slot.prefilling = False
        slot.pos = p
        slot.budget_left = req.max_new_tokens - 1
        self._cur_tok[slot.index] = streamed[-1]
        self._pos[slot.index] = p
        self.metrics.record_migration(
            "in", len(page_ids), reprefill_chunks=slot.plan.chunks_left)
        self.metrics.record_tenant_migrated(req.tenant or req.adapter_id,
                                            len(page_ids))
        if slot.budget_left == 0 or (
            self.eos_token_id is not None
            and streamed[-1] == self.eos_token_id
        ):
            self._retire(slot)

    def _insert_shipped_pages(self, cache, page_ids, payload):
        """Write a disaggregated handoff's KV pages into ``page_ids`` of the
        donated cache (MeshEngine re-places the rebuilt leaves onto its
        shardings afterwards)."""
        from .dist.kv_transfer import insert_kv_pages  # lazy: avoids cycle

        return insert_kv_pages(cache, page_ids, payload)

    def _prefill_quantum(self) -> bool:
        """Run up to ``prefill_chunks_per_step`` prefill chunk calls,
        SHORTEST-REMAINING-PROMPT first (ties: request id = arrival order).
        Bounding the per-step quantum keeps any single long prompt from
        stalling in-flight decodes; preferring short remainders keeps
        short-request TTFT flat while a long prompt streams in."""
        ran = False
        for _ in range(max(1, self.config.prefill_chunks_per_step)):
            pending = [s for s in self.slots.active_slots() if s.prefilling]
            if not pending:
                break
            slot = min(
                pending,
                key=lambda s: (s.plan.chunks_left, s.request.request_id),
            )
            self._run_chunk(slot)
            ran = True
        return ran

    def _run_chunk(self, slot: Slot) -> None:
        plan = slot.plan
        req = slot.request
        cfg = self.config
        C = cfg.page_len
        p0 = plan.next_start
        n = plan.prompt_len
        ids = np.full((1, C), self.model.config.pad_token_id, np.int32)
        chunk_toks = req.prompt[p0:p0 + C]
        ids[0, :len(chunk_toks)] = chunk_toks
        is_last = plan.chunks_done == len(plan.chunk_starts) - 1
        last_local = (n - 1 - p0) if is_last else (C - 1)
        row = self.pool.chunk_row(slot.index, p0, plan.null_target)
        t0 = time.monotonic()
        if self.adapters_enabled:
            self.cache, tok = self._chunk_fn(
                self.params, self.cache, jnp.asarray(ids), jnp.int32(p0),
                jnp.int32(last_local), jnp.asarray(row),
                self._adapter_a, self._adapter_b,
                jnp.int32(req.adapter_row),
            )
        else:
            self.cache, tok = self._chunk_fn(
                self.params, self.cache, jnp.asarray(ids), jnp.int32(p0),
                jnp.int32(last_local), jnp.asarray(row),
            )
        if self._cost_model is not None:
            # dispatch-time measurement: only the final chunk is host-synced
            # (int(tok) below), so mid-prompt chunk seconds are the dispatch
            # cost on an async backend — exact on CPU, a lower bound on TPU
            # (on-chip rerun is ROADMAP item 5's lane)
            self.metrics.record_program(
                "prefill_chunk",
                self._cost_model.prefill_chunk_cost(C, p0),
                time.monotonic() - t0)
        plan.chunks_done += 1
        self._chunks_run += 1
        if not plan.done:
            return
        # final chunk: first token, publication, CoW, hand over to decode
        first = int(np.asarray(tok))
        req.first_token_at = time.monotonic()
        if req.t_submit_ns:  # traced request: stamp TTFT for span emission
            req.t_first_ns = _tracing.now_ns()
        self.metrics.record_ttft(req.first_token_at - req.submitted_at,
                                 req.priority,
                                 trace_id=(req.trace_ctx or {}).get("trace_id"))
        req.stream._emit(first)
        self.metrics.record_tokens(1)  # prefill's first token
        self.pool.register(slot.index, req.prompt)
        cow = self.pool.resolve_cow(slot.index)
        if cow is not None:
            dst, src = cow
            self.cache = self._copy_fn(
                self.cache, jnp.int32(dst), jnp.int32(src))
        slot.prefilling = False
        slot.pos = n
        slot.budget_left = req.max_new_tokens - 1
        self._cur_tok[slot.index] = first
        self._pos[slot.index] = n
        if slot.budget_left == 0 or (
            self.eos_token_id is not None and first == self.eos_token_id
        ):
            self._retire(slot)

    # -- slab admission ------------------------------------------------------
    def _prefill_for(self, bucket: int):
        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = make_lm_prefill_fn(self.model, bucket)
        return self._prefill_fns[bucket]

    def _admit(self, req: Request) -> None:
        slot = self.slots.acquire()
        n = len(req.prompt)
        bucket = self.config.bucket_for(n)
        ids = np.full((1, bucket), self.model.config.pad_token_id, np.int32)
        ids[0, :n] = req.prompt
        tok, segment = self._prefill_for(bucket)(
            self.params, jnp.asarray(ids), jnp.asarray([n - 1], jnp.int32)
        )
        # graft the whole padded segment: pad positions >= n are masked by
        # the per-row validity check until decode writes overwrite them
        self.cache = self._insert(self.cache, segment, slot.index)
        first = int(tok[0])
        req.first_token_at = time.monotonic()
        if req.t_submit_ns:  # traced request: stamp TTFT for span emission
            req.t_first_ns = _tracing.now_ns()
        self.metrics.record_ttft(req.first_token_at - req.submitted_at,
                                 req.priority,
                                 trace_id=(req.trace_ctx or {}).get("trace_id"))
        req.stream._emit(first)
        self.metrics.record_tokens(1)  # prefill's first token
        slot.request = req
        slot.pos = n
        slot.budget_left = req.max_new_tokens - 1
        self._cur_tok[slot.index] = first
        self._pos[slot.index] = n
        if slot.budget_left == 0 or (
            self.eos_token_id is not None and first == self.eos_token_id
        ):
            self._retire(slot)

    # -- live weight swap (serve/weights.py) ---------------------------------
    def swap_params(self, new_params, *, version: Optional[int] = None
                    ) -> float:
        """Replace the serving weights BETWEEN decode steps: taken under
        ``_step_lock``, so no step is mid-flight — slots, host token/pos
        arrays and the paged pool are untouched, and in-flight streams
        continue on the new weights at their exact positions.  The new
        tree is resharded leaf-by-leaf onto the OLD leaves' shardings
        (``device_put`` per leaf — a tp/dp-partitioned checkpoint restores
        onto whatever mesh this engine serves on) after a structure/shape
        check that rejects mismatched trees before touching ``params``.

        Keeps the prior device tree for :meth:`rollback_params` and
        returns the swap's stall in milliseconds (request-to-done wall
        time: lock wait + reshard + transfer — the bound on the decode
        step gap the swap introduced)."""
        import jax

        t_req = time.monotonic()
        if _faults.enabled():
            _faults.perturb("weights.swap", key=self.name)
        with self._step_lock:
            old_leaves, old_tree = jax.tree_util.tree_flatten(self.params)
            new_leaves, new_tree = jax.tree_util.tree_flatten(new_params)
            if old_tree != new_tree:
                raise ValueError(
                    "weight swap rejected: parameter tree structure differs "
                    "from the serving model")
            placed = []
            for o, n in zip(old_leaves, new_leaves):
                arr = np.asarray(n)
                if tuple(arr.shape) != tuple(o.shape):
                    raise ValueError(
                        f"weight swap rejected: leaf shape {arr.shape} != "
                        f"serving shape {tuple(o.shape)}")
                placed.append(jax.device_put(arr.astype(o.dtype), o.sharding))
            for p in placed:
                p.block_until_ready()
            self._prev_params = self.params
            self._prev_version = self._weights_version
            self.params = jax.tree_util.tree_unflatten(new_tree, placed)
            self._weights_version = version
            stall_ms = (time.monotonic() - t_req) * 1000.0
        self.metrics.record_weights_swap(version, stall_ms)
        return stall_ms

    def rollback_params(self) -> float:
        """Restore the weights :meth:`swap_params` replaced — a pure
        device-tree pointer swap under ``_step_lock``, no store reads, so
        rollback works even when the bad publish's store objects are
        corrupt or already GC'd.  Raises RuntimeError with no prior
        version retained."""
        t_req = time.monotonic()
        with self._step_lock:
            if self._prev_params is None:
                raise RuntimeError("no prior weights retained to roll back to")
            # one-shot: clearing the slot frees the bad tree's device memory
            # and makes a second rollback (nothing to restore) an error
            self.params, self._prev_params = self._prev_params, None
            self._weights_version, self._prev_version = (
                self._prev_version, None)
            version = self._weights_version
            stall_ms = (time.monotonic() - t_req) * 1000.0
        self.metrics.record_weights_swap(version, stall_ms, rollback=True)
        return stall_ms

    def weights_version(self) -> Optional[int]:
        # airlint: disable=CC001 — GIL-atomic pointer read for stats; a
        # reader racing a swap sees the old or new version, both valid,
        # and taking _step_lock here would stall stats behind a decode
        return self._weights_version

    # -- multi-tenant LoRA adapters ------------------------------------------
    def _resolve_adapter(self, req: Request) -> bool:
        """Admission-time resolution of ``req.adapter_id`` to a bank row.
        Submit already validated the name, but the adapter may have been
        evicted while the request sat queued — then the stream fails
        loudly (the proxy surfaces the error) instead of silently serving
        base-model tokens under the tenant's name."""
        if req.adapter_id is None:
            req.adapter_row = 0
            return True
        with self._adapter_lock:
            row = self._adapter_rows.get(req.adapter_id)
        if row is None:
            req.stream._finish(RequestValidationError(
                f"adapter {req.adapter_id!r} was evicted while request "
                f"{req.request_id} was queued"))
            return False
        req.adapter_row = row
        return True

    def load_adapter(self, name: str, a, b) -> int:
        """Load (or reload in place) tenant ``name``'s LoRA head delta
        ``logits += (h @ a) @ b`` into a free bank row.  ``a``: [d_model,
        r], ``b``: [r, vocab]; rank r <= ``adapter_rank`` zero-pads into
        the bank (zero padding is exact — padded lanes contribute 0).
        A cheap sub-swap: two ``.at[row].set`` writes under ``_step_lock``
        between decode steps; the jitted step never retraces."""
        if not self.adapters_enabled:
            raise ValueError(
                "adapters not enabled (EngineConfig.adapter_slots=0)")
        mc = self.model.config
        cfg = self.config
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
            raise ValueError(
                f"adapter shapes must be [d,r] x [r,V], got {a.shape} "
                f"x {b.shape}")
        if a.shape[0] != mc.d_model or b.shape[1] != mc.vocab_size:
            raise ValueError(
                f"adapter {a.shape} x {b.shape} does not fit model "
                f"[d={mc.d_model}, V={mc.vocab_size}]")
        r = a.shape[1]
        if r > cfg.adapter_rank:
            raise ValueError(
                f"adapter rank {r} exceeds bank rank {cfg.adapter_rank}")
        pa = np.zeros((mc.d_model, cfg.adapter_rank), np.float32)
        pb = np.zeros((cfg.adapter_rank, mc.vocab_size), np.float32)
        pa[:, :r] = a
        pb[:r, :] = b
        with self._step_lock:
            with self._adapter_lock:
                row = self._adapter_rows.get(name)
                if row is None:
                    used = set(self._adapter_rows.values())
                    free = [i for i in range(1, cfg.adapter_slots + 1)
                            if i not in used]
                    if not free:
                        raise ValueError(
                            f"adapter bank full ({cfg.adapter_slots} rows); "
                            f"unload a tenant first")
                    row = free[0]
                    self._adapter_rows[name] = row
                n_loaded = len(self._adapter_rows)
            self._adapter_a = self._adapter_a.at[row].set(jnp.asarray(pa))
            self._adapter_b = self._adapter_b.at[row].set(jnp.asarray(pb))
        self.metrics.set_adapters_loaded(n_loaded)
        return row

    def unload_adapter(self, name: str) -> bool:
        """Evict tenant ``name``: zero its bank row and free it.  Refuses
        (RuntimeError) while any active slot decodes under the row —
        eviction must not change tokens mid-stream."""
        if not self.adapters_enabled:
            return False
        with self._step_lock:
            with self._adapter_lock:
                row = self._adapter_rows.get(name)
                if row is None:
                    return False
                if any(self._adapter_ids_host[s.index] == row
                       for s in self.slots.active_slots()):
                    raise RuntimeError(
                        f"adapter {name!r} is serving active slots; drain "
                        f"them before unloading")
                del self._adapter_rows[name]
                n_loaded = len(self._adapter_rows)
            self._adapter_a = self._adapter_a.at[row].set(0.0)
            self._adapter_b = self._adapter_b.at[row].set(0.0)
        self.metrics.set_adapters_loaded(n_loaded)
        return True

    def adapters(self) -> Dict[str, int]:
        """Loaded tenant adapters: name -> bank row."""
        with self._adapter_lock:
            return dict(self._adapter_rows)

    # -- decode --------------------------------------------------------------
    def _null_entry(self, slot_index: int) -> int:
        """The page id a non-decoding slot's table row is masked with.  The
        single-chip pool has one null page (id 0); the MeshEngine override
        returns the slot's OWN replica's null page so the ride-along
        scatter never crosses a data shard."""
        return 0

    def _decode_all(self) -> None:
        t0 = time.monotonic()
        if self.paged:
            # non-decoding rows (free OR mid-prefill) ride along pointed at
            # the null page: their ride-along scatter can't touch a live or
            # prefix-shared page.  The authoritative table stays host-side.
            table = self.pool.block_table.copy()
            for s in self.slots.slots:
                if not s.active or s.prefilling:
                    table[s.index] = self._null_entry(s.index)
            if self.adapters_enabled:
                # per-slot LoRA rows gathered the way the table is: one
                # host array in, no retrace, row 0 = exact-zero delta
                self.cache, nxt = self._decode_step(
                    self.params, self.cache,
                    jnp.asarray(self._cur_tok), jnp.asarray(self._pos),
                    jnp.asarray(table),
                    self._adapter_a, self._adapter_b,
                    jnp.asarray(self._adapter_ids_host),
                )
            else:
                self.cache, nxt = self._decode_step(
                    self.params, self.cache,
                    jnp.asarray(self._cur_tok), jnp.asarray(self._pos),
                    jnp.asarray(table),
                )
        else:
            self.cache, nxt = self._decode_step(
                self.params, self.cache,
                jnp.asarray(self._cur_tok), jnp.asarray(self._pos),
            )
        nxt = np.asarray(nxt)
        dt = time.monotonic() - t0
        if self._decode_cost is not None:
            self.metrics.record_program("decode_step", self._decode_cost, dt)
        emitted = 0
        for slot in self.slots.active_slots():
            if slot.prefilling:
                continue
            # airlint: disable=JX004 — nxt is the np.asarray'd step result;
            # the single device sync already happened above the loop
            token = int(nxt[slot.index])
            slot.request.stream._emit(token)
            emitted += 1
            slot.pos += 1
            slot.budget_left -= 1
            self._cur_tok[slot.index] = token
            self._pos[slot.index] = slot.pos
            if slot.budget_left == 0 or (
                self.eos_token_id is not None and token == self.eos_token_id
            ):
                self._retire(slot)
        self.metrics.record_step(dt, emitted)

    # -- retirement ----------------------------------------------------------
    def _retire(self, slot: Slot) -> None:
        if slot.request.t_submit_ns:
            self._emit_request_spans(slot)
        slot.request.stream._finish()
        self.metrics.record_complete()
        # goodput: every token this stream emitted reached a consumer that
        # saw the stream complete — useful work
        self.metrics.record_goodput(
            "useful", slot.pos - len(slot.request.prompt) + 1)
        # per-tenant cost attribution (airwatch ledger feed): bill the
        # stream's tokens and KV-page residency to its billing tenant —
        # the explicit ``tenant`` label when one rides the request (batch
        # lane), else its adapter_id tenant.  Residency runs from first
        # token (pages are fully resident once prefill lands) to
        # retirement; page count mirrors the pool's own ceil-division for
        # paged engines, the fixed slot reservation for slab engines.
        req = slot.request
        if self.paged:
            n_pages = -(-slot.pos // self.config.page_len)
        else:
            n_pages = self.config.pages_per_slot()
        resident_s = max(
            0.0, time.monotonic() - (req.first_token_at or req.submitted_at))
        self.metrics.record_tenant_retire(
            req.tenant or req.adapter_id,
            prefilled=len(req.prompt),
            decoded=slot.pos - len(req.prompt) + 1,
            kv_page_seconds=n_pages * resident_s)
        if self.paged:
            # private pages return to the free list; prompt pages the prefix
            # cache registered stay resident for future hits
            self.pool.release(slot.index)
        self.slots.release(slot)
        self._cur_tok[slot.index] = 0
        self._pos[slot.index] = 0
        self._adapter_ids_host[slot.index] = 0

    def _emit_request_spans(self, slot: Slot) -> None:
        """Retirement-time airtrace emission: the request's whole span tree
        (queue-wait → prefill → decode residency) is reconstructed here from
        the wall-clock stamps collected along the way, so the decode hot
        loop does zero tracing work (and stays JX004-clean)."""
        req = slot.request
        end = _tracing.now_ns()
        ctx = req.trace_ctx or {}
        root = _tracing.record_span(
            "engine.request",
            trace_id=ctx.get("trace_id"),
            parent_id=ctx.get("span_id"),
            start_ns=req.t_submit_ns,
            end_ns=end,
            attrs={"engine": self.name, "request_id": req.request_id},
        )
        if req.t_admit_ns:
            _tracing.record_span(
                "engine.queue_wait",
                trace_id=root.trace_id, parent_id=root.span_id,
                start_ns=req.t_submit_ns, end_ns=req.t_admit_ns,
            )
        if req.t_admit_ns and req.t_first_ns > req.t_admit_ns:
            # strictly-after: a disaggregated request lands with t_first ==
            # t_admit (its prefill span was recorded on the worker replica)
            attrs = {"slot": slot.index, "prompt_len": len(req.prompt)}
            if self.paged and slot.plan is not None:
                attrs["chunks"] = len(slot.plan.chunk_starts)
                attrs["prefix_hit"] = slot.plan.prefix_tokens > 0
                attrs["prefix_tokens"] = slot.plan.prefix_tokens
            elif not self.paged:
                attrs["bucket"] = self.config.bucket_for(len(req.prompt))
            _tracing.record_span(
                "engine.prefill",
                trace_id=root.trace_id, parent_id=root.span_id,
                start_ns=req.t_admit_ns, end_ns=req.t_first_ns,
                attrs=attrs,
            )
        if req.t_first_ns:
            _tracing.record_span(
                "engine.decode",
                trace_id=root.trace_id, parent_id=root.span_id,
                start_ns=req.t_first_ns, end_ns=end,
                attrs={
                    "slot": slot.index,
                    "tokens": slot.pos - len(req.prompt) + 1,
                    "occupancy": self.slots.occupancy(),
                },
            )

    # -- background loop / lifecycle -----------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"tpu-air-{self.name}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._closed:
            if not self.step():
                self.scheduler.wait_for_work(0.01)

    def close(self) -> None:
        """Stop the loop; fail queued and in-flight requests loudly."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._step_lock:
            err = EngineClosedError("engine shut down")
            for req in self.scheduler.drain():
                req.stream._finish(err)
            # goodput: compute already spent on in-flight requests is lost —
            # a drained close sheds work it had prefilled (the stream moved
            # to another replica), a hard close kills live streams outright
            waste_cat = ("shed_after_prefill" if self._draining
                         else "dead_stream")
            for slot in self.slots.active_slots():
                req = slot.request
                if slot.prefilling:
                    plan = slot.plan
                    done_tokens = 0
                    if plan is not None and plan.chunks_done:
                        done_tokens = min(
                            plan.chunks_done * self.config.page_len,
                            len(req.prompt))
                    wasted = done_tokens
                else:
                    wasted = slot.pos - len(req.prompt) + 1
                self.metrics.record_goodput(waste_cat, wasted)
                req.stream._finish(err)
                if self.paged:
                    self.pool.release(slot.index)
                self.slots.release(slot)
        unregister(self.name)

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
