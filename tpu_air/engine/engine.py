"""The continuous-batching inference engine.

One :class:`InferenceEngine` owns a fixed pool of ``S`` sequence slots
backed by per-layer flat KV slabs ``[S, slot_len, h*d]`` and keeps a single
persistent jit-compiled decode step alive over that pool for its whole
lifetime (the cache is donated — slabs update in place, never copied).
Requests flow through three host-side phases BETWEEN device steps:

1. **admission** — FIFO from the scheduler queue, up to the number of free
   slots.  Each admitted prompt is right-padded to its length bucket,
   prefilled (B=1, one compile per bucket), and its KV segment grafted into
   the free slab row with one jitted ``dynamic_update_slice``.  The first
   greedy token comes out of prefill itself — TTFT does not wait for the
   next pool step.
2. **decode** — one fixed-shape step over all ``S`` rows.  Free rows ride
   along (pos 0, output discarded host-side); occupied rows each scatter
   their token's K/V to ``(row, pos[row])`` and attend under a per-row
   validity mask, so slots at wildly different positions share the step.
3. **retirement** — a row that emits EOS (inclusive — the EOS id is
   delivered, matching offline ``generate``) or exhausts its budget is
   released on the very next host visit; no slab zeroing (stale K/V beyond
   a new occupant's written positions are masked, then overwritten).

Correctness anchor: with greedy decoding the engine's emitted tokens are
token-identical to offline ``generate()`` on the same prompts —
tests/test_engine.py pins this on CPU for burst, staggered and trickle
arrival schedules.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from tpu_air.models.lm.generate import (
    init_slot_cache,
    make_lm_decode_step_fn,
    make_lm_prefill_fn,
)

from tpu_air.observability import tracing as _tracing

from .metrics import EngineMetrics, unregister
from .scheduler import Scheduler
from .slots import Slot, SlotManager, make_insert_fn
from .types import (
    EngineClosedError,
    EngineConfig,
    EngineOverloadedError,
    Request,
    ResponseStream,
)


class InferenceEngine:
    """Slot-pool online inference over a causal LM.

    ``submit`` is thread-safe and non-blocking (raises
    :class:`EngineOverloadedError` under backpressure); tokens stream back
    on the returned :class:`ResponseStream` as they are decoded.  With
    ``auto_start=True`` (the default) a daemon thread drives the step loop;
    ``auto_start=False`` hands the loop to the caller via :meth:`step` —
    the deterministic mode the parity tests drive.
    """

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 *, auto_start: bool = True, name: str = "engine"):
        self.model = model
        self.params = params
        self.config = config or EngineConfig()
        self.name = name
        cfg = self.config
        if cfg.eos_token_id == "model":
            self.eos_token_id = model.config.eos_token_id
        else:
            self.eos_token_id = cfg.eos_token_id
        if cfg.slot_len > model.config.max_seq_len:
            raise ValueError(
                f"slot_len {cfg.slot_len} exceeds the model's max_seq_len "
                f"{model.config.max_seq_len}"
            )

        # device side: the persistent donated slab pool + compiled phases
        self.cache = init_slot_cache(model, cfg.num_slots, cfg.slot_len)
        self._decode_step = make_lm_decode_step_fn(model, cfg.slot_len)
        self._insert = make_insert_fn()
        self._prefill_fns: Dict[int, Any] = {}  # bucket -> compiled prefill

        # host side: authoritative per-slot state the step args come from
        self._cur_tok = np.zeros((cfg.num_slots,), np.int32)
        self._pos = np.zeros((cfg.num_slots,), np.int32)

        self.scheduler = Scheduler(cfg)
        self.slots = SlotManager(cfg.num_slots)
        self.metrics = EngineMetrics(name=name, num_slots=cfg.num_slots)

        self._next_request_id = 0
        self._id_lock = threading.Lock()
        self._step_lock = threading.Lock()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if auto_start:
            self.start()

    # -- submission (any thread) ---------------------------------------------
    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None) -> ResponseStream:
        """Queue one prompt; returns its token stream immediately."""
        if self._closed:
            raise EngineClosedError("engine is shut down")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        budget = (self.config.max_new_tokens if max_new_tokens is None
                  else int(max_new_tokens))
        if budget < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {budget}")
        if len(prompt) + budget > self.config.slot_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({budget}) "
                f"exceeds slot_len ({self.config.slot_len})"
            )
        with self._id_lock:
            rid = self._next_request_id
            self._next_request_id += 1
        stream = ResponseStream(rid)
        req = Request(request_id=rid, prompt=prompt, max_new_tokens=budget,
                      stream=stream)
        try:
            self.scheduler.submit(req)
        except EngineOverloadedError:  # backpressure: count the 503, surface it
            self.metrics.record_reject()
            raise
        self.metrics.record_submit()
        return stream

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[List[int]]:
        """Blocking convenience: submit every prompt, join every stream.
        In manual mode (no background thread) it drives :meth:`step`."""
        streams = [self.submit(p, max_new_tokens) for p in prompts]
        if self._thread is None:
            while not self.idle():
                self.step()
        return [s.result(timeout) for s in streams]

    # -- the engine loop -----------------------------------------------------
    def step(self) -> bool:
        """One deterministic engine iteration: admit into free slots, then
        one pool decode step if anything is active.  Returns True if any
        work happened (callers loop ``while engine.step(): ...`` to drain)."""
        with self._step_lock:
            worked = False
            for req in self.scheduler.pop_admissible(self.slots.free_count()):
                self._admit(req)
                worked = True
            if self.slots.occupancy():
                self._decode_all()
                worked = True
            self.metrics.observe_gauges(
                self.scheduler.depth(), self.slots.occupancy()
            )
            return worked

    def idle(self) -> bool:
        return self.scheduler.depth() == 0 and self.slots.occupancy() == 0

    def _prefill_for(self, bucket: int):
        if bucket not in self._prefill_fns:
            self._prefill_fns[bucket] = make_lm_prefill_fn(self.model, bucket)
        return self._prefill_fns[bucket]

    def _admit(self, req: Request) -> None:
        slot = self.slots.acquire()
        n = len(req.prompt)
        bucket = self.config.bucket_for(n)
        ids = np.full((1, bucket), self.model.config.pad_token_id, np.int32)
        ids[0, :n] = req.prompt
        tok, segment = self._prefill_for(bucket)(
            self.params, jnp.asarray(ids), jnp.asarray([n - 1], jnp.int32)
        )
        # graft the whole padded segment: pad positions >= n are masked by
        # the per-row validity check until decode writes overwrite them
        self.cache = self._insert(self.cache, segment, slot.index)
        first = int(tok[0])
        req.first_token_at = time.monotonic()
        if req.t_submit_ns:  # traced request: stamp TTFT for span emission
            req.t_first_ns = _tracing.now_ns()
        self.metrics.record_ttft(req.first_token_at - req.submitted_at)
        req.stream._emit(first)
        self.metrics.record_tokens(1)  # prefill's first token
        slot.request = req
        slot.pos = n
        slot.budget_left = req.max_new_tokens - 1
        self._cur_tok[slot.index] = first
        self._pos[slot.index] = n
        if slot.budget_left == 0 or (
            self.eos_token_id is not None and first == self.eos_token_id
        ):
            self._retire(slot)

    def _decode_all(self) -> None:
        t0 = time.monotonic()
        self.cache, nxt = self._decode_step(
            self.params, self.cache,
            jnp.asarray(self._cur_tok), jnp.asarray(self._pos),
        )
        nxt = np.asarray(nxt)
        dt = time.monotonic() - t0
        emitted = 0
        for slot in self.slots.active_slots():
            # airlint: disable=JX004 — nxt is the np.asarray'd step result;
            # the single device sync already happened above the loop
            token = int(nxt[slot.index])
            slot.request.stream._emit(token)
            emitted += 1
            slot.pos += 1
            slot.budget_left -= 1
            self._cur_tok[slot.index] = token
            self._pos[slot.index] = slot.pos
            if slot.budget_left == 0 or (
                self.eos_token_id is not None and token == self.eos_token_id
            ):
                self._retire(slot)
        self.metrics.record_step(dt, emitted)

    def _retire(self, slot: Slot) -> None:
        if slot.request.t_submit_ns:
            self._emit_request_spans(slot)
        slot.request.stream._finish()
        self.metrics.record_complete()
        self.slots.release(slot)
        self._cur_tok[slot.index] = 0
        self._pos[slot.index] = 0

    def _emit_request_spans(self, slot: Slot) -> None:
        """Retirement-time airtrace emission: the request's whole span tree
        (queue-wait → prefill → decode residency) is reconstructed here from
        the wall-clock stamps collected along the way, so the decode hot
        loop does zero tracing work (and stays JX004-clean)."""
        req = slot.request
        end = _tracing.now_ns()
        ctx = req.trace_ctx or {}
        root = _tracing.record_span(
            "engine.request",
            trace_id=ctx.get("trace_id"),
            parent_id=ctx.get("span_id"),
            start_ns=req.t_submit_ns,
            end_ns=end,
            attrs={"engine": self.name, "request_id": req.request_id},
        )
        if req.t_admit_ns:
            _tracing.record_span(
                "engine.queue_wait",
                trace_id=root.trace_id, parent_id=root.span_id,
                start_ns=req.t_submit_ns, end_ns=req.t_admit_ns,
            )
        if req.t_admit_ns and req.t_first_ns:
            _tracing.record_span(
                "engine.prefill",
                trace_id=root.trace_id, parent_id=root.span_id,
                start_ns=req.t_admit_ns, end_ns=req.t_first_ns,
                attrs={
                    "slot": slot.index,
                    "prompt_len": len(req.prompt),
                    "bucket": self.config.bucket_for(len(req.prompt)),
                },
            )
        if req.t_first_ns:
            _tracing.record_span(
                "engine.decode",
                trace_id=root.trace_id, parent_id=root.span_id,
                start_ns=req.t_first_ns, end_ns=end,
                attrs={
                    "slot": slot.index,
                    "tokens": slot.pos - len(req.prompt) + 1,
                    "occupancy": self.slots.occupancy(),
                },
            )

    # -- background loop / lifecycle -----------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"tpu-air-{self.name}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._closed:
            if not self.step():
                self.scheduler.wait_for_work(0.01)

    def close(self) -> None:
        """Stop the loop; fail queued and in-flight requests loudly."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._step_lock:
            err = EngineClosedError("engine shut down")
            for req in self.scheduler.drain():
                req.stream._finish(err)
            for slot in self.slots.active_slots():
                slot.request.stream._finish(err)
                self.slots.release(slot)
        unregister(self.name)

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
