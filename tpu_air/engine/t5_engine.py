"""Windowed continuous decoding for the T5 family.

The PR 1 engine entry points for T5 (models/t5/generate.py:
``make_t5_prefill_fn`` / ``make_t5_decode_step_fn``) are BATCH-
SYNCHRONIZED: the decode cache carries one scalar cache index and the
whole batch's cross-attention K/V, so rows cannot sit at different decode
positions the way the causal-LM slot pool allows.  :class:`T5Engine` is
therefore a WINDOW engine, honest about that boundary:

* requests queue through the same :class:`~tpu_air.engine.scheduler.
  Scheduler` (backpressure, FIFO) and stream back per-token on the same
  :class:`~tpu_air.engine.types.ResponseStream`;
* a *window* is one prefill (encode + cache build + first token) over up
  to ``max_batch`` queued requests padded to a fixed shape, followed by
  per-token decode steps driven between host visits — tokens stream out
  as they are decoded, rows retire individually on EOS (inclusive) or
  budget;
* ADMISSION happens only at window boundaries: a window must fully drain
  before the next batch starts (the cross-attn K/V of a retired row
  cannot be swapped out under the scalar index).  Early-retired rows ride
  along as dead weight until the window closes — exactly the cost the
  causal-LM slot engine exists to avoid; per-slot cross-attn slabs remain
  the open item before T5 can join the slot pool (ROADMAP).

Greedy by construction: token streams are identical to offline T5
``generate`` with ``early_stop=True`` on the same window batch.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

import jax.numpy as jnp

from tpu_air.models.t5.generate import (
    make_t5_decode_step_fn,
    make_t5_prefill_fn,
)

from .metrics import EngineMetrics, unregister
from .scheduler import Scheduler
from .types import (
    PRIORITIES,
    EngineClosedError,
    EngineDrainingError,
    EngineOverloadedError,
    Request,
    ResponseStream,
)


@dataclass
class T5EngineConfig:
    """Dials for the T5 window engine.

    * ``max_batch`` — rows per window (the fixed prefill/decode batch
      shape; short windows pad with dead all-pad rows).
    * ``max_input_len`` — encoder-side prompt cap; prompts right-pad to
      this fixed length so one compiled prefill serves every window.
    * ``max_new_tokens`` — decode budget cap per request (the cache is
      sized to it).
    * ``max_queue`` — queued request cap; beyond it ``submit`` raises
      :class:`EngineOverloadedError`.
    * ``queue_shares`` — per-priority-class fraction of ``max_queue`` at
      which submits shed, same contract as
      :class:`~tpu_air.engine.types.EngineConfig.queue_shares`.
    """

    max_batch: int = 4
    max_input_len: int = 64
    max_new_tokens: int = 32
    max_queue: int = 256
    reorder_window: int = 0  # window admission is FIFO; kept for Scheduler
    queue_shares: Optional[dict] = None

    def queue_cap(self, priority: str) -> int:
        """Total queue depth at which ``priority``-class submits shed
        (shares mirror EngineConfig's defaults)."""
        shares = self.queue_shares or {
            "interactive": 1.0, "batch": 0.85, "best_effort": 0.5,
        }
        return int(self.max_queue * float(shares.get(priority, 1.0)))


class _Window:
    """One in-flight batch: device cache + per-row host bookkeeping."""

    def __init__(self, requests: List[Request], cache, enc, enc_mask):
        self.requests: List[Optional[Request]] = list(requests)
        self.cache = cache
        self.enc = enc
        self.enc_mask = enc_mask
        self.cur_tok = np.zeros((enc_mask.shape[0],), np.int32)
        self.budget_left = np.zeros((enc_mask.shape[0],), np.int64)

    def live_rows(self):
        return [i for i, r in enumerate(self.requests) if r is not None]


class T5Engine:
    """Window-level continuous decoding over a T5 model (see module doc)."""

    def __init__(self, model, params, config: Optional[T5EngineConfig] = None,
                 *, auto_start: bool = True, name: str = "t5-engine"):
        self.model = model
        self.params = params
        self.config = config or T5EngineConfig()
        self.name = name
        self.eos_token_id = model.config.eos_token_id
        self.pad_token_id = model.config.pad_token_id

        cfg = self.config
        self._prefill = make_t5_prefill_fn(model, cfg.max_new_tokens + 1)
        self._decode_step = make_t5_decode_step_fn(model)
        self._window: Optional[_Window] = None

        self.scheduler = Scheduler(cfg)
        self.metrics = EngineMetrics(name=name, num_slots=cfg.max_batch)

        self._next_request_id = 0
        self._id_lock = threading.Lock()
        self._step_lock = threading.Lock()
        self._closed = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        if auto_start:
            self.start()

    # -- submission (any thread) ---------------------------------------------
    def submit(self, input_ids: Sequence[int],
               max_new_tokens: Optional[int] = None, *,
               priority: str = "interactive") -> ResponseStream:
        """Queue one encoder prompt; returns its token stream immediately.
        ``priority`` follows the same SLO-class contract as the causal-LM
        engine (admission is window-FIFO here, but shed thresholds and
        per-class gauges still apply)."""
        if self._closed:
            raise EngineClosedError("engine is shut down")
        if self._draining:
            raise EngineDrainingError(
                f"engine {self.name!r} is draining; submit elsewhere")
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r} (expected one of {PRIORITIES})"
            )
        prompt = [int(t) for t in input_ids]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.config.max_input_len:
            raise ValueError(
                f"prompt ({len(prompt)}) exceeds max_input_len "
                f"({self.config.max_input_len})"
            )
        budget = (self.config.max_new_tokens if max_new_tokens is None
                  else int(max_new_tokens))
        if not 1 <= budget <= self.config.max_new_tokens:
            raise ValueError(
                f"max_new_tokens must be in [1, "
                f"{self.config.max_new_tokens}], got {budget}"
            )
        with self._id_lock:
            rid = self._next_request_id
            self._next_request_id += 1
        stream = ResponseStream(rid)
        req = Request(request_id=rid, prompt=prompt, max_new_tokens=budget,
                      stream=stream, priority=priority)
        try:
            self.scheduler.submit(req)
        except EngineOverloadedError:
            self.metrics.record_reject(priority)
            raise
        self.metrics.record_submit(priority)
        return stream

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[List[int]]:
        """Blocking convenience: submit every prompt, join every stream.
        In manual mode (no background thread) it drives :meth:`step`."""
        streams = [self.submit(p, max_new_tokens) for p in prompts]
        if self._thread is None:
            while not self.idle():
                self.step()
        return [s.result(timeout) for s in streams]

    # -- the engine loop -----------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: open a window if none is in flight (one
        prefill over the queued batch), else one decode step.  Returns True
        if any work happened."""
        with self._step_lock:
            worked = False
            if self._window is None:
                worked = self._open_window()
            elif self._window is not None:
                self._decode_window()
                worked = True
            occ = len(self._window.live_rows()) if self._window else 0
            self.metrics.observe_gauges(
                self.scheduler.depth(), occ,
                queue_by_class=self.scheduler.depth_by_class(),
                draining=self._draining,
            )
            return worked

    def idle(self) -> bool:
        with self._step_lock:  # _window is step-loop state (see step())
            return self.scheduler.depth() == 0 and self._window is None

    # -- draining (same contract as InferenceEngine.drain) -------------------
    def drain(self) -> None:
        """Refuse new submits; queued + in-window work retires normally."""
        # airlint: disable=CC001 — monotonic GIL-atomic bool, flips
        # False→True once; a racing step() reads either value correctly
        self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def drained(self) -> bool:
        return self._draining and self.idle()

    def _open_window(self) -> bool:
        reqs = self.scheduler.pop_admissible(self.config.max_batch)
        if not reqs:
            return False
        cfg = self.config
        b, li = cfg.max_batch, cfg.max_input_len
        ids = np.full((b, li), self.pad_token_id, np.int32)
        mask = np.zeros((b, li), np.int32)
        for row, req in enumerate(reqs):
            ids[row, :len(req.prompt)] = req.prompt
            mask[row, :len(req.prompt)] = 1
        # rows past len(reqs) are dead filler: all-pad, zero mask — their
        # decode outputs are discarded host-side
        tok, cache, enc = self._prefill(
            self.params, jnp.asarray(ids), jnp.asarray(mask))
        tok = np.asarray(tok)
        rows: List[Optional[Request]] = list(reqs) + [None] * (b - len(reqs))
        win = _Window(rows, cache, enc, mask)
        now = time.monotonic()
        emitted = 0
        for row, req in enumerate(reqs):
            first = int(tok[row])
            req.first_token_at = now
            self.metrics.record_ttft(now - req.submitted_at)
            req.stream._emit(first)
            emitted += 1
            win.cur_tok[row] = first
            win.budget_left[row] = req.max_new_tokens - 1
            if win.budget_left[row] == 0 or first == self.eos_token_id:
                self._retire(win, row)
        self.metrics.record_tokens(emitted)
        self._window = win if win.live_rows() else None
        return True

    def _decode_window(self) -> None:
        win = self._window
        t0 = time.monotonic()
        win.cache, nxt = self._decode_step(
            self.params, win.cache, jnp.asarray(win.cur_tok), win.enc,
            jnp.asarray(win.enc_mask),
        )
        nxt = np.asarray(nxt)
        dt = time.monotonic() - t0
        emitted = 0
        for row in win.live_rows():
            # airlint: disable=JX004 — nxt is the np.asarray'd step result;
            # the single device sync already happened above the loop
            token = int(nxt[row])
            req = win.requests[row]
            req.stream._emit(token)
            emitted += 1
            win.cur_tok[row] = token
            win.budget_left[row] -= 1
            if win.budget_left[row] == 0 or token == self.eos_token_id:
                self._retire(win, row)
        self.metrics.record_step(dt, emitted)
        if not win.live_rows():
            # window drained: drop its cache, admit the next batch on the
            # following step
            self._window = None

    def _retire(self, win: _Window, row: int) -> None:
        win.requests[row].stream._finish()
        win.requests[row] = None
        self.metrics.record_complete()

    # -- background loop / lifecycle -----------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"tpu-air-{self.name}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        # airlint: disable=CC001 — GIL-atomic stop flag; close() sets it
        # then joins this thread, so a stale read costs one extra iteration
        while not self._closed:
            if not self.step():
                self.scheduler.wait_for_work(0.01)

    def close(self) -> None:
        """Stop the loop; fail queued and in-flight requests loudly."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._step_lock:
            err = EngineClosedError("engine shut down")
            for req in self.scheduler.drain():
                req.stream._finish(err)
            if self._window is not None:
                for row in self._window.live_rows():
                    self._window.requests[row].stream._finish(err)
                self._window = None
        unregister(self.name)

    def __enter__(self) -> "T5Engine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
