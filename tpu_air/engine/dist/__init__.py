"""tpu_air.engine.dist — sharded decode and prefill/decode disaggregation.

Two orthogonal pieces, composable:

* :class:`MeshEngine` — the paged engine's host loop over a leased
  ``(dp, tp)`` device mesh: pjit'd step bodies, tp-sharded weights,
  dp-sharded slots/pages (per-replica page pools via
  :class:`ShardedPagedPool`).
* :class:`DisaggRouter` + :class:`PrefillWorker` — chunked prefill on
  separate actor replicas, finished KV pages shipped to the decode
  engine through the shm object store and admitted via
  ``submit_prefilled`` (``engine.prefill`` → ``engine.kv_transfer`` →
  decode under one trace id).
"""

from .kv_transfer import (
    extract_kv_pages,
    insert_kv_pages,
    payload_nbytes,
    payload_pages,
)
from .mesh_engine import MeshEngine
from .pool import ShardedPagedPool
from .prefill_worker import PrefillWorker
from .router import DisaggRouter
from .sharded import (
    make_sharded_page_copy_fn,
    make_sharded_paged_decode_step_fn,
    make_sharded_prefill_chunk_fn,
    paged_cache_shardings,
)

__all__ = [
    "MeshEngine",
    "ShardedPagedPool",
    "PrefillWorker",
    "DisaggRouter",
    "extract_kv_pages",
    "insert_kv_pages",
    "payload_nbytes",
    "payload_pages",
    "paged_cache_shardings",
    "make_sharded_paged_decode_step_fn",
    "make_sharded_prefill_chunk_fn",
    "make_sharded_page_copy_fn",
]
