"""pjit wrappers for the paged engine's compiled phases over a
``(data, model)`` mesh.

The sharded engine runs the SAME step bodies as the single-chip engine
(models/lm/generate.py ``make_paged_decode_body`` /
``make_prefill_chunk_body`` / ``page_copy_body``) — only the jit options
differ: explicit ``in_shardings``/``out_shardings`` place the KV page
pools, per-slot indices and block tables over the ``data`` axis and the
q/k/v/gate/up/o/down kernels over ``model`` (parallel/sharding.py
``lm_param_spec``), and XLA's SPMD partitioner inserts the tensor-parallel
all-reduces the unchanged model code needs.  ``gather_pages`` runs
untouched inside each dp shard: the ShardedPagedPool hands out page ids
laid out so every slot's pages live in that slot's own data shard
(engine/dist/pool.py), making the gather shard-local.

Layout over a ``(dp, tp)`` mesh:

* ``cached_key`` / ``cached_value`` ``[P, page_len, h*d]`` →
  ``P("data", None, None)`` — pages split across dp replicas;
* ``cache_index`` ``[S]`` → ``P("data")``; ``block_table``
  ``[S, pages_per_slot]`` → ``P("data", None)`` — slots follow pages;
* decode ``tok``/``pos`` ``[S]`` → ``P("data")``; prefill chunk args
  (b=1 work) and CoW page ids → replicated.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_air.models.lm.generate import (
    make_paged_decode_body,
    make_prefill_chunk_body,
    page_copy_body,
)


def paged_cache_shardings(cache, mesh):
    """NamedSharding tree matching an ``init_paged_cache`` result: page
    pools and slot-indexed leaves over ``data``, everything else
    replicated."""

    def walk(d):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k in ("cached_key", "cached_value"):
                out[k] = NamedSharding(mesh, P("data", None, None))
            elif k == "cache_index":
                out[k] = NamedSharding(mesh, P("data"))
            elif k == "block_table":
                out[k] = NamedSharding(mesh, P("data", None))
            else:
                out[k] = NamedSharding(mesh, P())
        return out

    return walk(cache)


def make_sharded_paged_decode_step_fn(model, slot_len: int, mesh,
                                      param_shardings, cache_shardings):
    """The MeshEngine decode step: same body and donate contract as
    ``make_lm_paged_decode_step_fn``, with batch args over ``data``."""
    batch = NamedSharding(mesh, P("data"))
    table = NamedSharding(mesh, P("data", None))
    return jax.jit(
        make_paged_decode_body(model, slot_len),
        donate_argnums=(1,),
        in_shardings=(param_shardings, cache_shardings, batch, batch, table),
        out_shardings=(cache_shardings, batch),
    )


def make_sharded_prefill_chunk_fn(model, page_len: int, slot_len: int, mesh,
                                  param_shardings, cache_shardings):
    """The MeshEngine chunked-prefill unit: chunk args replicate (one b=1
    chunk is broadcast work; only its page writes land in a data shard)."""
    repl = NamedSharding(mesh, P())
    return jax.jit(
        make_prefill_chunk_body(model, page_len, slot_len),
        donate_argnums=(1,),
        in_shardings=(param_shardings, cache_shardings, repl, repl, repl,
                      repl),
        out_shardings=(cache_shardings, repl),
    )


def make_sharded_page_copy_fn(mesh, cache_shardings):
    """Copy-on-write under pjit.  The ShardedPagedPool always resolves CoW
    within one replica's page range, so the copy never crosses shards."""
    repl = NamedSharding(mesh, P())
    return jax.jit(
        page_copy_body,
        donate_argnums=(0,),
        in_shardings=(cache_shardings, repl, repl),
        out_shardings=cache_shardings,
    )
