"""DisaggRouter — the front door of a prefill/decode-disaggregated engine.

One router owns one decode engine (an :class:`InferenceEngine` or
:class:`MeshEngine`) plus a family of :class:`PrefillWorker` actor
replicas.  ``submit`` returns a live :class:`ResponseStream`
immediately; a per-request dispatcher thread then

1. picks the least-loaded LIVE prefill replica and runs the prompt's
   chunked prefill there (admission to prefill capacity — the queue
   forms at the actor mailbox, decode slots stay free for decoding);
2. under an ``engine.kv_transfer`` span, pulls the finished KV pages
   out of the shm object store and lands them on the decode engine via
   ``submit_prefilled`` — the decode engine's OWN capacity gate applies,
   so pool exhaustion defers the handoff in its admission queue instead
   of dropping it;
3. on prefill-replica death (``ActorDiedError``/``RemoteError``) marks
   the replica dead and re-routes under the retry discipline — bounded
   attempts with capped-exponential backoff + jitter, never past the
   request's deadline; an rpc TIMEOUT is treated as gray failure (alive
   but too slow): it trips the replica's circuit breaker rather than
   killing it, and a half-open probe restores the replica when it
   recovers.  With no routable replica left it falls back to a plain
   ``engine.submit`` on the same stream — the decode engine prefills
   locally.  Either way the caller's stream completes and in-flight
   decode streams never notice.

Tracing: the carrier captured at ``submit`` rides to the worker (its
``engine.prefill`` span) and wraps the transfer + handoff
(``engine.kv_transfer``); ``scheduler.submit`` inside that span parents
the decode engine's ``engine.request`` under it — one trace id from
queue_wait through prefill, kv_transfer and decode, across three
processes.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from tpu_air.core.runtime import ActorDiedError, RemoteError
from tpu_air.faults.retry import (
    Backoff,
    CircuitBreaker,
    Deadline,
    DeadlineExceededError,
)

from ..types import EngineConfig, ResponseStream


class DisaggRouter:
    """Prefill-anywhere, decode-here request router."""

    def __init__(self, checkpoint, engine_config: Optional[EngineConfig] = None,
                 *, prefill_replicas: int = 2, dtype: Optional[str] = None,
                 mesh: Optional[tuple] = None, engine=None,
                 prefill_timeout: float = 120.0, worker_pages: Optional[int] = None,
                 breaker_reset_s: float = 5.0, name: str = "disagg"):
        if prefill_replicas < 1:
            raise ValueError("prefill_replicas must be >= 1")
        self.name = name
        self.config = engine_config or EngineConfig()
        self._prefill_timeout = prefill_timeout
        self._lock = threading.Lock()
        self._rid = 0
        self.fallbacks = 0
        self.reroutes = 0
        self.handoffs = 0
        self._rr = 0  # rotates least-loaded ties so idle replicas alternate

        if engine is not None:
            self.engine = engine
        else:
            model, params = checkpoint.get_model(dtype=dtype)
            if mesh is not None:
                from .mesh_engine import MeshEngine

                dp, tp = mesh
                self.engine = MeshEngine(
                    model, params, self.config, dp=dp, tp=tp,
                    name=f"{name}-decode")
            else:
                from ..engine import InferenceEngine

                self.engine = InferenceEngine(
                    model, params, self.config, name=f"{name}-decode")

        import tpu_air

        from .prefill_worker import PrefillWorker

        worker_cls = tpu_air.remote(PrefillWorker)
        self._workers = [
            worker_cls.remote(
                checkpoint, page_len=self.config.page_len,
                slot_len=self.config.slot_len, num_pages=worker_pages,
                dtype=dtype, name=f"{name}-prefill-{i}",
            )
            for i in range(prefill_replicas)
        ]
        self._alive = [True] * prefill_replicas
        self._inflight = [0] * prefill_replicas
        # retry discipline (tpu_air.faults.retry): one breaker per replica
        # gates gray failures, one seeded backoff paces re-routes.  _sleep
        # is injectable so the storm regression test can record the delays.
        self._breakers = [
            CircuitBreaker(failure_threshold=1, reset_timeout_s=breaker_reset_s)
            for _ in range(prefill_replicas)
        ]
        self._backoff = Backoff(base=0.05, cap=1.0, seed=0)
        self._sleep = time.sleep
        self.retries = 0
        self.engine.metrics.set_topology(
            disagg="on", prefill_replicas=prefill_replicas,
            role="decode",
        )

    # -- submission ------------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None, *,
               priority: str = "interactive",
               deadline_ms: Optional[float] = None) -> ResponseStream:
        """Queue one prompt through the disaggregated path; the stream is
        live immediately (tokens start at first-token handoff).
        ``priority`` rides through to the decode engine's admission (the
        handoff itself bypasses a decode-side drain — this router admitted
        the work before any drain began).  ``deadline_ms`` (absolute
        unix-epoch ms) bounds the whole dispatch: re-routes never retry
        past it, and the decode engine's queue sweep enforces it after
        handoff."""
        from tpu_air.observability.tracing import current_propagation

        # surface draining at the front door, BEFORE spending prefill work
        if getattr(self.engine, "draining", False):
            from ..types import EngineDrainingError

            raise EngineDrainingError(
                f"decode engine {self.engine.name!r} is draining")
        with self._lock:
            self._rid += 1
            rid = self._rid
        stream = ResponseStream(rid)
        carrier = current_propagation()
        t = threading.Thread(
            target=self._dispatch,
            args=(list(prompt), max_new_tokens, stream, carrier, priority,
                  deadline_ms),
            name=f"{self.name}-dispatch-{rid}", daemon=True,
        )
        t.start()
        return stream

    def generate(self, prompts, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = 120.0) -> List[List[int]]:
        streams = [self.submit(p, max_new_tokens) for p in prompts]
        return [s.result(timeout) for s in streams]

    # -- replica choice --------------------------------------------------------
    def _pick_replica(self) -> Optional[int]:
        with self._lock:
            live = [i for i, ok in enumerate(self._alive) if ok]
            if not live:
                return None
            # least-loaded wins; ties rotate round-robin so a stream of
            # sequential (never-overlapping) requests still spreads.  The
            # first candidate whose breaker admits traffic is taken —
            # allow() is only called until it first answers True, so a
            # half-open probe slot is never consumed by a replica we then
            # don't call.
            n = len(self._workers)
            ranked = sorted(
                live, key=lambda j: (self._inflight[j], (j - self._rr) % n))
            for i in ranked:
                if self._breakers[i].allow():
                    self._rr = i + 1
                    self._inflight[i] += 1
                    return i
            return None

    def _mark_dead(self, i: int) -> None:
        with self._lock:
            if self._alive[i]:
                self._alive[i] = False
                self.reroutes += 1

    def live_prefill_replicas(self) -> int:
        with self._lock:
            return sum(self._alive)

    # -- draining (passthrough to the decode engine) ---------------------------
    def drain(self) -> None:
        """Refuse new submits; queued + in-flight work (including handoffs
        already dispatched) retires normally on the decode engine."""
        self.engine.drain()

    @property
    def draining(self) -> bool:
        return getattr(self.engine, "draining", False)

    def drained(self) -> bool:
        return self.engine.drained()

    # -- the per-request dispatcher -------------------------------------------
    def _dispatch(self, prompt, max_new, stream, carrier, priority,
                  deadline_ms=None) -> None:
        try:
            self._dispatch_inner(prompt, max_new, stream, carrier, priority,
                                 deadline_ms)
        except BaseException as e:  # never strand the caller's stream
            stream._finish(e)

    def _dispatch_inner(self, prompt, max_new, stream, carrier,
                        priority, deadline_ms=None) -> None:
        import tpu_air
        from tpu_air.observability.tracing import task_span

        from .kv_transfer import payload_nbytes, payload_pages

        deadline = Deadline.at_ms(deadline_ms)
        # bounded re-route (the death-storm fix): at most two passes over
        # the replica set, capped-exponential backoff + jitter between
        # failures, and no attempt ever launched past the deadline
        max_attempts = 2 * len(self._workers)
        result = None
        attempts = 0
        while result is None and attempts < max_attempts:
            if deadline is not None and deadline.expired:
                raise DeadlineExceededError(
                    f"deadline passed during prefill re-route "
                    f"(after {attempts} failed attempts)")
            i = self._pick_replica()
            if i is None:
                break  # every prefill replica is dead or breaker-open
            try:
                ref = self._workers[i].prefill.remote(prompt, carrier)
                result = tpu_air.get(ref, timeout=self._prefill_timeout)
                self._breakers[i].record_success()
            except (ActorDiedError, RemoteError):
                # confirmed death: out of rotation permanently (respawn is
                # the deployment layer's job, not this router's)
                self._mark_dead(i)
                attempts += 1
                with self._lock:
                    self.retries += 1
                self._sleep(self._backoff.next_delay(attempts))
            except TimeoutError:
                # gray failure: alive but too slow — trip the breaker; its
                # half-open probe restores the replica if it recovers
                self._breakers[i].record_failure()
                attempts += 1
                with self._lock:
                    self.retries += 1
                self._sleep(self._backoff.next_delay(attempts))
            finally:
                with self._lock:
                    self._inflight[i] -= 1
        if result is None:
            # no live prefill capacity: the decode engine prefills locally
            # on the SAME stream — degraded, never dropped
            with self._lock:
                self.fallbacks += 1
            # internal path: like submit_prefilled, a fallback is work this
            # router ALREADY admitted, so it rides through a decode-side
            # drain that began mid-dispatch instead of erroring the stream
            self.engine._enqueue(self.engine._make_request(
                prompt, max_new, stream, priority,
                admit_while_draining=True, deadline_ms=deadline_ms))
            return
        with task_span("engine.kv_transfer", carrier) as sp:
            payload = tpu_air.get(result["kv"])
            if sp is not None and hasattr(sp, "attrs"):
                sp.attrs.update({
                    "kv_bytes": payload_nbytes(payload),
                    "pages": payload_pages(payload),
                    "prompt_len": result["prompt_len"],
                })
            # scheduler.submit captures THIS span as the request's trace
            # parent: decode joins the same trace as prefill + transfer
            self.engine.submit_prefilled(
                prompt, result["first_token"], payload, max_new,
                stream=stream, priority=priority, deadline_ms=deadline_ms)
        with self._lock:
            self.handoffs += 1

    # -- observability / lifecycle --------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "name": self.name,
                "prefill_replicas": len(self._workers),
                "live_prefill_replicas": sum(self._alive),
                "handoffs": self.handoffs,
                "reroutes": self.reroutes,
                "fallbacks": self.fallbacks,
                "retries": self.retries,
                "breakers": [b.state for b in self._breakers],
            }
            alive = list(self._alive)  # snapshot: _mark_dead runs concurrently
        worker_stats = []
        for i, w in enumerate(self._workers):
            if not alive[i]:
                worker_stats.append({"name": f"{self.name}-prefill-{i}",
                                     "dead": True})
                continue
            try:
                import tpu_air

                worker_stats.append(
                    tpu_air.get(w.stats.remote(), timeout=10.0))
            except (ActorDiedError, RemoteError, TimeoutError):
                self._mark_dead(i)
                worker_stats.append({"name": f"{self.name}-prefill-{i}",
                                     "dead": True})
        out["workers"] = worker_stats
        out["engine"] = self.engine.metrics.snapshot()
        return out

    def close(self) -> None:
        import tpu_air

        self.engine.close()
        with self._lock:
            alive = list(self._alive)  # snapshot: _mark_dead runs concurrently
        for i, w in enumerate(self._workers):
            if alive[i]:
                try:
                    tpu_air.kill(w)
                except Exception:  # best-effort teardown races actor death
                    pass
