"""MeshEngine — the paged engine over a leased ``(dp, tp)`` device mesh.

Same host loop, scheduler, slot table and token streams as
:class:`~tpu_air.engine.InferenceEngine`; what changes is WHERE state
lives and which jit wraps the step bodies:

* **lease** — when the tpu_air runtime is up, the engine takes a shaped
  chip lease (``Runtime.lease_chips`` — topology-aware, honors queued
  reservations) and builds its mesh over those devices, releasing the
  lease on ``close()``; without a runtime it meshes over the visible
  devices directly (the CPU-rig and bench path).
* **params** — sharded once at construction via ``lm_param_spec`` (q/k/v
  and SwiGLU gate/up over ``model`` on the output dim, o/down on the
  input dim, embeddings/norms replicated).
* **KV pages** — the page pools shard over ``data``; the
  :class:`~tpu_air.engine.dist.pool.ShardedPagedPool` keeps every slot's
  pages (null page included) inside that slot's own dp shard so
  ``gather_pages`` and the decode scatter stay shard-local, and XLA's
  SPMD partitioner inserts only the tp all-reduces the matmuls need.
* **admission** — capacity is gated PER dp REPLICA (a full replica can't
  borrow pages across a shard boundary): the predicate simulates the
  slot each candidate will land in (lowest free row first — the
  SlotManager's acquire order) and reserves against that replica.

Token parity with the single-chip engine and offline ``generate()`` is
the acceptance anchor, pinned by tests/test_kvpool.py's parity matrix on
the forced-8-device CPU mesh and by the subprocess rig in
tests/_mesh_parity_driver.py.
"""

from __future__ import annotations

from typing import List, Optional

import jax

from tpu_air.models.lm.generate import init_paged_cache
from tpu_air.parallel.mesh import make_mesh, visible_devices
from tpu_air.parallel.sharding import lm_param_shardings, lm_param_spec, \
    shard_params

from ..engine import InferenceEngine
from ..types import EngineConfig
from .pool import ShardedPagedPool
from .sharded import (
    make_sharded_page_copy_fn,
    make_sharded_paged_decode_step_fn,
    make_sharded_prefill_chunk_fn,
    paged_cache_shardings,
)


class MeshEngine(InferenceEngine):
    """Tensor-parallel, data-parallel paged decode over a leased mesh."""

    def __init__(self, model, params, config: Optional[EngineConfig] = None,
                 *, dp: int = 1, tp: int = 1, devices=None,
                 lease_timeout: Optional[float] = 60.0,
                 auto_start: bool = True, name: str = "mesh-engine"):
        cfg = config or EngineConfig()
        if cfg.kv_mode != "paged":
            raise ValueError("MeshEngine requires kv_mode='paged'")
        if cfg.adapter_slots > 0:
            raise ValueError(
                "adapter_slots is single-chip-only for now: the sharded "
                "decode step has no bank shardings (see docs/SERVING.md)")
        if cfg.num_slots % dp != 0:
            raise ValueError(
                f"num_slots {cfg.num_slots} not divisible by dp {dp}")
        self._dp = int(dp)
        self._tp = int(tp)
        self._lease: Optional[List[int]] = None
        self._runtime = None
        devs = self._acquire_devices(devices, lease_timeout)
        self.mesh = make_mesh(("data", "model"), (self._dp, self._tp),
                              devices=devs)
        super().__init__(model, params, cfg, auto_start=auto_start, name=name)
        self.metrics.set_topology(
            lease=self.lease_id, mesh=f"{self._dp}x{self._tp}",
            role="decode", decode_replicas=self._dp,
            mesh_devices=self._dp * self._tp,
        )

    # -- lease / device acquisition ------------------------------------------
    def _acquire_devices(self, devices, lease_timeout):
        n = self._dp * self._tp
        if devices is not None:
            devs = list(devices)
            if len(devs) < n:
                raise ValueError(
                    f"mesh {self._dp}x{self._tp} needs {n} devices, "
                    f"got {len(devs)}")
            return devs[:n]
        from tpu_air.core import runtime as _rt

        if _rt.is_initialized():
            rt = _rt.get_runtime()
            chips = rt.lease_chips(n, timeout=lease_timeout)
            self._lease = chips
            self._runtime = rt
            # lease ids index the global device list; wrap for CPU test
            # meshes whose virtual chip count exceeds the local platform
            all_devs = jax.devices()
            return [all_devs[i % len(all_devs)] for i in chips]
        devs = visible_devices()
        if len(devs) < n:
            raise ValueError(
                f"mesh {self._dp}x{self._tp} needs {n} devices, "
                f"only {len(devs)} visible")
        return devs[:n]

    @property
    def lease_id(self) -> str:
        if self._lease is None:
            return "local"
        return "chips:" + "-".join(str(c) for c in self._lease)

    # -- sharded device state -------------------------------------------------
    def _pages_per_replica(self) -> int:
        cfg = self.config
        if cfg.num_pages is None:
            # slab-equivalent capacity per replica, each with its own null
            # page (dp * this stays dp-divisible, unlike S*ppslot + 1)
            return (cfg.num_slots // self._dp) * cfg.pages_per_slot() + 1
        if cfg.num_pages % self._dp != 0:
            raise ValueError(
                f"num_pages {cfg.num_pages} not divisible by dp {self._dp}")
        per = cfg.num_pages // self._dp
        if per < 2:
            raise ValueError(
                f"num_pages {cfg.num_pages} leaves <2 pages per replica")
        return per

    def _build_paged_state(self) -> None:
        cfg = self.config
        ppr = self._pages_per_replica()
        self.pool = ShardedPagedPool(
            self._dp, ppr, cfg.page_len, cfg.num_slots,
            cfg.pages_per_slot(), prefix_cache=cfg.prefix_cache,
        )
        cache = init_paged_cache(
            self.model, cfg.num_slots, self._dp * ppr, cfg.page_len,
            cfg.pages_per_slot(),
        )
        self._cache_sh = paged_cache_shardings(cache, self.mesh)
        self.cache = jax.tree_util.tree_map(
            jax.device_put, cache, self._cache_sh)
        self._param_sh = lm_param_shardings(self.params, self.mesh)
        self.params = shard_params(self.params, self.mesh, lm_param_spec)
        self._decode_step = make_sharded_paged_decode_step_fn(
            self.model, cfg.slot_len, self.mesh, self._param_sh,
            self._cache_sh)
        self._chunk_fn = make_sharded_prefill_chunk_fn(
            self.model, cfg.page_len, cfg.slot_len, self.mesh,
            self._param_sh, self._cache_sh)
        self._copy_fn = make_sharded_page_copy_fn(self.mesh, self._cache_sh)

    def _build_slab_state(self) -> None:  # pragma: no cover — ctor rejects
        raise ValueError("MeshEngine requires kv_mode='paged'")

    # -- per-replica admission ------------------------------------------------
    def _begin_admission_round(self) -> None:
        self._round_reserved_r = [0] * self._dp
        # acquire order: lowest free row first — the predicate must know
        # which replica each admit lands in before any acquire happens
        self._round_free = self.slots.free_indices()

    def _can_admit(self, req) -> bool:
        if not self._round_free:
            return False
        idx = self._round_free[0]
        r = self.pool.replica_of(idx)
        need = self.pool.worst_case_pages(len(req.prompt), req.max_new_tokens)
        if self._round_reserved_r[r] + need > self.pool.replica_capacity(r):
            return False
        self._round_reserved_r[r] += need
        self._round_free.pop(0)
        return True

    # -- sharded-layout hooks -------------------------------------------------
    def _null_entry(self, slot_index: int) -> int:
        return self.pool.null_page_of(slot_index)

    def _insert_shipped_pages(self, cache, page_ids, payload):
        cache = super()._insert_shipped_pages(cache, page_ids, payload)
        # the eager scatters above may not preserve the pjit layout; pin
        # the rebuilt leaves back onto the engine shardings before the
        # donated decode step sees them
        return jax.tree_util.tree_map(jax.device_put, cache, self._cache_sh)

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        super().close()
        if self._lease is not None and self._runtime is not None:
            try:
                self._runtime.release_chips(self._lease)
            finally:
                self._lease = None
                self._runtime = None
