"""ShardedPagedPool — dp independent PagedKVPools behind one global view.

The device cache shards its page pools ``[P_global, page_len, h*d]`` over
the ``data`` axis, so replica ``r`` physically holds the contiguous page
range ``[r * pages_per_replica, (r+1) * pages_per_replica)``.  This class
keeps the host bookkeeping consistent with that layout: slots are split
evenly across replicas (slot ``s`` lives on replica ``s // (S/dp)``), each
replica runs its OWN single-chip :class:`PagedKVPool` over local page ids,
and every id crossing the engine boundary is offset into the global range
— including the null page, so replica ``r``'s masked rides scatter into
``r * pages_per_replica`` (its own pinned null page) and never cross a
shard.  Prefix sharing therefore happens PER REPLICA: two slots on the
same replica share pages, slots on different replicas each keep their own
copy (cross-shard sharing would turn every gather into a collective).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..kvpool import PagedKVPool
from ..kvpool.pool import AdmitPlan


class ShardedPagedPool:
    """Per-dp-replica block tables + refcounts presenting the single-pool
    interface the engine host loop drives."""

    def __init__(self, dp: int, pages_per_replica: int, page_len: int,
                 num_slots: int, pages_per_slot: int,
                 prefix_cache: bool = True):
        if num_slots % dp != 0:
            raise ValueError(
                f"num_slots {num_slots} not divisible by dp {dp}")
        self.dp = dp
        self.page_len = page_len
        self.pages_per_slot = pages_per_slot
        self.num_slots = num_slots
        self.slots_per_replica = num_slots // dp
        self.pages_per_replica = pages_per_replica
        self.replicas: List[PagedKVPool] = [
            PagedKVPool(pages_per_replica, page_len, self.slots_per_replica,
                        pages_per_slot, prefix_cache=prefix_cache)
            for _ in range(dp)
        ]

    # -- id routing ----------------------------------------------------------
    def replica_of(self, slot: int) -> int:
        return slot // self.slots_per_replica

    def _local(self, slot: int) -> Tuple[PagedKVPool, int]:
        r, s = divmod(slot, self.slots_per_replica)
        return self.replicas[r], s

    def _offset(self, slot: int) -> int:
        return self.replica_of(slot) * self.pages_per_replica

    def null_page_of(self, slot: int) -> int:
        """The GLOBAL id of the null page in the slot's own shard (the
        MeshEngine masks non-decoding rows with this, not global 0)."""
        return self._offset(slot)

    # -- engine-facing surface (PagedKVPool contract, global ids) ------------
    @property
    def block_table(self) -> np.ndarray:
        out = np.empty((self.num_slots, self.pages_per_slot), np.int32)
        spr = self.slots_per_replica
        for r, pool in enumerate(self.replicas):
            out[r * spr:(r + 1) * spr] = (
                pool.block_table + r * self.pages_per_replica)
        return out

    def worst_case_pages(self, prompt_len: int, budget: int) -> int:
        return self.replicas[0].worst_case_pages(prompt_len, budget)

    def capacity(self) -> int:
        """Aggregate obtainable pages — for gauges only; admission gates on
        :meth:`replica_capacity` (a full replica can't borrow from another)."""
        return sum(p.capacity() for p in self.replicas)

    def replica_capacity(self, replica: int) -> int:
        return self.replicas[replica].capacity()

    def admit(self, slot: int, prompt, budget: int,
              share: bool = True) -> AdmitPlan:
        pool, s = self._local(slot)
        return pool.admit(s, prompt, budget, share=share)

    def chunk_row(self, slot: int, start: int, null_target: bool):
        pool, s = self._local(slot)
        # local row ids (null included) shift into the replica's page range
        return pool.chunk_row(s, start, null_target) + self._offset(slot)

    def register(self, slot: int, prompt) -> int:
        pool, s = self._local(slot)
        return pool.register(s, prompt)

    def resolve_cow(self, slot: int) -> Optional[Tuple[int, int]]:
        pool, s = self._local(slot)
        cow = pool.resolve_cow(s)
        if cow is None:
            return None
        dst, src = cow
        return dst + self._offset(slot), src + self._offset(slot)

    def prompt_page_ids(self, slot: int, n_tokens: int) -> List[int]:
        pool, s = self._local(slot)
        off = self._offset(slot)
        return [p + off for p in pool.prompt_page_ids(s, n_tokens)]

    def release(self, slot: int) -> None:
        pool, s = self._local(slot)
        pool.release(s)

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        per = [p.stats() for p in self.replicas]
        out = {"dp_replicas": self.dp, "page_len": self.page_len}
        for key in per[0]:
            if key == "page_len":
                continue
            vals = [s.get(key, 0) for s in per]
            if key == "prefix_hit_rate":
                looked = sum(s.get("prefix_hits", 0) + s.get("prefix_misses", 0)
                             for s in per)
                hits = sum(s.get("prefix_hits", 0) for s in per)
                out[key] = (hits / looked) if looked else 0.0
            else:
                out[key] = sum(vals)
        return out
