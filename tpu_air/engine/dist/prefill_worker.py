"""PrefillWorker — chunked prefill as a standalone actor family.

Disaggregation splits the two phases of a request across replicas:
prefill is compute-bound (one big attention pass over the prompt),
decode is memory-bound (one token per step against a growing KV cache).
A PrefillWorker runs ONLY the first phase: it drives the same
page-granular chunk program the engine uses
(``make_lm_prefill_chunk_fn``) against a private single-slot paged
cache, keeps a per-worker prefix cache so shared-prompt arrivals skip
recompute, and ships the finished pages + first token out through the
shm object store for a decode engine to land via
``InferenceEngine.submit_prefilled``.

The class is deliberately actor-shaped but not actor-bound: the
constructor keeps only a picklable recipe (checkpoint + shape config —
same discipline as serve's ``_EngineServer``) and builds jax state
lazily on first use, so ``tpu_air.remote(PrefillWorker).remote(...)``
round-trips the instance through the pickled object store; plain local
construction works too (the unit-test path).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from tpu_air.faults import plan as _faults

from .kv_transfer import extract_kv_pages, payload_nbytes, payload_pages


class PrefillWorker:
    """One prefill replica: prompt ids in, ``{"kv": ObjectRef,
    "first_token", "prompt_len"}`` out."""

    def __init__(self, checkpoint, *, page_len: int = 16,
                 slot_len: int = 256, num_pages: Optional[int] = None,
                 dtype: Optional[str] = None, name: str = "prefill"):
        if slot_len % page_len != 0:
            raise ValueError("slot_len must be a multiple of page_len")
        self._checkpoint = checkpoint
        self.page_len = page_len
        self.slot_len = slot_len
        self.pages_per_slot = slot_len // page_len
        # headroom beyond one slot keeps evicted-prefix pages resident
        # across requests (the worker-side prefix cache's working set)
        self.num_pages = (num_pages if num_pages is not None
                          else 4 * self.pages_per_slot + 1)
        self._dtype = dtype
        self.name = name
        self._built = False
        self._prefills = 0
        self._pages_shipped = 0
        self._bytes_shipped = 0

    # -- lazy jax state (unpicklable) ----------------------------------------
    def _ensure_built(self) -> None:
        if self._built:
            return
        from tpu_air.engine.kvpool import PagedKVPool
        from tpu_air.models.lm.generate import (
            init_paged_cache,
            make_lm_prefill_chunk_fn,
        )

        self.model, self.params = self._checkpoint.get_model(
            dtype=self._dtype)
        self.pool = PagedKVPool(self.num_pages, self.page_len, 1,
                                self.pages_per_slot)
        self.cache = init_paged_cache(
            self.model, 1, self.num_pages, self.page_len,
            self.pages_per_slot)
        self._chunk_fn = make_lm_prefill_chunk_fn(
            self.model, self.page_len, self.slot_len)
        self._built = True

    # -- the one rpc ----------------------------------------------------------
    def prefill(self, prompt, carrier: Optional[Dict[str, str]] = None
                ) -> Dict[str, Any]:
        """Run the prompt's chunked prefill, ship the pages, return the
        handoff descriptor.  ``carrier`` continues the submitter's trace:
        this records as the ``engine.prefill`` span of the request's
        single trace, on THIS process."""
        import numpy as np

        import jax.numpy as jnp
        import tpu_air
        from tpu_air.observability.tracing import task_span

        if _faults.enabled():
            # "slow" sleeps past the router's prefill timeout (gray failure:
            # alive but useless); "kill" dies the involuntary way — no
            # cleanup, the router sees the actor-death sentinel
            spec = _faults.perturb("prefill.worker", key=self.name)
            if spec is not None and spec.action == "kill":
                os._exit(1)
        self._ensure_built()
        prompt = [int(t) for t in prompt]
        n = len(prompt)
        if n == 0:
            raise ValueError("empty prompt")
        if n > self.slot_len:
            raise ValueError(
                f"prompt length {n} exceeds worker slot_len {self.slot_len}")
        with task_span("engine.prefill", carrier) as sp:
            t0 = time.monotonic()
            # budget=1: the worker never decodes — it needs the prompt's
            # pages plus the greedy first token, nothing more
            plan = self.pool.admit(0, prompt, 1)
            C = self.page_len
            pad = self.model.config.pad_token_id
            tok = None
            while not plan.done:
                p0 = plan.next_start
                ids = np.full((1, C), pad, np.int32)
                chunk = prompt[p0:p0 + C]
                ids[0, :len(chunk)] = chunk
                is_last = plan.chunks_done == len(plan.chunk_starts) - 1
                last_local = (n - 1 - p0) if is_last else (C - 1)
                row = self.pool.chunk_row(0, p0, plan.null_target)
                self.cache, tok = self._chunk_fn(
                    self.params, self.cache, jnp.asarray(ids),
                    jnp.int32(p0), jnp.int32(last_local), jnp.asarray(row),
                )
                plan.chunks_done += 1
            first = int(np.asarray(tok))
            self.pool.register(0, prompt)
            page_ids = self.pool.prompt_page_ids(0, n)
            payload = extract_kv_pages(self.cache, page_ids)
            # release AFTER extraction: prefix-registered pages stay
            # resident (refcounted) for the next shared-prefix arrival
            self.pool.release(0)
            ref = tpu_air.put(payload)
            nbytes = payload_nbytes(payload)
            self._prefills += 1
            self._pages_shipped += payload_pages(payload)
            self._bytes_shipped += nbytes
            if sp is not None and hasattr(sp, "attrs"):
                sp.attrs.update({
                    "prompt_len": n,
                    "pages": payload_pages(payload),
                    "kv_bytes": nbytes,
                    "chunks": len(plan.chunk_starts),
                    "worker": self.name,
                    "prefill_s": round(time.monotonic() - t0, 6),
                })
        return {"kv": ref, "first_token": first, "prompt_len": n}

    # -- observability ---------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        out = {
            "name": self.name,
            "role": "prefill",
            "prefills": self._prefills,
            "pages_shipped": self._pages_shipped,
            "bytes_shipped": self._bytes_shipped,
            "page_len": self.page_len,
            "slot_len": self.slot_len,
        }
        if self._built:
            out["kvpool"] = self.pool.stats()
        return out

    def ping(self) -> str:
        return "ok"
