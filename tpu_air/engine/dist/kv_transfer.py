"""KV-page extraction/insertion — the payload of a prefill→decode handoff.

A PrefillWorker replica runs chunked prefill into its own private paged
cache, then pulls the prompt's pages out as host numpy arrays keyed by
layer path; the payload travels through the shm object store
(core/object_store.py — zero-copy for the numpy leaves via the arena) and
the decode engine writes the pages into freshly-allocated unshared slots
of ITS pool.  Page-granular device-to-device DMA is the on-TPU follow-up
(ROADMAP item 2); this host round-trip is the correctness path and the
CPU-rig test surface.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from tpu_air.faults import plan as _faults


class KVTransferError(ValueError):
    """A shipped KV payload does not fit the destination cache — wrong
    page count, page shape, or a dtype the destination cannot hold
    losslessly.  Raised *before* any page is written: a migrated stream
    that cannot be inserted cleanly falls back to journal replay instead
    of decoding from silently-corrupted pages."""


def _kv_layers(cache, path=()):
    """Yield ``('/'.join(path), layer_dict)`` for every attention-layer
    cache dict (the ones holding cached_key/cached_value pools)."""
    for k, v in cache.items():
        if not isinstance(v, dict):
            continue
        if "cached_key" in v:
            yield "/".join(path + (k,)), v
        else:
            yield from _kv_layers(v, path + (k,))


def extract_kv_pages(cache, page_ids) -> Dict[str, Dict[str, np.ndarray]]:
    """Pull pages ``page_ids`` (in prompt order) out of a paged cache as
    host arrays: ``{layer_path: {"k": [n, page_len, h*d], "v": ...}}``."""
    if _faults.enabled():
        _faults.perturb("kv.transfer", key=str(len(page_ids)))
    ids = np.asarray(page_ids, np.int32)
    out = {}
    for path, layer in _kv_layers(cache):
        out[path] = {
            "k": np.asarray(layer["cached_key"][ids]),
            "v": np.asarray(layer["cached_value"][ids]),
        }
    return out


def _lossless_cast(src: np.dtype, dst: np.dtype) -> bool:
    """Can every value of ``src`` be represented in ``dst``?  ``safe``
    casting is exactly that rule; exotic dtypes numpy cannot reason about
    (possible with custom cache dtypes) count as lossy."""
    try:
        return bool(np.can_cast(src, dst, casting="safe"))
    except TypeError:
        return False


def validate_kv_payload(cache, page_ids, payload) -> None:
    """Check a shipped payload against the destination cache, raising
    :class:`KVTransferError` on any mismatch — truncated page counts,
    wrong page geometry, missing layers, or lossy dtype narrowing.  Runs
    before any write so a bad payload corrupts nothing."""
    n = len(page_ids)
    for path, layer in _kv_layers(cache):
        pages = payload.get(path)
        if pages is None:
            raise KVTransferError(
                f"kv payload missing layer {path!r} "
                f"(shipped layers: {sorted(payload)})")
        for name, key in (("k", "cached_key"), ("v", "cached_value")):
            if name not in pages:
                raise KVTransferError(
                    f"kv payload at {path!r} missing {name!r} pages")
            arr = np.asarray(pages[name])
            dst = layer[key]
            if arr.ndim != dst.ndim or arr.shape[0] != n:
                raise KVTransferError(
                    f"truncated kv payload at {path}/{name}: shipped "
                    f"shape {arr.shape} for {n} destination page ids")
            if tuple(arr.shape[1:]) != tuple(dst.shape[1:]):
                raise KVTransferError(
                    f"kv page shape mismatch at {path}/{name}: payload "
                    f"pages are {tuple(arr.shape[1:])}, destination pool "
                    f"holds {tuple(dst.shape[1:])}")
            src_dt, dst_dt = arr.dtype, np.dtype(dst.dtype)
            if src_dt != dst_dt and not _lossless_cast(src_dt, dst_dt):
                raise KVTransferError(
                    f"kv dtype mismatch at {path}/{name}: payload "
                    f"{src_dt} does not fit destination {dst_dt} "
                    "losslessly")


def insert_kv_pages(cache, page_ids, payload: Dict[str, Dict[str, np.ndarray]]):
    """Write shipped pages into ``page_ids`` of this cache (functional —
    returns the rebuilt cache; the caller rebinds its donated cache).
    ``page_ids[i]`` receives the payload's i-th page: id lists on both
    sides are in prompt order, so source and destination ids need not
    match — each engine allocates in its own pool.  Raises
    :class:`KVTransferError` (before writing anything) when the payload
    does not fit the destination cache."""
    import jax.numpy as jnp

    validate_kv_payload(cache, page_ids, payload)

    ids = jnp.asarray(np.asarray(page_ids, np.int32))

    def walk(d, path=()):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                if "cached_key" in v:
                    pages = payload["/".join(path + (k,))]
                    out[k] = dict(v)
                    out[k]["cached_key"] = v["cached_key"].at[ids].set(
                        jnp.asarray(pages["k"]).astype(v["cached_key"].dtype))
                    out[k]["cached_value"] = v["cached_value"].at[ids].set(
                        jnp.asarray(pages["v"]).astype(v["cached_value"].dtype))
                else:
                    out[k] = walk(v, path + (k,))
            else:
                out[k] = v
        return out

    return walk(cache)


def payload_nbytes(payload: Dict[str, Dict[str, np.ndarray]]) -> int:
    """Total K+V bytes in a handoff payload (the kv_transfer span attr)."""
    return sum(arr.nbytes for layer in payload.values()
               for arr in layer.values())


def payload_pages(payload: Dict[str, Dict[str, np.ndarray]]) -> int:
    first = next(iter(payload.values()), None)
    return int(first["k"].shape[0]) if first else 0
