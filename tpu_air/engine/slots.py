"""Slot table + KV-slab insertion for the continuous-batching engine.

A *slot* is one row of the engine's fixed decode batch: row ``s`` of every
per-layer flat KV slab ``[S, L_slot, h*d]``.  The host-side
:class:`SlotManager` tracks which request occupies each row and where its
context ends; the device side is one jitted ``dynamic_update_slice`` per
admission that grafts a prefilled cache segment into the free row.

Lifecycle of a slot (docs/SERVING.md §slab lifecycle)::

    free -> [admit] occupied(pos=len(prompt)) -> [decode steps] pos+1 each
         -> [EOS or budget] free again -- no slab zeroing on retirement:
    stale K/V beyond the next occupant's written positions are masked by
    the per-row validity mask (arange <= index[row]) and progressively
    overwritten, so retirement costs exactly one host-side list append.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional

import jax

from .types import Request


@dataclass
class Slot:
    """Host bookkeeping for one slab row (or paged block-table row)."""

    index: int
    request: Optional[Request] = None
    pos: int = 0            # cache write position == tokens in context
    budget_left: int = 0    # decode steps remaining before forced retirement
    # paged engine only: mid-chunked-prefill flag + the pool's AdmitPlan
    # (remaining chunk starts, prefix coverage).  A prefilling slot holds
    # pages and a request but does NOT ride the decode step yet.
    prefilling: bool = False
    plan: Any = None

    @property
    def active(self) -> bool:
        return self.request is not None


class SlotManager:
    """Free-list over the ``S`` slab rows."""

    def __init__(self, num_slots: int):
        self.slots: List[Slot] = [Slot(i) for i in range(num_slots)]
        # pop() takes from the end: keep it ascending-last so admission
        # fills row 0 first (deterministic slot assignment for the parity
        # tests — FIFO arrival k lands in the lowest free row)
        self._free: List[int] = list(range(num_slots))[::-1]

    def free_count(self) -> int:
        return len(self._free)

    def free_indices(self) -> List[int]:
        """Free rows in ACQUIRE order (lowest first) — what an admission
        predicate that must know which row each admit will land in (the
        MeshEngine's per-replica capacity gate) simulates against."""
        return sorted(self._free)

    def active_slots(self) -> List[Slot]:
        return [s for s in self.slots if s.active]

    def occupancy(self) -> int:
        return len(self.slots) - len(self._free)

    def acquire(self) -> Slot:
        slot = self.slots[self._free.pop()]
        assert not slot.active, "acquired an occupied slot"
        return slot

    def release(self, slot: Slot) -> None:
        slot.request = None
        slot.pos = 0
        slot.budget_left = 0
        slot.prefilling = False
        slot.plan = None
        # keep the free list sorted descending so the next acquire still
        # hands out the lowest free row
        self._free.append(slot.index)
        self._free.sort(reverse=True)


def make_insert_fn():
    """Jitted segment insertion: graft a prefilled cache segment (per-layer
    ``[1, Lb, h*d]`` slabs) into slab row ``slot`` of the engine cache.
    The engine cache is donated — insertion updates the pool in place.
    ``cache_index`` leaves pass through: the decode step overwrites them
    from the host-authoritative ``pos`` vector every call."""

    @partial(jax.jit, donate_argnums=(0,))
    def insert(cache: Dict[str, Any], segment: Dict[str, Any], slot):
        def walk(c, s):
            out = {}
            for k, v in c.items():
                if isinstance(v, dict):
                    out[k] = walk(v, s[k])
                elif k == "cache_index":
                    out[k] = v
                else:
                    out[k] = jax.lax.dynamic_update_slice(
                        v, s[k].astype(v.dtype), (slot, 0, 0)
                    )
            return out

        return walk(cache, segment)

    return insert
