"""tpu_air.engine — continuous-batching online inference.

A fixed pool of sequence slots over flat per-layer KV slabs, one
persistent compiled decode step, admission/retirement between steps, and
per-token streaming back to callers.  See docs/SERVING.md for the
architecture and the token-parity contract with offline ``generate``.
"""

from .engine import InferenceEngine
from .metrics import EngineMetrics, snapshot_all
from .scheduler import Scheduler
from .slots import Slot, SlotManager, make_insert_fn
from .types import (
    EngineClosedError,
    EngineConfig,
    EngineOverloadedError,
    Request,
    ResponseStream,
)

__all__ = [
    "EngineClosedError",
    "EngineConfig",
    "EngineMetrics",
    "EngineOverloadedError",
    "InferenceEngine",
    "Request",
    "ResponseStream",
    "Scheduler",
    "Slot",
    "SlotManager",
    "make_insert_fn",
    "snapshot_all",
]
