"""tpu_air.engine — continuous-batching online inference.

A fixed pool of sequence slots over per-layer KV storage — block-table
PAGED pools with prefix sharing and chunked prefill by default
(``kvpool/``), or the PR 1 flat slabs (``kv_mode="slab"``) — one
persistent compiled decode step, admission/retirement between steps, and
per-token streaming back to callers.  The T5 family runs through
:class:`T5Engine`, a window-level variant over the batch-synchronized T5
decode entry points.  See docs/SERVING.md for the architecture and the
token-parity contract with offline ``generate``.
"""

from .dist import DisaggRouter, MeshEngine, PrefillWorker, ShardedPagedPool
from .engine import InferenceEngine
from .kvpool import (
    AdmitPlan,
    BlockAllocator,
    KVPoolOOMError,
    PagedKVPool,
    PrefixCache,
    PrefixMatch,
)
from .metrics import EngineMetrics, snapshot_all
from .scheduler import Scheduler
from .slots import Slot, SlotManager, make_insert_fn
from .t5_engine import T5Engine, T5EngineConfig
from .types import (
    EngineClosedError,
    EngineConfig,
    EngineOverloadedError,
    Request,
    RequestValidationError,
    ResponseStream,
)

__all__ = [
    "AdmitPlan",
    "BlockAllocator",
    "DisaggRouter",
    "EngineClosedError",
    "EngineConfig",
    "EngineMetrics",
    "EngineOverloadedError",
    "InferenceEngine",
    "KVPoolOOMError",
    "MeshEngine",
    "PagedKVPool",
    "PrefillWorker",
    "PrefixCache",
    "PrefixMatch",
    "Request",
    "RequestValidationError",
    "ShardedPagedPool",
    "ResponseStream",
    "Scheduler",
    "Slot",
    "SlotManager",
    "T5Engine",
    "T5EngineConfig",
    "make_insert_fn",
    "snapshot_all",
]
