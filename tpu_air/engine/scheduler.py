"""Admission scheduler: priority-classed FIFO queues with backpressure +
a bounded reorder window.

Policy (docs/SERVING.md §SLO-aware serving): each request carries one of
the :data:`~tpu_air.engine.types.PRIORITIES` classes.  Admission pops
classes strictly in priority order every engine step — iteration-
granularity priority, the Orca framing applied to admission — and WITHIN
a class requests are admitted in arrival order up to the number of free
slots.  The paged engine additionally passes a ``can_admit`` predicate
(does the KV pool have pages for this request right now?) — and a blocked
HEAD no longer blocks its class: admission may look at most
``reorder_window`` entries past the first request that does not fit and
admit later ones that do (a big-prompt head waiting for pages can't
head-of-line-block a stream of small requests that would fit today).
Every such out-of-order admission increments ``reordered_admits``.  A
class whose head stays blocked after the window STOPS the round — lower
classes never steal the pages the blocked higher-class head is waiting
for (no priority inversion).

Backpressure is class-aware: a submit is rejected once the TOTAL queue
depth reaches ``config.queue_cap(priority)`` — best-effort sheds first
(half of ``max_queue`` by default), then batch, and interactive keeps the
full ``max_queue``.

``reorder_window=0`` (or no ``can_admit``) restores strict FIFO within a
class, which keeps the scheduler DETERMINISTIC for a given arrival
schedule — what the engine's token-parity gate tests against (all parity
traffic is single-class, where this scheduler is exactly the old FIFO);
the window itself is also deterministic: lowest-index fitting candidate
wins.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List

from tpu_air.faults.retry import DeadlineExceededError
from tpu_air.observability import tracing as _tracing

from .types import PRIORITIES, EngineConfig, EngineOverloadedError, Request


class Scheduler:
    """Thread-safe priority-classed admission queue over
    :class:`EngineConfig` dials."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self._queues: Dict[str, Deque[Request]] = {
            p: deque() for p in PRIORITIES
        }
        self._lock = threading.Lock()
        self._work = threading.Event()
        self.reordered_admits = 0  # admissions that jumped a blocked head
        # engine-side sheds by class (admission-queue rejections)
        self.rejected_by_class: Dict[str, int] = {p: 0 for p in PRIORITIES}
        # end-to-end deadlines: queued requests past Request.deadline_ms are
        # expired (stream fails with DeadlineExceededError → proxy 504)
        # instead of occupying a slot they can no longer use.  _deadlines
        # counts queued deadline-carrying requests so the per-round sweep is
        # free for deadline-less traffic.
        self.deadline_expired = 0
        self._deadlines = 0

    # -- producer side (any thread) ------------------------------------------
    def submit(self, request: Request) -> None:
        """Enqueue; raises :class:`EngineOverloadedError` when the total
        queue has reached this class's cap (``config.queue_cap``) —
        class-aware backpressure: the caller sees 503, retries."""
        # airlint: disable=CC001 — key-set membership only: _queues' keys
        # are fixed at __init__; the per-class deques mutate under _lock
        if request.priority not in self._queues:
            raise ValueError(
                f"unknown priority {request.priority!r} "
                f"(expected one of {PRIORITIES})"
            )
        if _tracing.enabled():
            # stamp outside the lock: carrier + submit time feed the
            # retirement-time span emission (engine._emit_request_spans)
            request.trace_ctx = _tracing.current_propagation()
            request.t_submit_ns = _tracing.now_ns()
        with self._lock:
            depth = sum(len(q) for q in self._queues.values())
            cap = self.config.queue_cap(request.priority)
            if depth >= cap:
                self.rejected_by_class[request.priority] += 1
                raise EngineOverloadedError(
                    f"engine admission queue full for "
                    f"{request.priority}-class ({depth}/{cap}, "
                    f"max_queue={self.config.max_queue})"
                )
            self._queues[request.priority].append(request)
            if request.deadline_ms is not None:
                self._deadlines += 1
            self._work.set()

    # -- engine-loop side ----------------------------------------------------
    def pop_admissible(self, free_slots: int,
                       can_admit=None) -> List[Request]:
        """Dequeue up to ``free_slots`` requests, classes in priority
        order, FIFO within a class.

        ``can_admit(request) -> bool`` (optional) gates each candidate on
        engine-side capacity (KV pages / the interactive slot reserve);
        the engine's predicate RESERVES capacity when it answers True, so
        one round never over-admits.  When a class's head is blocked, up
        to ``config.reorder_window`` later entries OF THAT CLASS are
        considered in queue order (head-of-line relief); out-of-order
        takes are counted in :attr:`reordered_admits`.  A class whose
        head stays blocked ends the round — lower classes must not claim
        the capacity it is waiting for."""
        out: List[Request] = []
        window = getattr(self.config, "reorder_window", 0)
        with self._lock:
            self._sweep_expired_locked()
            for priority in PRIORITIES:
                queue = self._queues[priority]
                blocked = False
                while queue and len(out) < free_slots:
                    if can_admit is None or can_admit(queue[0]):
                        out.append(queue.popleft())
                        continue
                    # head blocked: bounded look-ahead past it
                    took = None
                    if can_admit is not None and window > 0:
                        for j in range(1, min(window, len(queue) - 1) + 1):
                            if can_admit(queue[j]):
                                took = j
                                break
                    if took is None:
                        blocked = True
                        break
                    cand = queue[took]
                    del queue[took]
                    out.append(cand)
                    self.reordered_admits += 1
                if blocked or len(out) >= free_slots:
                    break
            for r in out:
                if r.deadline_ms is not None:
                    self._deadlines -= 1
            if not any(self._queues.values()):
                self._work.clear()
        if _tracing.enabled() and out:
            t = _tracing.now_ns()
            for r in out:
                if r.t_submit_ns:
                    r.t_admit_ns = t
        return out

    def _sweep_expired_locked(self) -> None:
        """Expire queued requests past their deadline (caller holds _lock).
        ``stream._finish`` is non-blocking (event set + queue put), safe
        under the lock; one wall-clock read covers the whole sweep."""
        if not self._deadlines:
            return
        now_ms = time.time() * 1000.0
        for q in self._queues.values():
            expired = [r for r in q
                       if r.deadline_ms is not None
                       and now_ms >= r.deadline_ms]
            if not expired:
                continue
            dead = {id(r) for r in expired}
            keep = [r for r in q if id(r) not in dead]
            q.clear()
            q.extend(keep)
            for r in expired:
                self.deadline_expired += 1
                self._deadlines -= 1
                r.stream._finish(DeadlineExceededError(
                    f"request {r.request_id} missed its deadline while "
                    f"queued ({r.priority}-class, deadline_ms="
                    f"{r.deadline_ms:.0f})"))

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def depth_by_class(self) -> Dict[str, int]:
        """Per-priority queue depths (admission/autoscaler gauge)."""
        with self._lock:
            return {p: len(q) for p, q in self._queues.items()}

    def drain(self) -> List[Request]:
        """Remove and return every queued request (engine shutdown)."""
        with self._lock:
            out = [r for p in PRIORITIES for r in self._queues[p]]
            for q in self._queues.values():
                q.clear()
            self._deadlines = 0
            self._work.clear()
        return out

    def wait_for_work(self, timeout: float) -> bool:
        """Block until something is queued (or timeout); engine idle-wait."""
        return self._work.wait(timeout)
