"""Admission scheduler: FIFO queue with backpressure + a bounded reorder
window.

Policy (docs/SERVING.md §scheduling): requests are admitted in arrival
order up to the number of free slots each engine step.  The paged engine
additionally passes a ``can_admit`` predicate (does the KV pool have pages
for this request right now?) — and a blocked HEAD no longer blocks the
whole queue: admission may look at most ``reorder_window`` entries past the
first request that does not fit and admit later ones that do (a big-prompt
head waiting for pages can't head-of-line-block a stream of small requests
that would fit today).  Every such out-of-order admission increments
``reordered_admits``.  ``reorder_window=0`` (or no ``can_admit``) restores
strict FIFO, which keeps the scheduler DETERMINISTIC for a given arrival
schedule — what the engine's token-parity gate tests against; the window
itself is also deterministic: lowest-index fitting candidate wins.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, List

from tpu_air.observability import tracing as _tracing

from .types import EngineConfig, EngineOverloadedError, Request


class Scheduler:
    """Thread-safe FIFO admission queue over :class:`EngineConfig` dials."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self._queue: Deque[Request] = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self.reordered_admits = 0  # admissions that jumped a blocked head

    # -- producer side (any thread) ------------------------------------------
    def submit(self, request: Request) -> None:
        """Enqueue; raises :class:`EngineOverloadedError` when the queue is
        at ``max_queue`` (backpressure — the caller sees 503, retries)."""
        if _tracing.enabled():
            # stamp outside the lock: carrier + submit time feed the
            # retirement-time span emission (engine._emit_request_spans)
            request.trace_ctx = _tracing.current_propagation()
            request.t_submit_ns = _tracing.now_ns()
        with self._lock:
            if len(self._queue) >= self.config.max_queue:
                raise EngineOverloadedError(
                    f"engine admission queue full "
                    f"({len(self._queue)}/{self.config.max_queue})"
                )
            self._queue.append(request)
            self._work.set()

    # -- engine-loop side ----------------------------------------------------
    def pop_admissible(self, free_slots: int,
                       can_admit=None) -> List[Request]:
        """Dequeue up to ``free_slots`` requests in FIFO order.

        ``can_admit(request) -> bool`` (optional) gates each candidate on
        engine-side capacity (KV pages, for the paged pool); the engine's
        predicate RESERVES capacity when it answers True, so one round
        never over-admits.  When the head is blocked, up to
        ``config.reorder_window`` later entries are considered in queue
        order (head-of-line relief); out-of-order takes are counted in
        :attr:`reordered_admits`."""
        out: List[Request] = []
        window = getattr(self.config, "reorder_window", 0)
        with self._lock:
            while self._queue and len(out) < free_slots:
                if can_admit is None or can_admit(self._queue[0]):
                    out.append(self._queue.popleft())
                    continue
                # head blocked: bounded look-ahead past it
                took = None
                if can_admit is not None and window > 0:
                    for j in range(1, min(window, len(self._queue) - 1) + 1):
                        if can_admit(self._queue[j]):
                            took = j
                            break
                if took is None:
                    break
                cand = self._queue[took]
                del self._queue[took]
                out.append(cand)
                self.reordered_admits += 1
            if not self._queue:
                self._work.clear()
        if _tracing.enabled() and out:
            t = _tracing.now_ns()
            for r in out:
                if r.t_submit_ns:
                    r.t_admit_ns = t
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def drain(self) -> List[Request]:
        """Remove and return every queued request (engine shutdown)."""
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
            self._work.clear()
        return out

    def wait_for_work(self, timeout: float) -> bool:
        """Block until something is queued (or timeout); engine idle-wait."""
        return self._work.wait(timeout)
