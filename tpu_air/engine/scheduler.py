"""Admission scheduler: FIFO queue with backpressure + bucket grouping.

Policy (docs/SERVING.md §scheduling): requests are admitted strictly in
arrival order — never reordered for bucket affinity — up to the number of
free slots each engine step.  FIFO keeps the scheduler DETERMINISTIC for a
given arrival schedule, which is what the engine's token-parity gate tests
against; bucket grouping is only an ordering hint WITHIN one admission
round so same-bucket prefills sit adjacent (shared compiled program,
warm icache), not a reordering across rounds.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, List

from tpu_air.observability import tracing as _tracing

from .types import EngineConfig, EngineOverloadedError, Request


class Scheduler:
    """Thread-safe FIFO admission queue over :class:`EngineConfig` dials."""

    def __init__(self, config: EngineConfig):
        self.config = config
        self._queue: Deque[Request] = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()

    # -- producer side (any thread) ------------------------------------------
    def submit(self, request: Request) -> None:
        """Enqueue; raises :class:`EngineOverloadedError` when the queue is
        at ``max_queue`` (backpressure — the caller sees 503, retries)."""
        if _tracing.enabled():
            # stamp outside the lock: carrier + submit time feed the
            # retirement-time span emission (engine._emit_request_spans)
            request.trace_ctx = _tracing.current_propagation()
            request.t_submit_ns = _tracing.now_ns()
        with self._lock:
            if len(self._queue) >= self.config.max_queue:
                raise EngineOverloadedError(
                    f"engine admission queue full "
                    f"({len(self._queue)}/{self.config.max_queue})"
                )
            self._queue.append(request)
            self._work.set()

    # -- engine-loop side ----------------------------------------------------
    def pop_admissible(self, free_slots: int) -> List[Request]:
        """Dequeue up to ``free_slots`` requests in FIFO order."""
        out: List[Request] = []
        with self._lock:
            while self._queue and len(out) < free_slots:
                out.append(self._queue.popleft())
            if not self._queue:
                self._work.clear()
        if _tracing.enabled() and out:
            t = _tracing.now_ns()
            for r in out:
                if r.t_submit_ns:
                    r.t_admit_ns = t
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def drain(self) -> List[Request]:
        """Remove and return every queued request (engine shutdown)."""
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
            self._work.clear()
        return out

    def wait_for_work(self, timeout: float) -> bool:
        """Block until something is queued (or timeout); engine idle-wait."""
        return self._work.wait(timeout)
