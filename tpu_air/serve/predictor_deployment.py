"""PredictorDeployment — serve any Predictor from a Checkpoint over HTTP.

Parity: ``serve.run(PredictorDeployment.options(name="XGBoostService",
num_replicas=2, route_prefix="/rayair").bind(XGBoostPredictor, best_ckpt,
http_adapter=pandas_read_json))`` (Introduction_to_Ray_AI_Runtime.ipynb:cc-71).

Each replica instantiates ``predictor_cls.from_checkpoint(checkpoint)`` once
(model weights land on the replica's chip lease / host memory), then serves
``adapter(body) → predictor.predict → jsonable`` per request.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .deployment import Deployment
from .http_adapters import pandas_read_json


class _PredictorServer:
    def __init__(
        self,
        predictor_cls,
        checkpoint,
        http_adapter: Optional[Callable[[bytes], Any]] = None,
        predict_kwargs: Optional[dict] = None,
        **from_checkpoint_kwargs,
    ):
        self._predictor = predictor_cls.from_checkpoint(
            checkpoint, **from_checkpoint_kwargs
        )
        self._http_adapter = http_adapter or pandas_read_json
        self._predict_kwargs = predict_kwargs or {}

    def __call__(self, data):
        out = self._predictor.predict(data, **self._predict_kwargs)
        return out


PredictorDeployment = Deployment(
    func_or_class=_PredictorServer,
    name="PredictorDeployment",
    num_replicas=1,
)
