"""HTTP proxy + serve.run/shutdown.

Request path (SURVEY.md §3.5): client POST :8000 → proxy → route match →
SLO admission (priority class + token budget, serve/admission.py) →
least-loaded replica actor → http_adapter(body) → predictor/callable →
JSON response.  The proxy is a threaded HTTP server owned by the driver
process (the "HTTP proxy actor" of the reference, cc-71,74,79).

Serving contract under load (docs/SERVING.md §SLO-aware serving):

* new work (blocking generate, or a streaming ``{"action": "submit"}``)
  passes the route's :class:`~tpu_air.serve.admission.AdmissionController`
  first — best-effort/batch queue proxy-side or shed (503 +
  ``Retry-After``) as engine queue depth climbs, interactive admits;
* streaming polls BYPASS admission (the work is already admitted) and PIN
  to the replica that took the submit via the ``x-tpu-air-replica``
  header, which the proxy round-trips on every routed response;
* replica-side backpressure (``EngineOverloadedError``) and drain refusal
  (``EngineDrainingError``) both map to 503 — retry semantics, nothing
  broken;
* every streaming submit with an explicit token budget is JOURNALED
  (serve/supervisor.py): when a pinned poll finds its replica dead, the
  proxy REPLAYS the request on a live replica with the already-streamed
  tokens as a forced prefix — the client sees a stall, never a 5xx, and
  greedy decoding keeps the stream token-identical;
* clients may send ``deadline_ms`` (a RELATIVE budget in ms) on new work;
  the proxy stamps the ABSOLUTE deadline at admission and propagates it
  end-to-end — queue expiry, re-routes and replays all respect it, and an
  exhausted budget maps to 504 + ``Retry-After``;
* ``serve.rollout(prefix)`` swaps every replica zero-downtime (drain
  before kill — pinned polls keep landing on the draining replica until
  its streams are fully delivered).

Deterministic chaos: ``serve.run(..., fault_plan=FaultPlan(...))``
installs a seeded fault plan (tpu_air.faults) before replicas spawn, so
the whole serve plane — proxy hooks, replicas, prefill workers — runs the
same fault schedule for the same seed (docs/RESILIENCE.md).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from tpu_air.core import api as core_api
from tpu_air.core.runtime import RemoteError
from tpu_air.faults import plan as _faults
from tpu_air.faults.retry import DeadlineExceededError
from tpu_air.observability import tracing as _tracing

from .admission import (
    AdmissionController,
    AdmissionPolicy,
    AdmissionShedError,
    QuotaExceededError,
)
from .autoscaler import Autoscaler, AutoscalerConfig
from .deployment import (
    Application,
    DeploymentHandle,
    NoLiveReplicasError,
    ReplicaGoneError,
    start_replicas,
)
from .supervisor import PreemptionWatcher, RequestJournal, journaled_poll

#: request header that pins streaming polls to the replica holding their
#: stream; the proxy sets it on every routed response
REPLICA_HEADER = "x-tpu-air-replica"


def _to_jsonable(obj: Any) -> Any:
    import numpy as np

    try:
        import pandas as pd

        if isinstance(obj, pd.DataFrame):
            return obj.to_dict(orient="records")
        if isinstance(obj, pd.Series):
            return obj.tolist()
    except ImportError:
        pass
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(v) for v in obj]
    return obj


class _ServeState:
    def __init__(self):
        self.routes: Dict[str, DeploymentHandle] = {}
        self.admission: Dict[str, AdmissionController] = {}
        self.autoscalers: Dict[str, Autoscaler] = {}
        self.watchers: Dict[str, PreemptionWatcher] = {}
        self.server: Optional[ThreadingHTTPServer] = None
        self.thread: Optional[threading.Thread] = None
        self.port: Optional[int] = None
        self.lock = threading.Lock()
        # in-flight streaming requests (prompt + delivered prefix) for
        # crash replay — serve/supervisor.py
        self.journal = RequestJournal()
        # metered-tenant streams holding an in-flight quota unit:
        # (prefix, pin, request_id) -> (controller, adapter_id); released
        # when a poll observes the stream's end (or its terminal error)
        self.tenant_streams: Dict[tuple, tuple] = {}

    def match(self, path: str):
        """Longest-prefix route match → ``(prefix, handle)`` (the prefix
        keys the route's admission controller/autoscaler), or None."""
        best = None
        for prefix, handle in self.routes.items():
            norm = prefix.rstrip("/") or "/"
            if path == norm or path.startswith(norm + "/") or norm == "/":
                if best is None or len(norm) > len(best[2]):
                    best = (prefix, handle, norm)
        return (best[0], best[1]) if best else None


_state = _ServeState()


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 keep-alive: streaming clients poll at high frequency, and a
    # connection per poll costs a proxy thread spawn each (ThreadingHTTPServer
    # is thread-per-CONNECTION) — persistent connections amortize it to one
    # thread per client.  Safe because _respond always sends Content-Length.
    # Nagle must be off or small responses on the reused socket wait out the
    # peer's delayed ACK (~40ms per poll).
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True

    def log_message(self, *args):  # quiet
        pass

    def _respond(self, code: int, payload: Any,
                 headers: Optional[Dict[str, str]] = None):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        # surface the request's trace to the client: curl the trace id
        # straight into /api/traces?trace_id=... (docs/OBSERVABILITY.md)
        ctx = _tracing.current_context()
        if ctx is not None:
            self.send_header("traceparent", _tracing.format_traceparent(ctx))
            self.send_header("x-tpu-air-trace-id", ctx.trace_id)
        self.end_headers()
        self.wfile.write(body)

    def _dispatch(self):
        if not _tracing.enabled():
            self._dispatch_inner()
            return
        # root span per HTTP request; an inbound W3C traceparent header
        # continues the caller's trace instead of rooting a new one
        parent = _tracing.extract_traceparent(self.headers.get("traceparent"))
        with _tracing.span(
            "http.request", parent=parent,
            attrs={"path": self.path.split("?")[0],
                   "method": self.command},
        ) as sp:
            self._dispatch_inner(sp)

    def _dispatch_inner(self, sp=None):
        from urllib.parse import urlsplit

        self.path = urlsplit(self.path).path
        if self.path.rstrip("/") == "/-/routes":
            self._respond(200, {p: h.deployment_name for p, h in _state.routes.items()})
            return
        if self.path.rstrip("/") == "/-/healthz":
            # per-deployment replica health: degraded (any route with zero
            # live replicas) is a 503 so load balancers can act on it
            detail = {
                p: {"name": h.deployment_name, "live_replicas": h.live_replicas()}
                for p, h in _state.routes.items()
            }
            healthy = all(d["live_replicas"] > 0 for d in detail.values())
            self._respond(
                200 if healthy else 503,
                {"status": "ok" if healthy else "degraded", "deployments": detail},
            )
            return
        if self.path.rstrip("/") == "/-/stats":
            # serve-plane control state per route: admission outcomes and
            # gauges, autoscaler decisions (docs/OBSERVABILITY.md)
            self._respond(200, serve_control_stats())
            return
        matched = _state.match(self.path)
        if matched is None:
            self._respond(404, {"error": f"no deployment for route {self.path!r}"})
            return
        prefix, handle = matched
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        pin = None
        # a metered tenant's in-flight quota unit taken at admit and owed
        # a release by THIS request (blocking calls release on response;
        # streaming submits hand the unit to the stream's lifetime)
        quota_hold = None
        try:
            try:
                payload = json.loads(body) if body else None
            except ValueError:
                payload = None  # non-JSON body: the replica's adapter decides
            action = payload.get("action") if isinstance(payload, dict) else None
            call_timeout = 300.0
            if isinstance(payload, dict):
                if action == "poll":
                    # already-admitted work: no admission, and the poll must
                    # land on the replica holding the stream's state
                    pin = self.headers.get(REPLICA_HEADER) or None
                    if _faults.enabled():
                        # deterministic chaos: delay this poll, or kill the
                        # pinned replica out from under it — the replay
                        # path's regression surface
                        spec = _faults.perturb("proxy.poll", key=prefix)
                        if (spec is not None and spec.action == "kill"
                                and pin):
                            from tpu_air.core.runtime import get_runtime
                            get_runtime().crash_actor(pin)
                else:
                    if _faults.enabled():
                        # deterministic chaos: delay this request, or crash
                        # a serving replica at admission time — the fleet
                        # loses capacity exactly when load arrives, the
                        # step change airwatch's detector must catch
                        spec = _faults.perturb("proxy.request", key=prefix)
                        if spec is not None and spec.action == "kill":
                            with handle._lock:
                                victims = [r._actor_id
                                           for r in handle._replicas]
                            if victims:
                                from tpu_air.core.runtime import get_runtime
                                get_runtime().crash_actor(victims[0])
                    dirty = False
                    controller = _state.admission.get(prefix)
                    if controller is not None:
                        priority = str(
                            payload.get("priority") or "interactive")
                        adapter_id = payload.get("adapter_id")
                        if adapter_id is not None:
                            adapter_id = str(adapter_id)
                        tenant = payload.get("tenant")
                        if tenant is not None:
                            tenant = str(tenant)
                        # raises QuotaExceededError (429) / shed (503);
                        # ``tenant`` only relabels billing attribution —
                        # quota metering stays keyed on adapter_id
                        controller.admit(priority, adapter_id=adapter_id,
                                         tenant=tenant)
                        if adapter_id is not None:
                            quota_hold = (controller, adapter_id)
                        clamped = controller.policy.clamp_budget(
                            priority, payload.get("max_new_tokens"),
                            adapter_id)
                        if clamped is not None and clamped != payload.get(
                                "max_new_tokens"):
                            payload["max_new_tokens"] = clamped
                            dirty = True
                    budget_ms = payload.get("deadline_ms")
                    if budget_ms is not None:
                        # clients send a RELATIVE budget; the proxy stamps
                        # the ABSOLUTE unix-epoch deadline at admission so
                        # every downstream hop (queue sweep, re-route,
                        # replay) measures against one clock instead of
                        # re-extending the budget per hop
                        budget_ms = float(budget_ms)
                        if budget_ms <= 0:
                            raise DeadlineExceededError(
                                "deadline_ms must be a positive budget in "
                                f"milliseconds, got {budget_ms:g}")
                        payload["deadline_ms"] = (
                            time.time() * 1000.0 + budget_ms)
                        dirty = True
                        # the routed call itself must not outlive the budget
                        call_timeout = min(300.0, budget_ms / 1000.0 + 5.0)
                    if dirty:
                        body = json.dumps(payload).encode()
            # failover path: replica death mid-request retries on a live
            # replica; only application errors surface as 500.  The serving
            # replica's tag rides back so streaming clients can pin polls.
            if action == "poll":
                # journal-aware poll: keeps the delivered prefix current and
                # replays the stream on a live replica if the pin is dead
                rid = payload.get("request_id", -1)
                try:
                    result, tag = journaled_poll(
                        _state.journal, handle, prefix, payload, pin,
                        timeout=call_timeout)
                except Exception:  # terminal for the client either way
                    # hand back the stream's tenant quota unit (idempotent)
                    _drop_stream_hold(prefix, pin, rid)
                    raise
                if isinstance(result, dict) and result.get("done"):
                    _drop_stream_hold(prefix, pin, rid)
            else:
                result, tag = handle.call_http_sync_tagged(
                    body, timeout=call_timeout, pin=pin)
                if (action == "submit" and isinstance(payload, dict)
                        and isinstance(result, dict)
                        and "request_id" in result
                        and payload.get("max_new_tokens") is not None):
                    # journal the admitted stream for crash replay (only
                    # budgeted requests are replayable — see supervisor.py)
                    _state.journal.record_submit(
                        prefix, tag, int(result["request_id"]),
                        prompt=payload.get("prompt") or [],
                        max_new_tokens=payload["max_new_tokens"],
                        priority=str(
                            payload.get("priority") or "interactive"),
                        deadline_ms=payload.get("deadline_ms"),
                        adapter_id=payload.get("adapter_id"),
                        tenant=payload.get("tenant"))
                if (action == "submit" and quota_hold is not None
                        and isinstance(result, dict)
                        and "request_id" in result):
                    # the quota unit now belongs to the STREAM: polls
                    # release it when they observe the stream's end
                    with _state.lock:
                        _state.tenant_streams[
                            (prefix, tag, int(result["request_id"]))
                        ] = quota_hold
                    quota_hold = None
            self._respond(200, _to_jsonable(result),
                          headers={REPLICA_HEADER: tag})
        except QuotaExceededError as e:
            # per-tenant quota, not capacity: 429 tells THIS client to
            # slow down (a 503 would suggest the fleet is the problem)
            self._respond(429, {"error": f"QuotaExceededError: {e}"},
                          headers={"Retry-After": f"{e.retry_after_s:g}"})
        except AdmissionShedError as e:
            self._respond(503, {"error": f"AdmissionShedError: {e}"},
                          headers={"Retry-After": f"{e.retry_after_s:g}"})
        except DeadlineExceededError as e:
            # the end-to-end budget is exhausted: 504, and Retry-After says
            # "re-issue with a fresh budget", distinct from 5xx breakage
            self._respond(504, {"error": f"DeadlineExceededError: {e}"},
                          headers={"Retry-After": "1"})
        except (NoLiveReplicasError, ReplicaGoneError) as e:
            self._respond(503, {"error": str(e)})
        except RemoteError as e:
            # replica-side backpressure (engine admission queue full) and
            # drain refusal (replica retiring mid-rollout) are the same
            # "retry later, nothing is broken" contract as zero live
            # replicas — 503, not 500
            if e.cause_repr.startswith("QuotaExceededError"):
                # a quota shed raised behind the actor boundary keeps the
                # 429 contract of the proxy-side check
                self._respond(429, {"error": e.cause_repr},
                              headers={"Retry-After": "1"})
            elif e.cause_repr.startswith(("EngineOverloadedError",
                                          "EngineDrainingError")):
                self._respond(503, {"error": e.cause_repr})
            elif e.cause_repr.startswith("DeadlineExceededError"):
                # a deadline expiry raised replica-side (queue sweep /
                # failed stream) crosses the actor boundary as RemoteError
                self._respond(504, {"error": e.cause_repr},
                              headers={"Retry-After": "1"})
            elif e.cause_repr.startswith("RequestValidationError"):
                # replica-side request validation (unknown adapter_id) is
                # the client's fault — same 400 the proxy-side ValueError
                # branch below produces.  Deliberately NOT plain ValueError:
                # an application ValueError inside a replica is a server
                # bug and must stay a 500
                self._respond(400, {"error": e.cause_repr})
            else:
                self._respond(500, {"error": f"{type(e).__name__}: {e}"})
        except ValueError as e:
            # malformed request (bad priority / bad payload shape caught
            # proxy-side): client error, not server error
            self._respond(400, {"error": f"ValueError: {e}"})
        except Exception as e:  # noqa: BLE001 — surface the error to the client
            self._respond(500, {"error": f"{type(e).__name__}: {e}"})
        finally:
            if quota_hold is not None:
                # blocking call, or any path that never handed the unit to
                # a stream: the request is over, return the unit
                quota_hold[0].release(quota_hold[1])

    do_POST = _dispatch
    do_GET = _dispatch


def _drop_stream_hold(prefix: str, pin: Optional[str], request_id) -> None:
    """Release the tenant quota unit held by a finished (or terminally
    failed) stream.  Idempotent — re-polls of a done stream pop nothing."""
    try:
        key = (prefix, pin or "", int(request_id))
    except (TypeError, ValueError):
        return
    with _state.lock:
        held = _state.tenant_streams.pop(key, None)
    if held is not None:
        held[0].release(held[1])


def run(
    target: Application,
    *,
    host: str = "127.0.0.1",
    port: int = 8000,
    name: Optional[str] = None,
    route_prefix: Optional[str] = None,
    admission_policy: Optional[AdmissionPolicy] = None,
    autoscaler: Optional[AutoscalerConfig] = None,
    fault_plan=None,
    _blocking: bool = False,
    **_ignored,
) -> DeploymentHandle:
    """Deploy an Application: start its replicas and route HTTP to them.

    Every route gets an :class:`~tpu_air.serve.admission.AdmissionController`
    (``admission_policy`` overrides the default
    :class:`~tpu_air.serve.admission.AdmissionPolicy`; routes without an
    engine see empty gauges and admit everything, so plain deployments are
    unaffected).  Passing ``autoscaler=AutoscalerConfig(...)`` additionally
    starts a gauge-driven replica scaling loop for this route.

    ``fault_plan=FaultPlan(...)`` installs a seeded deterministic fault
    plan (tpu_air.faults) for chaos testing — it must be installed before
    the replicas spawn so they inherit it through the environment."""
    if not isinstance(target, Application):
        raise TypeError(
            "serve.run expects a bound Application — call Deployment.bind(...)"
        )
    if fault_plan is not None:
        _faults.install(fault_plan)
    prefix = route_prefix or target.deployment.route_prefix or "/"
    # Validate the port before starting replicas or mutating routes — a
    # port-mismatch failure must not leave a half-deployed application.
    with _state.lock:
        if _state.server is not None and port != _state.port:
            raise RuntimeError(
                f"serve proxy already running on port {_state.port}; "
                f"cannot also listen on {port} (call serve.shutdown() first)"
            )
    handle = start_replicas(target)
    old = None
    old_scaler = None
    try:
        # validate the autoscaler config (and build the loop) BEFORE any
        # route-table mutation: a bad config must not half-deploy
        scaler = (Autoscaler(handle, autoscaler)
                  if autoscaler is not None else None)
        with _state.lock:
            # re-check under the same lock that creates the server — the
            # early check above is only a fast-fail; this one is authoritative
            if _state.server is not None and port != _state.port:
                raise RuntimeError(
                    f"serve proxy already running on port {_state.port}; "
                    f"cannot also listen on {port} (call serve.shutdown() first)"
                )
            if _state.server is None:
                # bind before touching routes: a failed bind (EADDRINUSE)
                # must not leave a route pointing at soon-dead replicas
                server = ThreadingHTTPServer((host, port), _Handler)
                thread = threading.Thread(target=server.serve_forever, daemon=True)
                thread.start()
                _state.server, _state.thread, _state.port = server, thread, port
            old = _state.routes.get(prefix)
            old_scaler = _state.autoscalers.pop(prefix, None)
            old_watcher = _state.watchers.pop(prefix, None)
            _state.routes[prefix] = handle
            _state.admission[prefix] = AdmissionController(
                handle, admission_policy)
            if scaler is not None:
                _state.autoscalers[prefix] = scaler.start()
            # preemption watcher: polls replicas for lease-revocation
            # notices and orchestrates migrate-or-replay (supervisor.py)
            _state.watchers[prefix] = PreemptionWatcher(
                handle, _state.journal, prefix,
                autoscaler=_state.autoscalers.get(prefix)).start()
    except Exception:  # noqa: BLE001 — ANY failure past replica start must release them
        _retire(handle)  # deployment failed after replicas started
        raise
    if old_scaler is not None:
        old_scaler.stop()  # must not keep scaling the retired handle
    if old_watcher is not None:
        old_watcher.stop()
    if old is not None:
        # Redeploy on an existing route: retire the previous deployment's
        # replicas so their actor processes and chip leases are released.
        _retire(old)
    # airwatch (observability/watch.py): an installed watch gets its fleet
    # scraper thread once replicas exist to scrape; off ⇒ one global read
    from tpu_air.observability import watch as _watch

    if _watch.enabled():
        _watch.current().start_scraper()
    return handle


def _retire(handle: DeploymentHandle) -> None:
    """Kill a deployment's replica actors (releases processes + chip leases)
    and stop its restart controller so nothing respawns them."""
    from tpu_air.core.remote import kill

    handle.stop()
    with handle._lock:
        # draining replicas (mid-rollout/scale-down) hold processes and
        # leases too — a retire must not leak them
        replicas = list(handle._replicas) + list(handle._draining)
        handle._replicas = []
        handle._draining = []
    for replica in replicas:
        try:
            kill(replica)
        except Exception:  # noqa: BLE001 — best-effort kill; replica may already be dead
            pass


def rollout(route_prefix: str = "/", timeout: float = 120.0) -> int:
    """Zero-downtime redeploy of one route's replicas: each is swapped for
    a freshly spawned replica, draining the old one first so in-flight
    streams finish where they started.  Returns the number swapped."""
    with _state.lock:
        handle = _state.routes.get(route_prefix)
    if handle is None:
        raise KeyError(f"no deployment at route {route_prefix!r}")
    return handle.rollout(timeout=timeout)


def shutdown() -> None:
    """Stop the proxy, the control loops, and every replica actor."""
    # the fleet scraper would only see dead replicas from here on
    from tpu_air.observability import watch as _watch

    if _watch.enabled():
        _watch.current().stop_scraper()
    with _state.lock:
        for watcher in _state.watchers.values():
            watcher.stop()
        _state.watchers.clear()
        for scaler in _state.autoscalers.values():
            scaler.stop()
        _state.autoscalers.clear()
        _state.admission.clear()
        _state.tenant_streams.clear()
        for handle in _state.routes.values():
            _retire(handle)
        _state.routes.clear()
        if _state.server is not None:
            _state.server.shutdown()
            _state.server.server_close()
            _state.server = None
            _state.thread = None
            _state.port = None
        # retired replicas take their streams with them — drop the journal
        _state.journal = RequestJournal()


def route_control(route_prefix: str) -> Dict[str, Any]:
    """The driver-side control surface for one deployed route: its
    deployment handle, admission controller, autoscaler (or None),
    preemption watcher, and the shared request journal.  The batch lane's
    :class:`~tpu_air.batch.BatchJobRunner` drives THROUGH these — the same
    admission path, journal replay, and preemption orchestration online
    traffic gets, rather than a parallel offline stack."""
    with _state.lock:
        handle = _state.routes.get(route_prefix)
        if handle is None:
            raise KeyError(f"no deployment at route {route_prefix!r}")
        return {
            "handle": handle,
            "admission": _state.admission.get(route_prefix),
            "autoscaler": _state.autoscalers.get(route_prefix),
            "watcher": _state.watchers.get(route_prefix),
            "journal": _state.journal,
        }


def replica_engine_stats() -> Dict[str, Dict[str, Any]]:
    """Engine-metrics snapshots from every deployed replica, merged across
    routes — the dashboard folds this into ``/api/engines`` + ``/metrics``
    so replica-side engines are visible beyond the driver's own registry."""
    with _state.lock:
        handles = list(_state.routes.values())
        controllers = dict(_state.admission)
    out: Dict[str, Dict[str, Any]] = {}
    for handle in handles:
        try:
            out.update(handle.engine_stats())
        except Exception:  # noqa: BLE001 — scrape is best-effort
            continue
    # proxy-side per-tenant quota sheds ride the ENGINE metric families
    # (``priority.<class>.quota_shed``): a synthetic partial snapshot per
    # route sums into the fleet view via merge_snapshots and renders as
    # tpu_air_engine_priority_quota_shed — both consumers key-guard, so
    # the missing engine gauges are simply absent, not zero
    for prefix, controller in controllers.items():
        qs = controller.stats()["quota_shed"]
        if any(qs.values()):
            name = f"admission{prefix.rstrip('/') or '/'}"
            out[name] = {
                "name": name,
                "priority": {p: {"quota_shed": int(n)}
                             for p, n in qs.items() if n},
            }
    return out


def serve_control_stats() -> Dict[str, Any]:
    """Per-route serve-plane control state (the ``/-/stats`` payload):
    admission outcomes + gauges, autoscaler decisions.  The dashboard folds
    this into ``/api/serve`` + ``/metrics``."""
    with _state.lock:
        controllers = dict(_state.admission)
        scalers = dict(_state.autoscalers)
        watchers = dict(_state.watchers)
        journal = _state.journal
    out: Dict[str, Any] = {
        prefix: {
            "admission": controller.stats(),
            "autoscaler": (scalers[prefix].stats()
                           if prefix in scalers else None),
        }
        for prefix, controller in controllers.items()
    }
    # self-healing counters (route prefixes always start with "/", so the
    # bare key can't collide): journal size, replays, replay failures, and
    # the installed fault plan's injection ledger (docs/RESILIENCE.md);
    # preemption-migration counters sum across routes' watchers
    preempt: Dict[str, int] = {}
    for watcher in watchers.values():
        for k, v in watcher.stats().items():
            preempt[k] = preempt.get(k, 0) + int(v)
    out["recovery"] = {**journal.stats(), **preempt,
                       "faults": _faults.stats()}
    # live-weight canary controllers (serve/weights.py): per-route state
    # machine, promotions/rollbacks, gate failures with reasons
    try:
        from tpu_air.serve.weights import controller_stats as _wctl

        weights = _wctl()
    except Exception:  # noqa: BLE001 — stats must never 500 the proxy
        weights = {}
    if weights:
        out["weights"] = weights
    # batch lane (tpu_air/batch): per-job progress/borrowing gauges ride
    # the same bare-key convention as "recovery"/"weights"
    try:
        from tpu_air.batch import jobs_stats as _bjobs

        batch = _bjobs()
    except Exception:  # noqa: BLE001 — stats must never 500 the proxy
        batch = {}
    if batch:
        out["batch"] = batch
    return out


def status() -> Dict[str, Any]:
    return {
        "proxy": {"port": _state.port, "running": _state.server is not None},
        "deployments": {
            prefix: {
                "name": h.deployment_name,
                "num_replicas": h.live_replicas(),
            }
            for prefix, h in _state.routes.items()
        },
    }
