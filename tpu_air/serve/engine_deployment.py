"""EngineDeployment — serve the continuous-batching engine over HTTP.

Each replica actor owns one engine built from a Checkpoint — a
:class:`tpu_air.engine.InferenceEngine` (slot/page pool + persistent decode
step + background loop), or a :class:`tpu_air.engine.T5Engine` when the
``engine_config`` is a :class:`~tpu_air.engine.T5EngineConfig` (the config
type selects the engine family).  Two client surfaces:

* blocking HTTP: ``POST {"prompts": [[ids...], ...], "max_new_tokens": n,
  "priority": "interactive"}`` → ``{"results": [{"request_id": ...,
  "tokens": [...]}, ...]}`` — every prompt is submitted up front so they
  share slot-pool steps, then joined.
* streaming over HTTP (action payloads): ``POST {"action": "submit",
  "prompt": [ids...], "priority": ...}`` → ``{"request_id": rid}``
  immediately (no blocking — the actor's message loop stays free), then
  ``POST {"action": "poll", "request_id": rid, "cursor": c}`` →
  ``{"tokens": <new since cursor>, "done": bool}``.  Polls must land on
  the replica that took the submit — the proxy round-trips the replica
  tag in the ``x-tpu-air-replica`` header and pins polls to it.  The same
  submit/poll pair is also callable over actor RPC
  (``handle.method("submit")(...)``).

Backpressure: a full admission queue raises
:class:`~tpu_air.engine.EngineOverloadedError` inside the replica (class-
aware — best-effort sheds at a lower queue depth than interactive); a
DRAINING replica (zero-downtime rollout) raises ``EngineDrainingError``
for new submits while admitted streams keep polling.  Both cross the
actor boundary as ``RemoteError`` and the proxy maps them to HTTP 503
(same retry semantics as ``NoLiveReplicasError``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from .deployment import Deployment


class _EngineServer:
    """The engine itself is built LAZILY on the first request, not in
    ``__init__``: the core runtime round-trips a replica instance through
    the (pickle-based) object store at actor creation, and a live engine
    holds threads, locks and device buffers — unpicklable by design.  The
    constructor keeps only the picklable recipe (checkpoint + config)."""

    def __init__(
        self,
        checkpoint,
        engine_config=None,
        *,
        dtype: Optional[str] = None,
        engine_name: str = "engine",
        join_timeout: float = 300.0,
        mesh: Optional[tuple] = None,
        disagg: Optional[Dict[str, Any]] = None,
    ):
        self._checkpoint = checkpoint
        self._engine_config = engine_config
        self._dtype = dtype
        self._engine_name = engine_name
        self._join_timeout = join_timeout
        # distributed serving (tpu_air.engine.dist): ``mesh=(dp, tp)``
        # builds a MeshEngine over a leased device mesh; ``disagg=`` (a
        # kwargs dict for DisaggRouter, e.g. {"prefill_replicas": 2})
        # routes prefill through separate worker actors.  Both compose.
        self._mesh = tuple(mesh) if mesh is not None else None
        self._disagg = dict(disagg) if disagg is not None else None
        self._engine = None
        self._router = None
        self._streams: Dict[int, Any] = {}
        # recently retired streams' full token lists: a poll AFTER the one
        # that delivered `done` still answers (insertion-ordered, bounded)
        self._finished: Dict[int, list] = {}
        self._draining = False
        # preemption: the chip lease this replica sits on (attached when
        # the engine builds) and the revocation notice, if one arrived
        self._lease = None
        self._preempt_notice_s: Optional[float] = None
        self._preempt_at: Optional[float] = None

    def _ensure_engine(self):
        if self._engine is None:
            # lazy import: the serve package must stay importable without jax
            from tpu_air.engine import (
                EngineConfig,
                InferenceEngine,
                T5Engine,
                T5EngineConfig,
            )

            model, params = self._checkpoint.get_model(dtype=self._dtype)
            if self._dtype:
                import jax
                import jax.numpy as jnp

                params = jax.tree_util.tree_map(
                    lambda x: (x.astype(jnp.dtype(self._dtype))
                               if hasattr(x, "astype") else x),
                    params,
                )
            # the config type picks the engine family: a T5EngineConfig
            # gets the window engine (batch-synchronized T5 decode), any
            # EngineConfig (or None) the causal-LM slot/page engine
            if isinstance(self._engine_config, T5EngineConfig):
                if self._mesh or self._disagg:
                    raise ValueError(
                        "mesh/disagg serving supports the causal-LM paged "
                        "engine only")
                self._engine = T5Engine(
                    model, params, self._engine_config,
                    name=self._engine_name,
                )
            elif self._mesh is not None:
                from tpu_air.engine import MeshEngine

                dp, tp = self._mesh
                self._engine = MeshEngine(
                    model, params, self._engine_config or EngineConfig(),
                    dp=dp, tp=tp, name=self._engine_name,
                )
            else:
                self._engine = InferenceEngine(
                    model, params, self._engine_config or EngineConfig(),
                    name=self._engine_name,
                )
            if self._disagg is not None:
                from tpu_air.engine import DisaggRouter

                self._router = DisaggRouter(
                    self._checkpoint,
                    self._engine_config or EngineConfig(),
                    engine=self._engine, dtype=self._dtype,
                    name=self._engine_name, **self._disagg,
                )
            # attach the chip lease this actor was placed on: a revocation
            # notice (runtime.lease fault site, or a real preemption in
            # prod) freezes admission immediately, and the supervisor's
            # watcher sees it via preempt_status and orchestrates
            # migrate-or-replay from the driver side
            from tpu_air.core.runtime import attach_chip_lease

            self._lease = attach_chip_lease()
            self._lease.on_revoke(self._on_preempt)
        return self._engine

    def _on_preempt(self, notice_s: float) -> None:
        """Lease-revocation callback (the revoker's thread): stamp the
        notice and freeze engine admission.  The queued backlog stays
        queued — the notice window belongs to LIVE slots."""
        self._preempt_notice_s = float(notice_s)
        self._preempt_at = time.monotonic()
        engine = self._engine
        if engine is not None and hasattr(engine, "preempt"):
            engine.preempt()

    def _front(self):
        """The submit surface: the disagg router when configured (prefill
        on worker actors), else the engine itself."""
        self._ensure_engine()
        return self._router if self._router is not None else self._engine

    # -- HTTP path (blocking generate + streaming actions) --------------------
    def __call__(self, payload) -> Dict[str, Any]:
        if not isinstance(payload, dict):
            raise ValueError(
                'expected JSON object {"prompts": [[ids...], ...]} '
                '(or {"prompt": [ids...]}, or {"action": "submit"/"poll"})'
            )
        # streaming actions: fast, non-blocking RPCs — the actor's serial
        # message loop turns around immediately, so MANY clients can hold
        # concurrent streams against one replica (continuous batching is
        # only observable end-to-end through this path)
        action = payload.get("action")
        if action == "submit":
            return {"request_id": self.submit(
                payload.get("prompt") or [],
                payload.get("max_new_tokens"),
                priority=payload.get("priority", "interactive"),
                deadline_ms=payload.get("deadline_ms"),
                adapter_id=payload.get("adapter_id"),
                tenant=payload.get("tenant"),
            )}
        if action == "poll":
            return self.poll(int(payload.get("request_id", -1)),
                             int(payload.get("cursor", 0)))
        if action is not None:
            raise ValueError(f"unknown action {action!r}")
        if "prompt" in payload:
            prompts = [payload["prompt"]]
        else:
            prompts = payload.get("prompts")
        if not prompts:
            raise ValueError('payload needs "prompt" or a non-empty "prompts"')
        max_new = payload.get("max_new_tokens")
        priority = payload.get("priority", "interactive")
        deadline_ms = payload.get("deadline_ms")
        front = self._front()
        kw = {} if deadline_ms is None else {"deadline_ms": float(deadline_ms)}
        if payload.get("adapter_id") is not None:
            kw["adapter_id"] = str(payload["adapter_id"])
        # submit ALL before joining ANY — concurrent prompts share pool steps
        streams = [front.submit(p, max_new, priority=priority, **kw)
                   for p in prompts]
        return {
            "results": [
                {"request_id": s.request_id,
                 "tokens": s.result(self._join_timeout)}
                for s in streams
            ]
        }

    # -- streaming path (HTTP actions above, or direct actor RPC) -------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None, *,
               priority: str = "interactive",
               deadline_ms: Optional[float] = None,
               adapter_id: Optional[str] = None,
               tenant: Optional[str] = None) -> int:
        # deadline_ms is absolute unix-epoch ms (the proxy converts the
        # client's relative budget at admission).  Passed through only when
        # set: the T5 window engine doesn't take it, and None means "no
        # deadline" everywhere.  Same for adapter_id (multi-tenant LoRA —
        # paged causal-LM engines only) and tenant (pure cost-attribution
        # label, e.g. the batch lane's ``batch:<job_id>``).
        kw = {} if deadline_ms is None else {"deadline_ms": float(deadline_ms)}
        front = self._front()
        if adapter_id is not None:
            if self._router is not None:
                from ..engine.types import RequestValidationError
                raise RequestValidationError(
                    "adapter_id is not supported with disaggregated "
                    "serving (prefill workers hold no adapter bank)")
            kw["adapter_id"] = str(adapter_id)
        if tenant is not None and self._router is None \
                and hasattr(front, "submit_migrated"):
            # pure billing label, causal-LM engines only — the T5 window
            # engine (and the disagg router) take no per-request tenant;
            # dropping the label there degrades attribution, never submits
            kw["tenant"] = str(tenant)
        stream = front.submit(prompt, max_new_tokens,
                              priority=priority, **kw)
        self._streams[stream.request_id] = stream
        return stream.request_id

    def poll(self, request_id: int, cursor: int = 0) -> Dict[str, Any]:
        stream = self._streams.get(request_id)
        if stream is None:
            toks = self._finished.get(request_id)
            if toks is None:
                raise KeyError(f"unknown request_id {request_id}")
            if isinstance(toks, BaseException):
                raise toks  # failed-stream tombstone: every re-poll re-raises
            return {"tokens": toks[cursor:], "done": True}
        # read `done` BEFORE the tokens: done observed first guarantees the
        # token list is complete, so a client may stop at its first done
        # response without losing a tail emitted between the two reads
        done = stream.done
        toks = stream.tokens_so_far()
        if done:
            # delivery completes with this response; move the stream to the
            # bounded tombstone map so drain_status stops counting it but a
            # trailing confirmation poll still answers.  A FAILED stream
            # surfaces its error instead of masquerading as a short success —
            # DeadlineExceededError crosses the actor boundary as RemoteError
            # and the proxy maps it to HTTP 504 with Retry-After.
            self._streams.pop(request_id, None)
            err = getattr(stream, "_error", None)
            self._finished[request_id] = err if err is not None else toks
            while len(self._finished) > 512:
                self._finished.pop(next(iter(self._finished)))
            if err is not None:
                raise err
        return {"tokens": toks[cursor:], "done": done}

    # -- live weights (serve/weights.py WeightsController RPCs) ---------------
    def weights_swap(self, store_root: str,
                     version: Optional[int] = None) -> float:
        """Load ``version`` (default: latest) from the weight store —
        checksum-validated — and hot-swap it into the serving engine
        between decode steps.  Returns the swap's stall in ms."""
        from .weights import WeightStore

        engine = self._ensure_engine()
        store = WeightStore(store_root)
        if version is None:
            version = store.latest_version()
        params = store.load(version)
        return engine.swap_params(params, version=version)

    def weights_rollback(self) -> float:
        """Restore the pre-swap weights (engine-held device tree — no
        store reads, survives a corrupt/GC'd publish)."""
        return self._ensure_engine().rollback_params()

    def weights_version(self) -> Optional[int]:
        if self._engine is None:
            return None
        return self._engine.weights_version()

    def weights_probe(self, prompts, max_new: int = 8, *,
                      adapter_id: Optional[str] = None,
                      timeout_s: float = 60.0) -> list:
        """Run the canary probe prompts through THIS replica's engine
        (the full admit/prefill/decode path, not an offline forward) and
        return their greedy token lists."""
        engine = self._ensure_engine()
        kw = {} if adapter_id is None else {"adapter_id": str(adapter_id)}
        streams = [engine.submit([int(t) for t in p], int(max_new), **kw)
                   for p in prompts]
        return [s.result(float(timeout_s)) for s in streams]

    def weights_probe_logits(self, prompts) -> list:
        """Last-prompt-position logits under the SERVING params (the
        logit-tolerance gate surface for quantized bases)."""
        from .weights import probe_logits

        engine = self._ensure_engine()
        return probe_logits(engine.model, engine.params, prompts)

    def weights_load_adapter(self, name: str, a, b) -> int:
        return self._ensure_engine().load_adapter(name, a, b)

    def weights_unload_adapter(self, name: str) -> bool:
        return self._ensure_engine().unload_adapter(name)

    def weights_adapters(self) -> Dict[str, int]:
        if self._engine is None:
            return {}
        return self._engine.adapters()

    # -- draining (zero-downtime rollout / scale-down) ------------------------
    def drain(self) -> None:
        """Stop admitting new work; admitted streams retire and stay
        pollable.  Never forces the lazy engine build — a replica that
        served nothing drains instantly."""
        self._draining = True
        front = self._router if self._router is not None else self._engine
        if front is not None:
            front.drain()

    def drain_status(self) -> Dict[str, Any]:
        """``drained`` means: drain was requested, the engine retired all
        admitted work, and every finished stream was polled to its end
        (the deployment kills the replica only then — no client loses a
        tail it hasn't read)."""
        # drop fully-delivered streams a client finished mid-drain but
        # never polled past the end of
        pending = len(self._streams)
        engine_done = (self._engine is None
                       or (self._engine.drained() if self._draining
                           else False))
        return {
            "draining": self._draining,
            "pending_streams": pending,
            "drained": bool(self._draining and engine_done and pending == 0),
        }

    # -- preemption (serve/supervisor.py PreemptionWatcher RPCs) --------------
    def preempt_status(self) -> Dict[str, Any]:
        """Cheap poll surface for the driver-side watcher.  Never forces
        the lazy engine build; ``notice_left_s`` is how much of the
        revocation window remains (the watcher's migrate-vs-replay
        input)."""
        if self._preempt_notice_s is None:
            return {"preempting": False}
        left = self._preempt_notice_s - (time.monotonic() - self._preempt_at)
        return {
            "preempting": True,
            "notice_s": self._preempt_notice_s,
            "notice_left_s": max(0.0, left),
        }

    def borrow_return(self, notice_s: float = 5.0) -> bool:
        """Elastic chip borrowing (tpu_air/batch): hand this replica's
        chips back to the pool THROUGH the preemption path — deliver a
        revocation notice to our own lease, which freezes admission and
        lets the driver-side watcher drain/migrate live slots exactly as
        a real preemption would.  The batch broker calls this on replicas
        it borrowed during a trough when interactive load returns; reusing
        the lease-notice machinery means borrow-return is chaos-tested by
        construction.  Returns False when there is no lease to revoke
        (engine never built — nothing to return)."""
        if self._lease is None:
            return False
        self._lease.deliver_notice(float(notice_s))
        return True

    def migrate_out(self) -> list:
        """Freeze this replica's engine and pull every live decoding
        slot's state into portable payloads (prompt + streamed tokens +
        KV pages).  Also flips the engine into preemption drain if the
        notice callback hasn't already."""
        engine = self._ensure_engine()
        if not hasattr(engine, "migrate_out"):
            raise ValueError(
                "migrate_out needs the paged causal-LM engine "
                f"(this replica serves {type(engine).__name__})")
        # the abandoned source streams stay in ``_streams`` on purpose: a
        # client poll racing the migration window must keep getting 200s
        # (a stale-but-correct prefix) until the supervisor re-pins the
        # journal entry to the destination — this replica is going away,
        # so its drain accounting no longer matters
        return engine.migrate_out()

    def submit_migrated(self, payload: Dict[str, Any]) -> int:
        """Land one migrated stream on THIS replica (the survivor side of
        a preemption migration).  Raises synchronously — KVTransferError /
        RequestValidationError cross the actor boundary as RemoteError —
        when the payload cannot be admitted cleanly, so the supervisor
        falls back to journal replay."""
        engine = self._ensure_engine()
        if not hasattr(engine, "submit_migrated"):
            raise ValueError(
                "submit_migrated needs the paged causal-LM engine "
                f"(this replica serves {type(engine).__name__})")
        stream = engine.submit_migrated(payload)
        self._streams[stream.request_id] = stream
        return stream.request_id

    def stats(self) -> Dict[str, Any]:
        # a dashboard scrape must NEVER force the lazy engine build (model
        # load + compile) — no engine yet means nothing to report
        if self._engine is None:
            return {}
        snap = self._engine.metrics.snapshot()
        if self._router is not None:
            rst = self._router.stats()
            snap.setdefault("topology", {}).update(
                disagg="on",
                prefill_replicas=rst["prefill_replicas"],
                live_prefill_replicas=rst["live_prefill_replicas"],
            )
            snap["disagg"] = {k: rst[k] for k in
                              ("handoffs", "reroutes", "fallbacks",
                               "retries", "breakers")}
        return snap


EngineDeployment = Deployment(
    func_or_class=_EngineServer,
    name="EngineDeployment",
    num_replicas=1,
)
