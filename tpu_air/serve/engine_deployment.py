"""EngineDeployment — serve the continuous-batching engine over HTTP.

Each replica actor owns one engine built from a Checkpoint — a
:class:`tpu_air.engine.InferenceEngine` (slot/page pool + persistent decode
step + background loop), or a :class:`tpu_air.engine.T5Engine` when the
``engine_config`` is a :class:`~tpu_air.engine.T5EngineConfig` (the config
type selects the engine family).  Two client surfaces:

* blocking HTTP: ``POST {"prompts": [[ids...], ...], "max_new_tokens": n}``
  → ``{"results": [{"request_id": ..., "tokens": [...]}, ...]}`` — every
  prompt is submitted up front so they share slot-pool steps, then joined.
* streaming over actor RPC: ``handle.method("submit")(prompt)`` →
  request id, then ``handle.method("poll")(rid, cursor)`` →
  ``{"tokens": <new since cursor>, "done": bool}`` — polling cursor
  streaming, the shape HTTP long-poll clients want (the proxy itself is
  plain request/response).

Backpressure: a full admission queue raises
:class:`~tpu_air.engine.EngineOverloadedError` inside the replica; it
crosses the actor boundary as ``RemoteError`` and the proxy maps it to
HTTP 503 (same retry semantics as ``NoLiveReplicasError``).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .deployment import Deployment


class _EngineServer:
    """The engine itself is built LAZILY on the first request, not in
    ``__init__``: the core runtime round-trips a replica instance through
    the (pickle-based) object store at actor creation, and a live engine
    holds threads, locks and device buffers — unpicklable by design.  The
    constructor keeps only the picklable recipe (checkpoint + config)."""

    def __init__(
        self,
        checkpoint,
        engine_config=None,
        *,
        dtype: Optional[str] = None,
        engine_name: str = "engine",
        join_timeout: float = 300.0,
        mesh: Optional[tuple] = None,
        disagg: Optional[Dict[str, Any]] = None,
    ):
        self._checkpoint = checkpoint
        self._engine_config = engine_config
        self._dtype = dtype
        self._engine_name = engine_name
        self._join_timeout = join_timeout
        # distributed serving (tpu_air.engine.dist): ``mesh=(dp, tp)``
        # builds a MeshEngine over a leased device mesh; ``disagg=`` (a
        # kwargs dict for DisaggRouter, e.g. {"prefill_replicas": 2})
        # routes prefill through separate worker actors.  Both compose.
        self._mesh = tuple(mesh) if mesh is not None else None
        self._disagg = dict(disagg) if disagg is not None else None
        self._engine = None
        self._router = None
        self._streams: Dict[int, Any] = {}

    def _ensure_engine(self):
        if self._engine is None:
            # lazy import: the serve package must stay importable without jax
            from tpu_air.engine import (
                EngineConfig,
                InferenceEngine,
                T5Engine,
                T5EngineConfig,
            )

            model, params = self._checkpoint.get_model(dtype=self._dtype)
            if self._dtype:
                import jax
                import jax.numpy as jnp

                params = jax.tree_util.tree_map(
                    lambda x: (x.astype(jnp.dtype(self._dtype))
                               if hasattr(x, "astype") else x),
                    params,
                )
            # the config type picks the engine family: a T5EngineConfig
            # gets the window engine (batch-synchronized T5 decode), any
            # EngineConfig (or None) the causal-LM slot/page engine
            if isinstance(self._engine_config, T5EngineConfig):
                if self._mesh or self._disagg:
                    raise ValueError(
                        "mesh/disagg serving supports the causal-LM paged "
                        "engine only")
                self._engine = T5Engine(
                    model, params, self._engine_config,
                    name=self._engine_name,
                )
            elif self._mesh is not None:
                from tpu_air.engine import MeshEngine

                dp, tp = self._mesh
                self._engine = MeshEngine(
                    model, params, self._engine_config or EngineConfig(),
                    dp=dp, tp=tp, name=self._engine_name,
                )
            else:
                self._engine = InferenceEngine(
                    model, params, self._engine_config or EngineConfig(),
                    name=self._engine_name,
                )
            if self._disagg is not None:
                from tpu_air.engine import DisaggRouter

                self._router = DisaggRouter(
                    self._checkpoint,
                    self._engine_config or EngineConfig(),
                    engine=self._engine, dtype=self._dtype,
                    name=self._engine_name, **self._disagg,
                )
        return self._engine

    def _front(self):
        """The submit surface: the disagg router when configured (prefill
        on worker actors), else the engine itself."""
        self._ensure_engine()
        return self._router if self._router is not None else self._engine

    # -- blocking HTTP path ---------------------------------------------------
    def __call__(self, payload) -> Dict[str, Any]:
        if not isinstance(payload, dict):
            raise ValueError(
                'expected JSON object {"prompts": [[ids...], ...]} '
                '(or {"prompt": [ids...]})'
            )
        if "prompt" in payload:
            prompts = [payload["prompt"]]
        else:
            prompts = payload.get("prompts")
        if not prompts:
            raise ValueError('payload needs "prompt" or a non-empty "prompts"')
        max_new = payload.get("max_new_tokens")
        front = self._front()
        # submit ALL before joining ANY — concurrent prompts share pool steps
        streams = [front.submit(p, max_new) for p in prompts]
        return {
            "results": [
                {"request_id": s.request_id,
                 "tokens": s.result(self._join_timeout)}
                for s in streams
            ]
        }

    # -- streaming path (actor RPC) -------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None) -> int:
        stream = self._front().submit(prompt, max_new_tokens)
        self._streams[stream.request_id] = stream
        return stream.request_id

    def poll(self, request_id: int, cursor: int = 0) -> Dict[str, Any]:
        stream = self._streams.get(request_id)
        if stream is None:
            raise KeyError(f"unknown request_id {request_id}")
        toks = stream.tokens_so_far()
        done = stream.done
        if done and len(toks) <= cursor:
            self._streams.pop(request_id, None)  # fully drained
        return {"tokens": toks[cursor:], "done": done}

    def stats(self) -> Dict[str, Any]:
        # a dashboard scrape must NEVER force the lazy engine build (model
        # load + compile) — no engine yet means nothing to report
        if self._engine is None:
            return {}
        snap = self._engine.metrics.snapshot()
        if self._router is not None:
            rst = self._router.stats()
            snap.setdefault("topology", {}).update(
                disagg="on",
                prefill_replicas=rst["prefill_replicas"],
                live_prefill_replicas=rst["live_prefill_replicas"],
            )
            snap["disagg"] = {k: rst[k] for k in
                              ("handoffs", "reroutes", "fallbacks")}
        return snap


EngineDeployment = Deployment(
    func_or_class=_EngineServer,
    name="EngineDeployment",
    num_replicas=1,
)
