"""tpu_air.serve — online inference over replica actors + HTTP proxy.

Parity surface (SURVEY.md §1-L5 "Online (serving)", §3.5):
``serve.run(PredictorDeployment.options(name=..., num_replicas=2,
route_prefix="/rayair").bind(PredictorCls, checkpoint,
http_adapter=pandas_read_json))`` and client ``requests.post`` to
``http://localhost:8000/<route>`` (Introduction_to_Ray_AI_Runtime.ipynb:cc-70-74).

TPU-native shape: each replica is a core-runtime actor holding a jitted
model on its chip lease; the proxy is a threaded HTTP server in the driver
routing round-robin across replicas (cc-79: "a managed group of Ray actors
that ... handle requests load-balanced across them").
"""

from .admission import AdmissionController, AdmissionPolicy, AdmissionShedError
from .autoscaler import Autoscaler, AutoscalerConfig
from .deployment import (
    Application,
    Deployment,
    DeploymentHandle,
    NoLiveReplicasError,
    ReplicaGoneError,
    deployment,
)
from .engine_deployment import EngineDeployment
from .http_adapters import json_request, pandas_read_json
from .predictor_deployment import PredictorDeployment
from .proxy import rollout, run, shutdown, status
from .weights import (
    GateFailedError,
    TornPublishError,
    WeightsController,
    WeightsIntegrityError,
    WeightStore,
    attach_weights,
    compute_probe,
)

__all__ = [
    "AdmissionController",
    "AdmissionPolicy",
    "AdmissionShedError",
    "Application",
    "Autoscaler",
    "AutoscalerConfig",
    "Deployment",
    "DeploymentHandle",
    "EngineDeployment",
    "GateFailedError",
    "NoLiveReplicasError",
    "PredictorDeployment",
    "ReplicaGoneError",
    "TornPublishError",
    "WeightStore",
    "WeightsController",
    "WeightsIntegrityError",
    "attach_weights",
    "compute_probe",
    "deployment",
    "json_request",
    "pandas_read_json",
    "rollout",
    "run",
    "shutdown",
    "status",
]
