"""SLO-aware admission for the serve proxy.

The proxy used to answer overload with a binary 503 (whatever the engine's
admission queue said).  This module moves the decision UP to the serve
plane, where it can be class-aware and gauge-driven:

* every request carries a priority class (``interactive`` / ``batch`` /
  ``best_effort``, :data:`tpu_air.engine.types.PRIORITIES`) and gets a
  per-class TOKEN BUDGET clamp (a best-effort client cannot reserve a
  1000-token decode during a surge);
* the controller scrapes the deployment's live engine gauges
  (``DeploymentHandle.engine_stats`` — queue depth, slot occupancy, KV
  pressure) on a short TTL and turns them into one scalar: mean queued
  depth per live replica;
* under pressure the TAIL classes degrade first — best-effort starts
  QUEUEING at ``queue_soft`` (the request waits proxy-side, bounded by its
  class's ``queue_timeout_s``) and SHEDS at ``queue_high``; batch queues
  at ``queue_high`` and sheds at ``queue_hard``; interactive is admitted
  at every depth this controller sees (its own ceiling is the engine's
  class-aware queue cap).  Shed responses are 503 + ``Retry-After``.

The same scrape feeds the handle's least-loaded routing — the handle
records per-replica loads as a side effect of ``engine_stats`` — so one
gauge pass serves admission, routing and the autoscaler.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from tpu_air.core.runtime import TpuAirError
from tpu_air.engine.types import PRIORITIES

#: default per-class max_new_tokens clamps (requests asking for more are
#: trimmed, not refused — the stream just ends at the budget)
_DEFAULT_TOKEN_BUDGETS = {
    "interactive": 256,
    "batch": 1024,
    "best_effort": 512,
}

#: default proxy-side queue waits before a "queue" decision becomes a shed
_DEFAULT_QUEUE_TIMEOUTS = {
    "interactive": 0.0,   # interactive never waits at the proxy
    "batch": 2.0,
    "best_effort": 5.0,
}


class AdmissionShedError(TpuAirError):
    """The admission controller refused this request (overload).  Maps to
    HTTP 503 + ``Retry-After`` — same retry contract as engine
    backpressure, decided one hop earlier and class-aware."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class QuotaExceededError(AdmissionShedError):
    """One TENANT (``adapter_id``) is over its per-tenant queue share —
    not a capacity problem, a fairness one, so it maps to HTTP 429 (the
    client is the thing to slow down, not the fleet) while still carrying
    ``Retry-After``.  Subclasses :class:`AdmissionShedError` so callers
    that only know the overload contract keep working; the proxy catches
    THIS class first to pick the status code."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 adapter_id: Optional[str] = None):
        super().__init__(msg, retry_after_s=retry_after_s)
        self.adapter_id = adapter_id


@dataclass(frozen=True)
class AdmissionPolicy:
    """Dials for one route's admission controller.

    Depth thresholds are MEAN QUEUED REQUESTS PER LIVE REPLICA (engine
    admission queue depth, from ``engine_stats``), so they keep meaning
    as the autoscaler changes the replica count:

    * ``queue_soft`` — best-effort starts queueing proxy-side;
    * ``queue_high`` — best-effort sheds; batch starts queueing;
    * ``queue_hard`` — batch sheds (interactive still admits — the
      engine's own class-aware cap is its ceiling).

    ``token_budgets`` clamps per-request ``max_new_tokens`` by class;
    ``queue_timeout_s`` bounds the proxy-side wait before a queued class
    sheds; ``stats_ttl_s`` is the gauge-scrape cache horizon (stale stats
    also disable least-loaded routing in the handle); ``retry_after_s``
    rides back on shed responses as the ``Retry-After`` header.

    Per-TENANT quotas (multi-tenant LoRA serving — the tenant key is the
    request's ``adapter_id``, ``None`` meaning the base-model tenant):

    * ``tenant_token_budgets`` — per-tenant ``max_new_tokens`` caps,
      composing with the class budget by MIN (the tighter bound wins);
    * ``tenant_queue_shares`` — fraction of total route capacity
      (``queue_hard × live replicas``) one tenant may hold IN FLIGHT at
      once.  Over-share submits raise :class:`QuotaExceededError`
      (HTTP 429 + ``Retry-After``) regardless of class — quotas compose
      with priority, they don't replace it.  Tenants absent from the
      mapping are unmetered."""

    token_budgets: Dict[str, int] = field(
        default_factory=lambda: dict(_DEFAULT_TOKEN_BUDGETS))
    queue_soft: float = 4.0
    queue_high: float = 12.0
    queue_hard: float = 32.0
    queue_timeout_s: Dict[str, float] = field(
        default_factory=lambda: dict(_DEFAULT_QUEUE_TIMEOUTS))
    queue_poll_s: float = 0.05
    retry_after_s: float = 1.0
    stats_ttl_s: float = 0.25
    tenant_token_budgets: Optional[Dict[str, int]] = None
    tenant_queue_shares: Optional[Dict[str, float]] = None

    def clamp_budget(self, priority: str,
                     max_new_tokens: Optional[int],
                     adapter_id: Optional[str] = None) -> Optional[int]:
        """The effective decode budget for one request of this class (and
        tenant).  An INTERACTIVE request with an unset ask stays unset —
        the engine config's own default (sized to its slots) governs; the
        class budget only trims explicit asks.  The TAIL classes
        (``batch``/``best_effort``) get their class budget applied even to
        UNSET asks: a batch flood that omits ``max_new_tokens`` must not
        default to the engine max.  A tenant budget composes by MIN with
        the class budget and also caps unset asks for every class (a
        metered tenant must not inherit the engine default)."""
        cap = self.token_budgets.get(priority)
        tenant_cap = None
        if self.tenant_token_budgets is not None and adapter_id is not None:
            tenant_cap = self.tenant_token_budgets.get(adapter_id)
        if max_new_tokens is None:
            caps = [c for c in (tenant_cap,
                                cap if priority in ("batch", "best_effort")
                                else None)
                    if c is not None]
            return min(int(c) for c in caps) if caps else None
        out = int(max_new_tokens)
        if cap is not None:
            out = min(out, int(cap))
        if tenant_cap is not None:
            out = min(out, int(tenant_cap))
        return out

    def tenant_inflight_cap(self, adapter_id: Optional[str],
                            replicas: int) -> Optional[int]:
        """Max concurrent in-flight requests for one tenant, or ``None``
        when the tenant is unmetered.  Scales with the live replica count
        so a share keeps meaning as the autoscaler acts."""
        if self.tenant_queue_shares is None or adapter_id is None:
            return None
        share = self.tenant_queue_shares.get(adapter_id)
        if share is None:
            return None
        return max(1, round(float(share) * self.queue_hard
                            * max(int(replicas), 1)))


class AdmissionController:
    """Per-route admission: gauges in, admit/queue/shed out.

    One controller serves one route prefix (one
    :class:`~tpu_air.serve.deployment.DeploymentHandle`).  The proxy asks
    :meth:`admit` before forwarding any NEW work (blocking HTTP generate
    or a streaming ``submit`` action); polls of already-admitted requests
    bypass admission entirely."""

    def __init__(self, handle, policy: Optional[AdmissionPolicy] = None):
        self._handle = handle
        self.policy = policy or AdmissionPolicy()
        self._lock = threading.Lock()
        self._gauges: Dict[str, Any] = {}
        self._gauges_at = 0.0
        # per-class outcome counters (surface on /-/stats + /metrics)
        self.admitted = {p: 0 for p in PRIORITIES}
        self.queued = {p: 0 for p in PRIORITIES}
        self.shed = {p: 0 for p in PRIORITIES}
        # per-class QUOTA sheds (429s) — folded into the merged engine
        # snapshot as ``priority.<class>.quota_shed`` so the metric rides
        # the same /metrics families as engine-side sheds
        self.quota_shed = {p: 0 for p in PRIORITIES}
        # tenant → currently in-flight request count (admitted minus
        # released); only metered tenants appear
        self._tenant_inflight: Dict[str, int] = {}
        # per-tenant admission outcomes keyed by billing tenant — the
        # explicit ``tenant`` label when one rides the request (the batch
        # lane's ``batch:<job_id>``), else adapter_id, else "default" —
        # the airwatch cost ledger's shed/quota feed; EVERY tenant appears
        # here, metered or not
        self.tenants: Dict[str, Dict[str, int]] = {}

    def _tenant_outcome(self, tenant: Optional[str],
                        outcome: str) -> None:
        """Count one admission outcome against a billing tenant
        (``self._lock`` must be held)."""
        key = tenant if tenant else "default"
        d = self.tenants.get(key)
        if d is None:
            d = {"admitted": 0, "queued": 0, "shed": 0, "quota_shed": 0}
            self.tenants[key] = d
        d[outcome] += 1

    # -- gauges ---------------------------------------------------------------
    def gauges(self, force: bool = False) -> Dict[str, Any]:
        """TTL-cached scrape of the route's engine gauges, reduced to the
        scalars admission steers on.  The same pass pushes per-replica
        loads into the handle (least-loaded routing) — stale gauges mean
        the handle falls back to round-robin on its own."""
        now = time.monotonic()
        with self._lock:
            fresh = (now - self._gauges_at) <= self.policy.stats_ttl_s
            if fresh and not force:
                return dict(self._gauges)
        snaps = {}
        try:
            snaps = self._handle.engine_stats(timeout=5.0)
        except Exception:  # noqa: BLE001 — scrape is best-effort; admit on no data
            snaps = {}
        live = max(self._handle.num_replicas(), 1)
        depth = sum(int(s.get("queue_depth", 0)) for s in snaps.values())
        occupancy = sum(int(s.get("slot_occupancy", 0)) for s in snaps.values())
        draining = sum(1 for s in snaps.values() if s.get("draining"))
        gauges = {
            "replicas": live,
            "queue_depth": depth,
            "depth_per_replica": depth / live,
            "slot_occupancy": occupancy,
            "draining_replicas": draining,
            "scraped_engines": len(snaps),
        }
        with self._lock:
            self._gauges = gauges
            self._gauges_at = time.monotonic()
        return dict(gauges)

    # -- the decision ---------------------------------------------------------
    def decide(self, priority: str,
               gauges: Optional[Dict[str, Any]] = None) -> str:
        """Pure policy: ``"admit"`` / ``"queue"`` / ``"shed"`` for one
        request of ``priority`` class under ``gauges`` (defaults to a
        fresh TTL scrape).  No counters, no waiting — unit-testable."""
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r} (expected one of {PRIORITIES})"
            )
        g = self.gauges() if gauges is None else gauges
        d = float(g.get("depth_per_replica", 0.0))
        p = self.policy
        if priority == "interactive":
            return "admit"  # its ceiling is the engine's class-aware cap
        if priority == "batch":
            if d >= p.queue_hard:
                return "shed"
            if d >= p.queue_high:
                return "queue"
            return "admit"
        # best_effort
        if d >= p.queue_high:
            return "shed"
        if d >= p.queue_soft:
            return "queue"
        return "admit"

    def _check_quota(self, priority: str,
                     adapter_id: Optional[str],
                     tenant: Optional[str] = None) -> None:
        """Raise :class:`QuotaExceededError` (and count the 429) when the
        tenant is at its in-flight cap; otherwise take one in-flight unit.
        Quota is checked BEFORE the class decision so a hot tenant cannot
        burn proxy-side queue waits on requests that were never going to
        admit."""
        if (self.policy.tenant_queue_shares is None
                or adapter_id is None
                or adapter_id not in self.policy.tenant_queue_shares):
            return  # unmetered: never touches the handle
        cap = self.policy.tenant_inflight_cap(
            adapter_id, self._handle.num_replicas())
        if cap is None:
            return
        with self._lock:
            held = self._tenant_inflight.get(adapter_id, 0)
            if held >= cap:
                self.quota_shed[priority] += 1
                self._tenant_outcome(tenant or adapter_id, "quota_shed")
                raise QuotaExceededError(
                    f"tenant {adapter_id!r} is at its queue share "
                    f"({held}/{cap} in flight)",
                    retry_after_s=self.policy.retry_after_s,
                    adapter_id=adapter_id,
                )
            self._tenant_inflight[adapter_id] = held + 1

    def release(self, adapter_id: Optional[str]) -> None:
        """Return one in-flight unit for a metered tenant — the proxy
        calls this when the request completes, sheds downstream, or its
        stream finishes delivery.  No-op for unmetered tenants."""
        if (self.policy.tenant_queue_shares is None
                or adapter_id is None
                or adapter_id not in self.policy.tenant_queue_shares):
            return
        with self._lock:
            held = self._tenant_inflight.get(adapter_id, 0)
            if held > 0:
                self._tenant_inflight[adapter_id] = held - 1

    def admit(self, priority: str,
              adapter_id: Optional[str] = None,
              tenant: Optional[str] = None) -> None:
        """Admit-or-raise for one new request: a "queue" decision waits
        proxy-side (re-scraping each poll) up to the class's
        ``queue_timeout_s``, then sheds.  ``tenant`` is the BILLING label
        for outcome attribution (falls back to ``adapter_id``) — quota
        metering stays keyed on ``adapter_id``, the thing shares are
        declared against.  Raises :class:`QuotaExceededError` when the
        tenant is over its share (429), :class:`AdmissionShedError` on
        class shed (503); returns normally on admit — the caller then
        owes a matching :meth:`release` for metered tenants."""
        bill = tenant or adapter_id
        self._check_quota(priority, adapter_id, bill)
        try:
            decision = self.decide(priority)
            if decision == "admit":
                with self._lock:
                    self.admitted[priority] += 1
                    self._tenant_outcome(bill, "admitted")
                return
            p = self.policy
            if decision == "queue":
                with self._lock:
                    self.queued[priority] += 1
                    self._tenant_outcome(bill, "queued")
                deadline = time.monotonic() + float(
                    p.queue_timeout_s.get(priority, 0.0))
                while time.monotonic() < deadline:
                    time.sleep(p.queue_poll_s)
                    decision = self.decide(priority)
                    if decision == "admit":
                        with self._lock:
                            self.admitted[priority] += 1
                            self._tenant_outcome(bill, "admitted")
                        return
                    if decision == "shed":
                        break
            with self._lock:
                self.shed[priority] += 1
                self._tenant_outcome(bill, "shed")
            raise AdmissionShedError(
                f"{priority}-class shed at the proxy "
                f"(queue depth/replica past policy thresholds)",
                retry_after_s=p.retry_after_s,
            )
        except AdmissionShedError:
            # the in-flight unit taken by _check_quota is only owed on
            # ADMIT — hand it back on any shed path
            self.release(adapter_id)
            raise

    # -- observability --------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "policy": {
                    "queue_soft": self.policy.queue_soft,
                    "queue_high": self.policy.queue_high,
                    "queue_hard": self.policy.queue_hard,
                    "token_budgets": dict(self.policy.token_budgets),
                    "tenant_token_budgets": dict(
                        self.policy.tenant_token_budgets or {}),
                    "tenant_queue_shares": dict(
                        self.policy.tenant_queue_shares or {}),
                },
                "admitted": dict(self.admitted),
                "queued": dict(self.queued),
                "shed": dict(self.shed),
                "quota_shed": dict(self.quota_shed),
                "tenant_inflight": dict(self._tenant_inflight),
                "tenants": {t: dict(d) for t, d in self.tenants.items()},
                "gauges": dict(self._gauges),
            }
