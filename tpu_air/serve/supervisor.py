"""Self-healing serve plane: proxy-side request journal + stream replay.

The deployment layer already *respawns* crashed replicas
(``DeploymentHandle._control_tick`` prunes dead replicas and re-spawns the
deficit) — what it cannot do is rescue the STREAMS that lived on the
corpse: a streaming client whose pinned replica died used to see
``ReplicaGoneError`` → HTTP 503, losing every token already decoded.

This module closes that gap.  The proxy journals every streaming submit
(prompt, clamped budget, priority, absolute deadline) and each poll's
delivered-token prefix.  When a pinned poll hits a dead replica, the
journal REPLAYS the request on a live replica: the original prompt plus
the already-streamed tokens are re-submitted as a forced prefix with the
remaining budget — greedy decoding is deterministic, so the continuation
is token-identical to the stream the dead replica would have produced.
The client keeps polling its ORIGINAL request id and pin header; the
journal translates cursors across the redirect.  Net effect: a replica
crash is a stall, never a 5xx and never a token lost or changed.

Replay discipline comes from :mod:`tpu_air.faults.retry`: bounded
attempts, capped-exponential backoff on overload/drain, and no attempt
past the request's deadline (``DeadlineExceededError`` → proxy 504).

Scope notes:

* only streaming requests with an EXPLICIT ``max_new_tokens`` are
  replayable — without the budget the proxy cannot compute the remaining
  allowance for the continuation (the engine-side default is not visible
  here);
* greedy decoding only: a sampled continuation would not be
  token-identical (that is a statement about sampling, not about replay);
* the journal is per-proxy-process, bounded (FIFO eviction), and keyed by
  ``(route prefix, replica tag, request id)`` — request ids are minted
  per replica, so the pin disambiguates.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tpu_air.core.runtime import RemoteError
from tpu_air.faults.retry import Backoff, Deadline, DeadlineExceededError
from tpu_air.observability import tracing as _tracing

from .deployment import NoLiveReplicasError, ReplicaGoneError

__all__ = ["JournalEntry", "PreemptionWatcher", "RequestJournal",
           "journaled_poll"]


@dataclass(eq=False)
class JournalEntry:
    """One in-flight streaming request as the proxy knows it."""

    prefix: str
    pin: str                      # replica tag of the ORIGINAL submit
    request_id: int               # the id the client keeps polling
    prompt: List[int]
    max_new_tokens: Optional[int]
    priority: str
    deadline_ms: Optional[float]  # absolute unix-epoch ms
    adapter_id: Optional[str] = None  # tenant LoRA adapter, None = base
    tenant: Optional[str] = None  # billing label (batch:<job_id>), not a bank row
    tokens: List[int] = field(default_factory=list)  # delivered prefix
    done: bool = False
    # after a replay: (new replica tag, new request id, token offset) — the
    # continuation stream starts at ``offset`` of the client-visible stream
    redirect: Optional[Tuple[str, int, int]] = None
    replays: int = 0
    # per-entry lock: replay must be exclusive per request, but must not
    # serialize the whole journal for its duration
    lock: threading.Lock = field(default_factory=threading.Lock)


class RequestJournal:
    """Bounded, thread-safe map of in-flight streaming requests."""

    def __init__(self, cap: int = 1024):
        self._cap = int(cap)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str, int], JournalEntry]" = (
            OrderedDict())
        self.replays = 0
        self.replay_failures = 0
        # cap evictions that had to take a LIVE (not-done, not-redirected)
        # entry — each one is a stream that silently lost its replay
        # safety net, so the counter surfaces on /-/stats recovery
        self.evicted_live = 0

    # -- bookkeeping (proxy handler threads) --------------------------------
    def record_submit(self, prefix: str, pin: str, request_id: int, *,
                      prompt, max_new_tokens: Optional[int],
                      priority: str,
                      deadline_ms: Optional[float],
                      adapter_id: Optional[str] = None,
                      tenant: Optional[str] = None) -> None:
        entry = JournalEntry(
            prefix=prefix, pin=pin, request_id=int(request_id),
            prompt=[int(t) for t in (prompt or [])],
            max_new_tokens=(None if max_new_tokens is None
                            else int(max_new_tokens)),
            priority=str(priority),
            deadline_ms=(None if deadline_ms is None else float(deadline_ms)),
            adapter_id=(None if adapter_id is None else str(adapter_id)),
            tenant=(None if tenant is None else str(tenant)))
        with self._lock:
            self._entries[(prefix, pin, int(request_id))] = entry
            while len(self._entries) > self._cap:
                self._evict_one_locked()

    def _evict_one_locked(self) -> None:
        """Drop one entry to make room, preferring the oldest FINISHED
        one (done, or fully delivered) — blind FIFO used to evict the
        oldest entry even while its stream was live, silently discarding
        its replay safety net.  Only when every entry is live does the
        cap force a live eviction, and that is counted."""
        for key, e in self._entries.items():
            if e.done:
                del self._entries[key]
                return
        self.evicted_live += 1
        self._entries.popitem(last=False)

    def lookup(self, prefix: str, pin: Optional[str],
               request_id: int) -> Optional[JournalEntry]:
        if not pin:
            return None
        with self._lock:
            return self._entries.get((prefix, pin, int(request_id)))

    def record_progress(self, entry: JournalEntry, tokens: List[int],
                        done: bool) -> None:
        """``tokens`` is the FULL client-visible list so far (the proxy
        polls upstream with cursor 0 precisely so the journal always holds
        a complete prefix to replay from)."""
        with entry.lock:
            entry.tokens = list(tokens)
            entry.done = bool(done)

    def repin(self, entry: JournalEntry, new_pin: str,
              new_request_id: int) -> None:
        """Migration re-pin: the stream continues on ``new_pin`` under
        ``new_request_id``.  Unlike a replay redirect, the destination
        engine force-emits every already-streamed token before resuming
        decode, so the continuation stream carries the FULL client-visible
        list — the redirect offset is 0 and no journal prefix is
        stitched in front of it."""
        with entry.lock:
            entry.redirect = (str(new_pin), int(new_request_id), 0)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "journal_size": len(self._entries),
                "replays": self.replays,
                "replay_failures": self.replay_failures,
                "journal_evicted_live": self.evicted_live,
            }

    # -- recovery ------------------------------------------------------------
    def replay(self, handle, entry: JournalEntry, *,
               timeout: float = 60.0,
               sleep=time.sleep) -> Optional[Tuple[str, int, int]]:
        """Re-submit ``entry`` on a live replica with the delivered tokens
        as a forced prefix.  Returns the redirect tuple, or None when the
        journal already holds the complete stream (nothing left to decode).
        Raises when the request is not replayable or every attempt failed.
        """
        with entry.lock:
            if entry.redirect is not None:
                return entry.redirect  # another poll thread already replayed
            if entry.done:
                return None
            if entry.max_new_tokens is None:
                raise ReplicaGoneError(
                    f"request {entry.request_id} on {entry.pin!r} is gone "
                    "and not replayable (no explicit max_new_tokens)")
            streamed = list(entry.tokens)
            remaining = int(entry.max_new_tokens) - len(streamed)
            if remaining <= 0:
                entry.done = True  # fully delivered before the crash
                return None
            payload: Dict[str, Any] = {
                "action": "submit",
                "prompt": list(entry.prompt) + streamed,
                "max_new_tokens": remaining,
                "priority": entry.priority,
            }
            if entry.deadline_ms is not None:
                payload["deadline_ms"] = entry.deadline_ms
            if entry.adapter_id is not None:
                # the continuation must decode under the SAME tenant
                # adapter or the forced-prefix replay changes tokens
                payload["adapter_id"] = entry.adapter_id
            if entry.tenant is not None:
                # billing continuity: the continuation's tokens belong to
                # the same cost tenant as the stream it resumes
                payload["tenant"] = entry.tenant
            body = json.dumps(payload).encode()
            deadline = Deadline.at_ms(entry.deadline_ms)
            backoff = Backoff(base=0.05, cap=1.0, seed=0)
            last: Optional[BaseException] = None
            with _tracing.span("serve.replay", attrs={
                    "request_id": entry.request_id, "from": entry.pin,
                    "streamed": len(streamed), "remaining": remaining}):
                for attempt in range(1, 6):
                    if deadline is not None and deadline.expired:
                        self._count_failure()
                        raise DeadlineExceededError(
                            f"deadline passed while replaying request "
                            f"{entry.request_id}") from last
                    try:
                        result, tag = handle.call_http_sync_tagged(
                            body, timeout=timeout, pin=None)
                        entry.redirect = (tag, int(result["request_id"]),
                                          len(streamed))
                        entry.replays += 1
                        with self._lock:
                            self.replays += 1
                        return entry.redirect
                    except RemoteError as e:
                        # overload/drain is "retry later"; anything else is
                        # a real error the client should see
                        if not e.cause_repr.startswith(
                                ("EngineOverloadedError",
                                 "EngineDrainingError")):
                            self._count_failure()
                            raise
                        last = e
                    except NoLiveReplicasError as e:
                        last = e  # respawn in progress: back off and retry
                    delay = backoff.next_delay(attempt)
                    if (deadline is not None
                            and delay > deadline.remaining_s()):
                        self._count_failure()
                        raise DeadlineExceededError(
                            f"replay backoff would overrun the deadline for "
                            f"request {entry.request_id}") from last
                    sleep(delay)
            self._count_failure()
            raise last  # type: ignore[misc]

    def _count_failure(self) -> None:
        with self._lock:
            self.replay_failures += 1


def journaled_poll(journal: RequestJournal, handle, prefix: str,
                   payload: Dict[str, Any], pin: Optional[str], *,
                   timeout: float = 300.0) -> Tuple[Dict[str, Any], str]:
    """The proxy's poll path: serve the poll, keep the journal current,
    and recover through a replay when the pinned replica is gone.

    Returns ``(result, header_tag)`` — the header tag stays the ORIGINAL
    pin across a redirect so the client never re-learns its pin."""
    rid = int(payload.get("request_id", -1))
    cursor = int(payload.get("cursor", 0))
    entry = journal.lookup(prefix, pin, rid)
    if entry is not None and (entry.redirect is not None or entry.done):
        return _poll_redirected(journal, handle, entry, cursor,
                                timeout=timeout), pin or ""
    # upstream cursor is ALWAYS 0: the journal needs the full prefix to
    # replay from, and the proxy slices the client's cursor locally
    body = json.dumps({"action": "poll", "request_id": rid,
                       "cursor": 0}).encode()
    try:
        result, tag = handle.call_http_sync_tagged(
            body, timeout=timeout, pin=pin)
    except ReplicaGoneError:
        if entry is None:
            raise  # not journaled (no explicit budget / evicted): 503
        journal.replay(handle, entry)
        return _poll_redirected(journal, handle, entry, cursor,
                                timeout=timeout), pin or ""
    toks = list(result.get("tokens") or [])
    done = bool(result.get("done"))
    if entry is not None:
        journal.record_progress(entry, toks, done)
    return {"tokens": toks[cursor:], "done": done}, tag


class PreemptionWatcher:
    """Driver-side preemption orchestration for one route.

    A daemon thread polls every replica's ``preempt_status`` (cheap — it
    never forces an engine build).  When a replica reports a revocation
    notice the watcher, in order:

    1. signals the autoscaler (``notice_scale_up`` on a side thread —
       capacity is ANNOUNCED to leave, no gauge needed, and the blocking
       spawn must not eat the notice window);
    2. if enough notice remains, MIGRATES: ``migrate_out`` freezes the
       source and returns one payload per live decoding slot;
       ``submit_migrated`` lands each on a survivor, and the journal
       entry is re-pinned so the client's next poll reads the
       destination stream (token-identical, zero re-prefill);
    3. falls back to the PR 13 journal REPLAY for anything it could not
       migrate (notice too short, no survivor, payload rejected): taking
       the source out of rotation makes the next pinned poll raise
       ``ReplicaGoneError``, which ``journaled_poll`` already recovers;
    4. takes the source out of rotation either way — its chips are gone
       at the end of the window whether or not anyone drained.
    """

    def __init__(self, handle, journal: RequestJournal, prefix: str, *,
                 autoscaler=None, poll_s: float = 0.2,
                 min_migrate_notice_s: float = 0.5,
                 migrate_timeout_s: float = 30.0):
        self._handle = handle
        self._journal = journal
        self._prefix = prefix
        self._autoscaler = autoscaler
        self.poll_s = float(poll_s)
        self.min_migrate_notice_s = float(min_migrate_notice_s)
        self.migrate_timeout_s = float(migrate_timeout_s)
        self._lock = threading.Lock()
        self.preemptions = 0
        self.migrations = 0
        self.migrated_pages = 0
        self.migration_fallbacks = 0
        #: worst orchestration wall time (notice observed -> replica out of
        #: rotation): the window during which the doomed replica's streams
        #: are being re-seated — the bench's ``preemption_recovery_ms``
        self.preemption_recovery_ms = 0.0
        self._handled: set = set()  # replica tags already orchestrated
        # replica tags whose coming revocation is a BORROW RETURN (the
        # batch broker handing a soaked replica back, engine_deployment
        # ``borrow_return``): orchestrated exactly like a real preemption
        # — drain, migrate, out of rotation — but WITHOUT the autoscaler
        # backfill, because the capacity is leaving on purpose
        self._borrowed: set = set()
        self.borrow_returns = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def mark_borrowed(self, tag: str) -> None:
        """Flag ``tag``'s next revocation notice as a voluntary borrow
        return (no autoscaler scale-up).  Called by the batch broker
        BEFORE it delivers the notice — the watcher thread only reads the
        flag inside :meth:`_orchestrate`, after the notice lands."""
        with self._lock:
            self._borrowed.add(str(tag))

    # -- replica RPC plumbing -------------------------------------------------
    @staticmethod
    def _call(replica, method: str, *args, timeout: float = 30.0):
        from tpu_air.core import api as core_api

        return core_api.get(
            replica.handle.remote(method, tuple(args), {}), timeout=timeout)

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "PreemptionWatcher":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"preemption-watcher-{self._prefix}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the watcher must outlive any one bad tick
                pass

    # -- one poll round -------------------------------------------------------
    def tick(self) -> None:
        with self._handle._lock:
            replicas = list(self._handle._replicas)
        for replica in replicas:
            tag = replica._actor_id
            # airlint: disable=CC001 — _handled is confined to the single
            # watcher thread (tick + the set below); never read elsewhere
            if tag in self._handled:
                continue
            try:
                status = self._call(replica, "preempt_status", timeout=5.0)
            except Exception:  # noqa: BLE001 — dead/foreign replicas just aren't preempting
                continue
            if not (status or {}).get("preempting"):
                continue
            self._handled.add(tag)
            self._orchestrate(replica, status)

    def _orchestrate(self, replica, status: Dict[str, Any]) -> None:
        tag = replica._actor_id
        t_start = time.monotonic()
        with self._lock:
            self.preemptions += 1
            borrowed = tag in self._borrowed
            if borrowed:
                self._borrowed.discard(tag)
                self.borrow_returns += 1
        if self._autoscaler is not None and not borrowed:
            threading.Thread(  # blocking spawn: keep it off the notice clock
                target=self._notice_autoscaler, daemon=True,
                name=f"preemption-scale-up-{self._prefix}").start()
        notice_left = float(status.get("notice_left_s") or 0.0)
        with _tracing.span("serve.migrate", attrs={
                "from": tag, "notice_s": status.get("notice_s"),
                "notice_left_s": notice_left}):
            migrated_all = False
            if notice_left >= self.min_migrate_notice_s:
                migrated_all = self._migrate(replica)
            if not migrated_all:
                with self._lock:
                    self.migration_fallbacks += 1
        # out of rotation LAST: while migration runs, pinned polls still
        # reach the frozen source and serve correct (stale) prefixes.
        # After this, un-migrated streams' polls raise ReplicaGoneError
        # and journaled_poll replays them on a survivor.
        self._handle.mark_dead(replica)
        recovery_ms = (time.monotonic() - t_start) * 1000.0
        with self._lock:
            self.preemption_recovery_ms = max(
                self.preemption_recovery_ms, recovery_ms)
        # airwatch gets the recovery as a first-class event next to any
        # anomaly the capacity drop trips (off ⇒ one module-global read)
        from tpu_air.observability import watch as _watch

        if _watch.enabled():
            _watch.current().note(
                "preemption.recovered", route=self._prefix, replica=tag,
                recovery_ms=round(recovery_ms, 3),
                migrated_all=migrated_all, borrowed=borrowed)
        # the serve plane took everything it wants from the zombie
        # (payloads migrated, pollers re-pinned or replaying): terminate
        # it so its chips return to the pool — the preempted capacity must
        # be re-leasable, not leaked to a drained husk
        try:
            from tpu_air.core.runtime import get_runtime

            get_runtime().kill_actor(tag)
        except Exception:  # noqa: BLE001 — best-effort reclaim of a dying actor
            pass

    def _notice_autoscaler(self) -> None:
        try:
            self._autoscaler.notice_scale_up()
        except Exception:  # noqa: BLE001 — a failed spawn must not kill the watcher
            pass

    @staticmethod
    def _payload_pages(payload: Dict[str, Any]) -> int:
        first = next(iter((payload.get("pages") or {}).values()), None)
        try:
            return int(first["k"].shape[0]) if first else 0
        except Exception:  # noqa: BLE001 — page count is observability, not control flow
            return 0

    def _migrate(self, source) -> bool:
        """Drain ``source``'s live slots onto survivors.  True only when
        EVERY payload landed (an empty payload list counts — nothing was
        decoding); anything less lets the caller count a fallback and the
        stranded streams take the replay path."""
        tag = source._actor_id
        try:
            payloads = self._call(source, "migrate_out",
                                  timeout=self.migrate_timeout_s)
        except Exception:  # noqa: BLE001 — a frozen/dying source means replay for everyone
            return False
        with self._handle._lock:
            survivors = [r for r in self._handle._replicas
                         if r._actor_id != tag]
        if not survivors and payloads:
            return False
        ok = True
        for i, payload in enumerate(payloads):
            placed = False
            # spread migrated streams across survivors round-robin; on a
            # rejected payload (KVTransferError crossing as RemoteError)
            # try the next survivor before giving the stream to replay
            for j in range(len(survivors)):
                dest = survivors[(i + j) % len(survivors)]
                try:
                    new_rid = self._call(dest, "submit_migrated", payload,
                                         timeout=self.migrate_timeout_s)
                except Exception:  # noqa: BLE001 — rejected here ≠ lost: replay covers it
                    continue
                entry = self._journal.lookup(
                    self._prefix, tag, int(payload.get("request_id", -1)))
                if entry is not None:
                    self._journal.repin(entry, dest._actor_id, new_rid)
                with self._lock:
                    self.migrations += 1
                    self.migrated_pages += self._payload_pages(payload)
                placed = True
                break
            if not placed:
                ok = False
        return ok

    # -- observability --------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "preemptions": self.preemptions,
                "borrow_returns": self.borrow_returns,
                "migrations": self.migrations,
                "migrated_pages": self.migrated_pages,
                "migration_fallbacks": self.migration_fallbacks,
                "preemption_recovery_ms": round(
                    self.preemption_recovery_ms, 3),
            }


def _poll_redirected(journal: RequestJournal, handle, entry: JournalEntry,
                     cursor: int, *, timeout: float = 300.0,
                     _depth: int = 0) -> Dict[str, Any]:
    """Serve a poll for a replayed (or journal-complete) stream: stitch
    ``journal prefix + continuation`` into the client-visible token list."""
    with entry.lock:
        redirect = entry.redirect
        toks = list(entry.tokens)
    if redirect is None:
        # no continuation stream: the journal holds the whole delivery
        return {"tokens": toks[cursor:], "done": True}
    new_pin, new_rid, offset = redirect
    body = json.dumps({"action": "poll", "request_id": new_rid,
                       "cursor": 0}).encode()
    try:
        result, _tag = handle.call_http_sync_tagged(
            body, timeout=timeout, pin=new_pin)
    except ReplicaGoneError:
        # the replacement died too — replay again from the journal prefix
        if _depth >= 3:
            raise
        with entry.lock:
            if entry.redirect == redirect:
                entry.redirect = None
        journal.replay(handle, entry)
        return _poll_redirected(journal, handle, entry, cursor,
                                timeout=timeout, _depth=_depth + 1)
    new_toks = list(result.get("tokens") or [])
    done = bool(result.get("done"))
    with entry.lock:
        full = list(entry.tokens[:offset]) + new_toks
        entry.tokens = full
        entry.done = done
    return {"tokens": full[cursor:], "done": done}
