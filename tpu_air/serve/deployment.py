"""Deployment / Application / DeploymentHandle.

A Deployment is "a managed group of Ray actors that ... handle requests
load-balanced across them" (Introduction_to_Ray_AI_Runtime.ipynb:cc-79).
``.options(name=..., num_replicas=..., route_prefix=...)`` + ``.bind(*args)``
mirror the reference call shape (cc-71).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional

from tpu_air.core import api as core_api
from tpu_air.core.runtime import RemoteError, TpuAirError


class NoLiveReplicasError(TpuAirError):
    """Every replica of a deployment is dead (the proxy maps this to 503)."""


class ReplicaGoneError(TpuAirError):
    """A request pinned to a specific replica (streaming poll via the
    ``x-tpu-air-replica`` header) found that replica out of rotation.  The
    proxy maps this to 503: the stream's state died with the replica, so
    the client must re-submit — rollouts drain before killing precisely so
    admitted streams never hit this."""


def _is_death(e: Exception) -> bool:
    """True when a RemoteError means the replica process died (crash /
    kill / placement failure) rather than the application code raising."""
    return isinstance(e, RemoteError) and e.cause_repr.startswith(
        ("WorkerCrashed", "ActorDiedError", "ActorPlacementFailed")
    )


def _actor_dead(replica) -> bool:
    """Liveness of a replica actor straight from the runtime's actor table —
    no ping task needed (worker death is detected on pipe close)."""
    from tpu_air.core import runtime as rt_mod

    rt = rt_mod.get_runtime()
    with rt.lock:
        st = rt.actors.get(replica._actor_id)
        if st is None:
            # not in the table: dead unless its creation is still queued
            return replica._actor_id not in rt.pending_actors
        # st.worker.alive is the LISTENER's view and lags a kill by one
        # pipe-EOF detection; /-/healthz right after a replica dies must
        # not report 200, so ask the process itself (ROADMAP item 3a)
        return st.dead or not st.worker.alive or not st.worker.proc.is_alive()


@dataclass(frozen=True)
class Deployment:
    """A replicated callable class. ``func_or_class`` instances run as core
    runtime actors; each instance handles requests via ``__call__`` (or a
    named method through the handle)."""

    func_or_class: Any
    name: str = ""
    num_replicas: int = 1
    route_prefix: Optional[str] = None
    num_cpus: float = 0.0
    num_chips: float = 0.0
    # dead-replica restart budget: -1 = unlimited (default), 0 = never
    max_restarts: int = -1

    def options(
        self,
        name: Optional[str] = None,
        num_replicas: Optional[int] = None,
        route_prefix: Optional[str] = None,
        num_cpus: Optional[float] = None,
        num_chips: Optional[float] = None,
        ray_actor_options: Optional[Dict[str, Any]] = None,
        max_restarts: Optional[int] = None,
        **_ignored,
    ) -> "Deployment":
        kw: Dict[str, Any] = {}
        if name is not None:
            kw["name"] = name
        if num_replicas is not None:
            kw["num_replicas"] = num_replicas
        if route_prefix is not None:
            kw["route_prefix"] = route_prefix
        if max_restarts is not None:
            kw["max_restarts"] = max_restarts
        opts = dict(ray_actor_options or {})
        if num_cpus is not None or "num_cpus" in opts:
            kw["num_cpus"] = float(num_cpus if num_cpus is not None else opts["num_cpus"])
        if num_chips is not None or "num_chips" in opts:
            kw["num_chips"] = float(num_chips if num_chips is not None else opts["num_chips"])
        return replace(self, **kw)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    route_prefix: Optional[str] = None,
    num_cpus: float = 0.0,
    num_chips: float = 0.0,
    max_restarts: int = -1,
    **_ignored,
):
    """``@serve.deployment`` decorator (bare or parameterized)."""

    def make(obj):
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            route_prefix=route_prefix,
            num_cpus=num_cpus,
            num_chips=num_chips,
            max_restarts=max_restarts,
        )

    if _func_or_class is not None:
        return make(_func_or_class)
    return make


@dataclass
class Application:
    """A Deployment bound to constructor args — what ``serve.run`` deploys."""

    deployment: Deployment
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)


class _Replica:
    """Actor body wrapping one instance of the deployment class."""

    def __init__(self, cls, init_args, init_kwargs):
        self._obj = cls(*init_args, **init_kwargs)

    def handle(self, method: Optional[str], args, kwargs):
        target = self._obj if method is None else getattr(self._obj, method)
        return target(*args, **kwargs)

    def handle_http(self, body: bytes):
        """Adapt the raw request body and invoke the deployment object."""
        from .http_adapters import json_request

        obj = self._obj
        if hasattr(obj, "handle_http"):
            return obj.handle_http(body)
        adapter = getattr(obj, "_http_adapter", None) or json_request
        return obj(adapter(body))

    def ping(self):
        return "ok"

    def drain(self):
        """Forward a drain to the wrapped object (EngineDeployment stops
        admitting; a plain deployment has nothing to drain)."""
        fn = getattr(self._obj, "drain", None)
        if callable(fn):
            fn()
        return "ok"

    def drain_status(self) -> Dict[str, Any]:
        """Whether the wrapped object finished draining.  Objects without
        the protocol are stateless per-request handlers: always drained."""
        fn = getattr(self._obj, "drain_status", None)
        if callable(fn):
            out = fn()
            if isinstance(out, dict):
                return out
        return {"draining": True, "drained": True}

    def engine_stats(self) -> Dict[str, Any]:
        """Engine-metrics snapshot from the wrapped object, when it exposes
        one (``EngineDeployment``'s ``stats``); ``{}`` for plain deployments.
        The dashboard merges these into ``/api/engines`` and ``/metrics``."""
        stats = getattr(self._obj, "stats", None)
        if not callable(stats):
            return {}
        out = stats()
        return out if isinstance(out, dict) else {}


class DeploymentHandle:
    """Least-loaded handle over a deployment's live replica actors, with
    failure semantics (VERDICT r2 item 7; reference: "a managed group of Ray
    actors that ... handle requests load-balanced across them", cc-79):

    * replica choice is LEAST-LOADED over the engine gauges the last
      ``engine_stats`` scrape recorded (queue depth + slot occupancy,
      adjusted by this handle's own in-flight call counts); when the
      scrape is stale (> ``_loads_ttl``) it falls back to round-robin;
    * a replica that died (crash or kill) is dropped from rotation as soon
      as a call to it fails or the restart controller notices;
    * synchronous calls fail over to the remaining live replicas — an
      application-level exception is NOT retried, only replica death; a
      call PINNED to one replica (streaming poll) never fails over — its
      state lived there — and raises :class:`ReplicaGoneError` instead;
    * a background controller respawns dead replicas back up to the
      handle's replica TARGET (initially ``num_replicas``; the autoscaler
      moves it via :meth:`scale_up` / :meth:`scale_down`), bounded by the
      deployment's ``max_restarts``;
    * :meth:`rollout` swaps every replica with a freshly spawned one,
      draining each old replica before killing it — in-flight streams
      keep polling the draining replica through their pin, so a deploy
      under load loses zero admitted streams;
    * when nothing is live, :class:`NoLiveReplicasError` (proxy → 503).
    """

    def __init__(self, app: Application, replicas: List[Any]):
        d = app.deployment
        self.deployment_name = d.name
        self._app = app
        self._replicas = list(replicas)  # live rotation
        self._rr = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._restarts_left = d.max_restarts  # -1 = unlimited
        self._target = d.num_replicas    # autoscaler-mutable replica target
        self._spawning = 0               # scale_up spawns in flight (not yet in rotation)
        self._draining: List[Any] = []   # out of rotation, pinned-reachable
        self._inflight: Dict[str, int] = {}  # actor id -> in-flight calls
        self._loads: Dict[str, float] = {}   # actor id -> scraped load
        self._loads_at = 0.0
        self._loads_ttl = 3.0            # stale loads → round-robin fallback
        self._controller = None
        if d.max_restarts != 0:
            import weakref

            # the thread holds only a weakref: a handle the application
            # dropped must be collectable (and its controller must exit),
            # not kept alive forever by its own controller's bound method
            self._controller = threading.Thread(
                target=_controller_main, args=(weakref.ref(self),),
                daemon=True, name=f"serve-controller-{d.name}",
            )
            self._controller.start()

    # -- replica selection ---------------------------------------------------
    def _next_replica(self, pin: Optional[str] = None):
        with self._lock:
            if pin is not None:
                # pinned (streaming poll): the stream's state lives on ONE
                # replica — in rotation or draining, never a different one
                for r in self._replicas + self._draining:
                    if r._actor_id == pin:
                        return r
                raise ReplicaGoneError(
                    f"deployment {self.deployment_name!r}: pinned replica "
                    f"{pin!r} is gone (crashed or already retired)"
                )
            if not self._replicas:
                raise NoLiveReplicasError(
                    f"deployment {self.deployment_name!r}: all replicas dead"
                )
            n = len(self._replicas)
            self._rr = (self._rr + 1) % n
            if self._loads and time.monotonic() - self._loads_at <= self._loads_ttl:
                # least-loaded: last scraped engine load plus our own
                # in-flight calls (covers load the scrape hasn't seen yet);
                # ties rotate with the round-robin cursor so equally idle
                # replicas still alternate
                rr = self._rr

                def load_key(ir):
                    i, r = ir
                    return (self._loads.get(r._actor_id, 0.0)
                            + self._inflight.get(r._actor_id, 0),
                            (i - rr) % n)

                _, best = min(enumerate(self._replicas), key=load_key)
                return best
            return self._replicas[self._rr]  # stats stale: round-robin

    def mark_dead(self, replica) -> None:
        """Drop a replica from rotation (called on observed death)."""
        with self._lock:
            self._replicas = [r for r in self._replicas if r is not replica]
            self._draining = [r for r in self._draining if r is not replica]

    def num_replicas(self) -> int:
        """Cheap rotation size (no liveness probe — used on the request
        hot path to bound failover retries)."""
        with self._lock:
            return len(self._replicas)

    def live_replicas(self) -> int:
        """Count of LIVE replicas, pruning dead ones.  Used by health/status
        endpoints so reporting is accurate even with the restart controller
        disabled (max_restarts=0) and no traffic since a replica died.  Not
        for the request path: each liveness check takes the runtime lock."""
        with self._lock:
            self._replicas = [r for r in self._replicas if not _actor_dead(r)]
            return len(self._replicas)

    def engine_stats(self, timeout: float = 10.0) -> Dict[str, Dict[str, Any]]:
        """Engine-metrics snapshots from every replica in rotation, keyed
        ``<deployment>/<replica-idx>/<engine-name>``.  Replicas without an
        engine (plain deployments, or an EngineDeployment that hasn't built
        yet) contribute nothing; a dying replica must not fail the scrape."""
        with self._lock:
            replicas = list(self._replicas)
        out: Dict[str, Dict[str, Any]] = {}
        loads: Dict[str, float] = {}
        for i, replica in enumerate(replicas):
            try:
                snap = core_api.get(replica.engine_stats.remote(),
                                    timeout=timeout)
            except Exception:  # noqa: BLE001 — scrape is best-effort
                continue
            # even an empty snap ({} — engine not built yet) is a load
            # sample: an idle replica should attract traffic
            loads[replica._actor_id] = (
                float(snap.get("queue_depth", 0))
                + float(snap.get("slot_occupancy", 0)))
            if snap:
                key = f"{self.deployment_name}/{i}/{snap.get('name', 'engine')}"
                out[key] = snap
        if loads:
            # side effect: the scrape doubles as the least-loaded routing
            # signal (_next_replica); staleness re-enables round-robin
            with self._lock:
                self._loads = loads
                self._loads_at = time.monotonic()
        return out

    # -- calls ---------------------------------------------------------------
    def remote(self, *args, **kwargs):
        """Call the replica object (``__call__``); returns an ObjectRef."""
        return self._next_replica().handle.remote(None, args, kwargs)

    def method(self, name: str) -> Callable:
        def call(*args, **kwargs):
            return self._next_replica().handle.remote(name, args, kwargs)

        return call

    def remote_http(self, body: bytes):
        """Route raw HTTP body bytes to a replica's adapter + callable."""
        return self._next_replica().handle_http.remote(body)

    def call_http_sync(self, body: bytes, timeout: float = 300.0):
        """HTTP-path call with failover: a request in flight on a replica
        that crashes is transparently retried on the next live one."""
        return self.call_http_sync_tagged(body, timeout=timeout)[0]

    def call_http_sync_tagged(self, body: bytes, timeout: float = 300.0,
                              pin: Optional[str] = None):
        """Like :meth:`call_http_sync` but returns ``(result, replica_tag)``
        so the proxy can round-trip the serving replica to the client
        (``x-tpu-air-replica``).  ``pin`` routes to that exact replica —
        required for streaming polls, whose cursor state lives on the
        replica that took the submit; a pinned call never fails over
        (:class:`ReplicaGoneError` if the replica left)."""
        # bound retries by the starting live count + respawn headroom so a
        # crash-looping deployment can't loop forever
        for _ in range(max(self.num_replicas(), 1) + 2):
            replica = self._next_replica(pin=pin)
            tag = replica._actor_id
            with self._lock:
                self._inflight[tag] = self._inflight.get(tag, 0) + 1
            try:
                return (
                    core_api.get(replica.handle_http.remote(body),
                                 timeout=timeout),
                    tag,
                )
            except RemoteError as e:
                if not _is_death(e):
                    raise  # application error: surface, don't failover
                self.mark_dead(replica)
                if pin is not None:
                    raise ReplicaGoneError(
                        f"deployment {self.deployment_name!r}: pinned "
                        f"replica {pin!r} died mid-call"
                    )
            finally:
                with self._lock:
                    left = self._inflight.get(tag, 1) - 1
                    if left <= 0:
                        self._inflight.pop(tag, None)
                    else:
                        self._inflight[tag] = left
        raise NoLiveReplicasError(
            f"deployment {self.deployment_name!r}: replicas keep dying"
        )

    # -- scaling (autoscaler entry points) -----------------------------------
    def target_replicas(self) -> int:
        """The replica count the restart controller maintains (starts at
        the deployment's ``num_replicas``; scale_up/scale_down move it)."""
        with self._lock:
            return self._target

    def scale_up(self, timeout: float = 120.0) -> bool:
        """Add one replica: a fresh actor through the runtime's normal
        placement path (process + chip lease), pinged live, then entered
        into rotation.  Returns False (and restores the target) when the
        spawn fails — the autoscaler treats that as "hold"."""
        with self._lock:
            if self._stop.is_set():
                return False
            self._target += 1
            # the restart controller must not read target-minus-live as a
            # deficit while THIS spawn is still pinging — it would spawn a
            # phantom second replica nothing ever retires
            self._spawning += 1
        replica = None
        try:
            replica = _spawn_replica(self._app)
            core_api.get(replica.ping.remote(), timeout=timeout)
            with self._lock:
                if self._stop.is_set():
                    raise NoLiveReplicasError("handle retired during scale-up")
                self._replicas.append(replica)
                self._spawning -= 1
            return True
        except Exception:  # noqa: BLE001 — failed scale-up must not leak the spawn
            with self._lock:
                self._target -= 1
                self._spawning -= 1
            if replica is not None:
                from tpu_air.core.remote import kill

                try:
                    kill(replica)
                except Exception:  # noqa: BLE001 — best-effort kill; replica may already be dead
                    pass
            return False

    def shrink_target(self) -> int:
        """Lower the restart controller's replica target by one (floor 1)
        WITHOUT retiring anyone here — for callers already retiring a
        specific replica through another path (the batch lane's borrow
        return rides the preemption watcher's drain; without this the
        controller would respawn the returned replica right back).
        Returns the new target."""
        with self._lock:
            if self._target > 1:
                self._target -= 1
            return self._target

    def scale_down(self, timeout: float = 120.0) -> bool:
        """Remove one replica, gracefully: out of rotation FIRST (no new
        work routes to it; its in-flight streams keep polling it through
        their pin), then drain, then kill — which releases its process and
        chip lease.  Never drops the last replica."""
        with self._lock:
            if len(self._replicas) <= 1 or self._target <= 1:
                return False
            self._target -= 1
            victim = min(
                self._replicas,
                key=lambda r: (self._inflight.get(r._actor_id, 0)
                               + self._loads.get(r._actor_id, 0.0)),
            )
            self._replicas = [r for r in self._replicas if r is not victim]
            self._draining.append(victim)
        self._drain_and_kill(victim, timeout)
        return True

    def rollout(self, timeout: float = 120.0) -> int:
        """Zero-downtime replica swap: for every replica in rotation at
        call time, spawn-and-ping a replacement, enter it into rotation,
        pull the old one out, DRAIN it (admitted streams keep polling it
        via their pin until every token is delivered), then kill it.
        Returns the number of replicas swapped."""
        with self._lock:
            old = list(self._replicas)
        swapped = 0
        for replica in old:
            fresh = _spawn_replica(self._app)
            try:
                core_api.get(fresh.ping.remote(), timeout=timeout)
            except Exception:  # noqa: BLE001 — ANY spawn/ping failure (death, timeout, init error) must abort the rollout before the old replica is touched; re-raised below
                from tpu_air.core.remote import kill

                try:
                    kill(fresh)
                except Exception:  # noqa: BLE001 — best-effort kill; replica may already be dead
                    pass
                raise  # a rollout that can't spawn must fail loudly
            with self._lock:
                self._replicas.append(fresh)
                if replica in self._replicas:
                    self._replicas.remove(replica)
                    self._draining.append(replica)
                else:
                    # crashed (or scaled away) since the snapshot: the
                    # replacement still counts, nothing left to drain
                    swapped += 1
                    continue
            self._drain_and_kill(replica, timeout)
            swapped += 1
        return swapped

    def _drain_and_kill(self, replica, timeout: float = 120.0) -> None:
        """Drain one out-of-rotation replica, wait until it reports
        ``drained`` AND this handle has zero in-flight calls on it (a
        request could have picked it just before it left rotation), then
        kill it.  The timeout bounds an abandoned stream's hold."""
        from tpu_air.core.remote import kill

        tag = replica._actor_id
        try:
            core_api.get(replica.drain.remote(), timeout=30.0)
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    inflight = self._inflight.get(tag, 0)
                st = core_api.get(replica.drain_status.remote(), timeout=10.0)
                if st.get("drained") and inflight == 0:
                    break
                time.sleep(0.05)
        except Exception:  # noqa: BLE001 — a dying/dead replica can't block the drain
            pass
        try:
            kill(replica)
        except Exception:  # noqa: BLE001 — best-effort kill; replica may already be dead
            pass
        with self._lock:
            self._draining = [r for r in self._draining if r is not replica]

    # -- restart controller --------------------------------------------------
    def _control_tick(self, backoff: float) -> float:
        """One controller iteration: prune dead replicas, respawn the
        deficit vs the handle's replica TARGET (``num_replicas`` until the
        autoscaler moves it).  Returns the next crash-loop backoff."""
        with self._lock:
            live = [r for r in self._replicas if not _actor_dead(r)]
            pruned = len(self._replicas) - len(live)
            self._replicas = live
            # in-flight scale_up spawns already cover part of the target
            deficit = self._target - len(live) - self._spawning
        if pruned:
            backoff = 0.25  # fresh death: reset the crash-loop backoff
        if deficit <= 0 or self._restarts_left == 0:
            return backoff
        replica = None
        try:
            replica = _spawn_replica(self._app)
            core_api.get(replica.ping.remote(), timeout=60.0)
            with self._lock:
                if self._stop.is_set():
                    # _retire snapshotted-and-killed the rotation while we
                    # were pinging: this fresh replica must not outlive it
                    raise NoLiveReplicasError("handle retired during respawn")
                self._replicas.append(replica)
            if self._restarts_left > 0:
                self._restarts_left -= 1
            return 0.25
        except Exception:  # noqa: BLE001 — crash loop: back off, retry
            if replica is not None:
                # a replica that failed/timed-out its ping still holds a
                # worker process + lease — it must not leak per attempt
                from tpu_air.core.remote import kill

                try:
                    kill(replica)
                except Exception:  # noqa: BLE001 — best-effort kill; replica may already be dead
                    pass
            self._stop.wait(backoff)
            return min(backoff * 2, 10.0)

    def stop(self):
        self._stop.set()


def _controller_main(handle_ref) -> None:
    """Controller thread body.  Re-derefs the weakref each tick so a handle
    with no other referents is GC'd and the thread exits."""
    backoff = 0.25
    while True:
        handle = handle_ref()
        if handle is None:
            return
        stop_evt = handle._stop
        del handle  # don't pin the handle across the wait
        if stop_evt.wait(0.25):
            return
        handle = handle_ref()
        if handle is None:
            return
        try:
            backoff = handle._control_tick(backoff)
        finally:
            del handle


def _spawn_replica(app: Application):
    from tpu_air.core.remote import remote

    d = app.deployment
    actor_cls = remote(num_cpus=d.num_cpus, num_chips=d.num_chips)(_Replica)
    return actor_cls.remote(d.func_or_class, app.init_args, app.init_kwargs)


def start_replicas(app: Application) -> DeploymentHandle:
    """Instantiate the application's replica actors and wait until live."""
    replicas = [_spawn_replica(app) for _ in range(app.deployment.num_replicas)]
    core_api.get([r.ping.remote() for r in replicas])  # surface init errors now
    return DeploymentHandle(app, replicas)
