"""Deployment / Application / DeploymentHandle.

A Deployment is "a managed group of Ray actors that ... handle requests
load-balanced across them" (Introduction_to_Ray_AI_Runtime.ipynb:cc-79).
``.options(name=..., num_replicas=..., route_prefix=...)`` + ``.bind(*args)``
mirror the reference call shape (cc-71).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional

from tpu_air.core import api as core_api


@dataclass(frozen=True)
class Deployment:
    """A replicated callable class. ``func_or_class`` instances run as core
    runtime actors; each instance handles requests via ``__call__`` (or a
    named method through the handle)."""

    func_or_class: Any
    name: str = ""
    num_replicas: int = 1
    route_prefix: Optional[str] = None
    num_cpus: float = 0.0
    num_chips: float = 0.0

    def options(
        self,
        name: Optional[str] = None,
        num_replicas: Optional[int] = None,
        route_prefix: Optional[str] = None,
        num_cpus: Optional[float] = None,
        num_chips: Optional[float] = None,
        ray_actor_options: Optional[Dict[str, Any]] = None,
        **_ignored,
    ) -> "Deployment":
        kw: Dict[str, Any] = {}
        if name is not None:
            kw["name"] = name
        if num_replicas is not None:
            kw["num_replicas"] = num_replicas
        if route_prefix is not None:
            kw["route_prefix"] = route_prefix
        opts = dict(ray_actor_options or {})
        if num_cpus is not None or "num_cpus" in opts:
            kw["num_cpus"] = float(num_cpus if num_cpus is not None else opts["num_cpus"])
        if num_chips is not None or "num_chips" in opts:
            kw["num_chips"] = float(num_chips if num_chips is not None else opts["num_chips"])
        return replace(self, **kw)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    route_prefix: Optional[str] = None,
    num_cpus: float = 0.0,
    num_chips: float = 0.0,
    **_ignored,
):
    """``@serve.deployment`` decorator (bare or parameterized)."""

    def make(obj):
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            route_prefix=route_prefix,
            num_cpus=num_cpus,
            num_chips=num_chips,
        )

    if _func_or_class is not None:
        return make(_func_or_class)
    return make


@dataclass
class Application:
    """A Deployment bound to constructor args — what ``serve.run`` deploys."""

    deployment: Deployment
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)


class _Replica:
    """Actor body wrapping one instance of the deployment class."""

    def __init__(self, cls, init_args, init_kwargs):
        self._obj = cls(*init_args, **init_kwargs)

    def handle(self, method: Optional[str], args, kwargs):
        target = self._obj if method is None else getattr(self._obj, method)
        return target(*args, **kwargs)

    def handle_http(self, body: bytes):
        """Adapt the raw request body and invoke the deployment object."""
        from .http_adapters import json_request

        obj = self._obj
        if hasattr(obj, "handle_http"):
            return obj.handle_http(body)
        adapter = getattr(obj, "_http_adapter", None) or json_request
        return obj(adapter(body))

    def ping(self):
        return "ok"


class DeploymentHandle:
    """Round-robin handle over a deployment's live replica actors."""

    def __init__(self, name: str, replicas: List[Any]):
        self.deployment_name = name
        self._replicas = replicas
        self._rr = itertools.cycle(range(len(replicas)))
        self._lock = threading.Lock()

    def _next_replica(self):
        with self._lock:
            return self._replicas[next(self._rr)]

    def remote(self, *args, **kwargs):
        """Call the replica object (``__call__``); returns an ObjectRef."""
        return self._next_replica().handle.remote(None, args, kwargs)

    def method(self, name: str) -> Callable:
        def call(*args, **kwargs):
            return self._next_replica().handle.remote(name, args, kwargs)

        return call

    def remote_http(self, body: bytes):
        """Route raw HTTP body bytes to a replica's adapter + callable."""
        return self._next_replica().handle_http.remote(body)

    def num_replicas(self) -> int:
        return len(self._replicas)


def start_replicas(app: Application) -> DeploymentHandle:
    """Instantiate the application's replica actors and wait until live."""
    from tpu_air.core.remote import remote

    d = app.deployment
    actor_cls = remote(num_cpus=d.num_cpus, num_chips=d.num_chips)(_Replica)
    replicas = [
        actor_cls.remote(d.func_or_class, app.init_args, app.init_kwargs)
        for _ in range(d.num_replicas)
    ]
    core_api.get([r.ping.remote() for r in replicas])  # surface init errors now
    return DeploymentHandle(d.name, replicas)
