"""Deployment / Application / DeploymentHandle.

A Deployment is "a managed group of Ray actors that ... handle requests
load-balanced across them" (Introduction_to_Ray_AI_Runtime.ipynb:cc-79).
``.options(name=..., num_replicas=..., route_prefix=...)`` + ``.bind(*args)``
mirror the reference call shape (cc-71).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional

from tpu_air.core import api as core_api
from tpu_air.core.runtime import RemoteError, TpuAirError


class NoLiveReplicasError(TpuAirError):
    """Every replica of a deployment is dead (the proxy maps this to 503)."""


def _is_death(e: Exception) -> bool:
    """True when a RemoteError means the replica process died (crash /
    kill / placement failure) rather than the application code raising."""
    return isinstance(e, RemoteError) and e.cause_repr.startswith(
        ("WorkerCrashed", "ActorDiedError", "ActorPlacementFailed")
    )


def _actor_dead(replica) -> bool:
    """Liveness of a replica actor straight from the runtime's actor table —
    no ping task needed (worker death is detected on pipe close)."""
    from tpu_air.core import runtime as rt_mod

    rt = rt_mod.get_runtime()
    with rt.lock:
        st = rt.actors.get(replica._actor_id)
        if st is None:
            # not in the table: dead unless its creation is still queued
            return replica._actor_id not in rt.pending_actors
        # st.worker.alive is the LISTENER's view and lags a kill by one
        # pipe-EOF detection; /-/healthz right after a replica dies must
        # not report 200, so ask the process itself (ROADMAP item 3a)
        return st.dead or not st.worker.alive or not st.worker.proc.is_alive()


@dataclass(frozen=True)
class Deployment:
    """A replicated callable class. ``func_or_class`` instances run as core
    runtime actors; each instance handles requests via ``__call__`` (or a
    named method through the handle)."""

    func_or_class: Any
    name: str = ""
    num_replicas: int = 1
    route_prefix: Optional[str] = None
    num_cpus: float = 0.0
    num_chips: float = 0.0
    # dead-replica restart budget: -1 = unlimited (default), 0 = never
    max_restarts: int = -1

    def options(
        self,
        name: Optional[str] = None,
        num_replicas: Optional[int] = None,
        route_prefix: Optional[str] = None,
        num_cpus: Optional[float] = None,
        num_chips: Optional[float] = None,
        ray_actor_options: Optional[Dict[str, Any]] = None,
        max_restarts: Optional[int] = None,
        **_ignored,
    ) -> "Deployment":
        kw: Dict[str, Any] = {}
        if name is not None:
            kw["name"] = name
        if num_replicas is not None:
            kw["num_replicas"] = num_replicas
        if route_prefix is not None:
            kw["route_prefix"] = route_prefix
        if max_restarts is not None:
            kw["max_restarts"] = max_restarts
        opts = dict(ray_actor_options or {})
        if num_cpus is not None or "num_cpus" in opts:
            kw["num_cpus"] = float(num_cpus if num_cpus is not None else opts["num_cpus"])
        if num_chips is not None or "num_chips" in opts:
            kw["num_chips"] = float(num_chips if num_chips is not None else opts["num_chips"])
        return replace(self, **kw)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


def deployment(
    _func_or_class=None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    route_prefix: Optional[str] = None,
    num_cpus: float = 0.0,
    num_chips: float = 0.0,
    max_restarts: int = -1,
    **_ignored,
):
    """``@serve.deployment`` decorator (bare or parameterized)."""

    def make(obj):
        return Deployment(
            func_or_class=obj,
            name=name or getattr(obj, "__name__", "deployment"),
            num_replicas=num_replicas,
            route_prefix=route_prefix,
            num_cpus=num_cpus,
            num_chips=num_chips,
            max_restarts=max_restarts,
        )

    if _func_or_class is not None:
        return make(_func_or_class)
    return make


@dataclass
class Application:
    """A Deployment bound to constructor args — what ``serve.run`` deploys."""

    deployment: Deployment
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)


class _Replica:
    """Actor body wrapping one instance of the deployment class."""

    def __init__(self, cls, init_args, init_kwargs):
        self._obj = cls(*init_args, **init_kwargs)

    def handle(self, method: Optional[str], args, kwargs):
        target = self._obj if method is None else getattr(self._obj, method)
        return target(*args, **kwargs)

    def handle_http(self, body: bytes):
        """Adapt the raw request body and invoke the deployment object."""
        from .http_adapters import json_request

        obj = self._obj
        if hasattr(obj, "handle_http"):
            return obj.handle_http(body)
        adapter = getattr(obj, "_http_adapter", None) or json_request
        return obj(adapter(body))

    def ping(self):
        return "ok"

    def engine_stats(self) -> Dict[str, Any]:
        """Engine-metrics snapshot from the wrapped object, when it exposes
        one (``EngineDeployment``'s ``stats``); ``{}`` for plain deployments.
        The dashboard merges these into ``/api/engines`` and ``/metrics``."""
        stats = getattr(self._obj, "stats", None)
        if not callable(stats):
            return {}
        out = stats()
        return out if isinstance(out, dict) else {}


class DeploymentHandle:
    """Round-robin handle over a deployment's live replica actors, with
    failure semantics (VERDICT r2 item 7; reference: "a managed group of Ray
    actors that ... handle requests load-balanced across them", cc-79):

    * a replica that died (crash or kill) is dropped from rotation as soon
      as a call to it fails or the restart controller notices;
    * synchronous calls fail over to the remaining live replicas — an
      application-level exception is NOT retried, only replica death;
    * a background controller respawns dead replicas back up to
      ``num_replicas`` (bounded by the deployment's ``max_restarts``);
    * when nothing is live, :class:`NoLiveReplicasError` (proxy → 503).
    """

    def __init__(self, app: Application, replicas: List[Any]):
        d = app.deployment
        self.deployment_name = d.name
        self._app = app
        self._replicas = list(replicas)  # live rotation
        self._rr = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._restarts_left = d.max_restarts  # -1 = unlimited
        self._controller = None
        if d.max_restarts != 0:
            import weakref

            # the thread holds only a weakref: a handle the application
            # dropped must be collectable (and its controller must exit),
            # not kept alive forever by its own controller's bound method
            self._controller = threading.Thread(
                target=_controller_main, args=(weakref.ref(self),),
                daemon=True, name=f"serve-controller-{d.name}",
            )
            self._controller.start()

    # -- replica selection ---------------------------------------------------
    def _next_replica(self):
        with self._lock:
            if not self._replicas:
                raise NoLiveReplicasError(
                    f"deployment {self.deployment_name!r}: all replicas dead"
                )
            self._rr = (self._rr + 1) % len(self._replicas)
            return self._replicas[self._rr]

    def mark_dead(self, replica) -> None:
        """Drop a replica from rotation (called on observed death)."""
        with self._lock:
            self._replicas = [r for r in self._replicas if r is not replica]

    def num_replicas(self) -> int:
        """Cheap rotation size (no liveness probe — used on the request
        hot path to bound failover retries)."""
        with self._lock:
            return len(self._replicas)

    def live_replicas(self) -> int:
        """Count of LIVE replicas, pruning dead ones.  Used by health/status
        endpoints so reporting is accurate even with the restart controller
        disabled (max_restarts=0) and no traffic since a replica died.  Not
        for the request path: each liveness check takes the runtime lock."""
        with self._lock:
            self._replicas = [r for r in self._replicas if not _actor_dead(r)]
            return len(self._replicas)

    def engine_stats(self, timeout: float = 10.0) -> Dict[str, Dict[str, Any]]:
        """Engine-metrics snapshots from every replica in rotation, keyed
        ``<deployment>/<replica-idx>/<engine-name>``.  Replicas without an
        engine (plain deployments, or an EngineDeployment that hasn't built
        yet) contribute nothing; a dying replica must not fail the scrape."""
        with self._lock:
            replicas = list(self._replicas)
        out: Dict[str, Dict[str, Any]] = {}
        for i, replica in enumerate(replicas):
            try:
                snap = core_api.get(replica.engine_stats.remote(),
                                    timeout=timeout)
            except Exception:  # noqa: BLE001 — scrape is best-effort
                continue
            if snap:
                key = f"{self.deployment_name}/{i}/{snap.get('name', 'engine')}"
                out[key] = snap
        return out

    # -- calls ---------------------------------------------------------------
    def remote(self, *args, **kwargs):
        """Call the replica object (``__call__``); returns an ObjectRef."""
        return self._next_replica().handle.remote(None, args, kwargs)

    def method(self, name: str) -> Callable:
        def call(*args, **kwargs):
            return self._next_replica().handle.remote(name, args, kwargs)

        return call

    def remote_http(self, body: bytes):
        """Route raw HTTP body bytes to a replica's adapter + callable."""
        return self._next_replica().handle_http.remote(body)

    def call_http_sync(self, body: bytes, timeout: float = 300.0):
        """HTTP-path call with failover: a request in flight on a replica
        that crashes is transparently retried on the next live one."""
        # bound retries by the starting live count + respawn headroom so a
        # crash-looping deployment can't loop forever
        for _ in range(max(self.num_replicas(), 1) + 2):
            replica = self._next_replica()
            try:
                return core_api.get(replica.handle_http.remote(body), timeout=timeout)
            except RemoteError as e:
                if not _is_death(e):
                    raise  # application error: surface, don't failover
                self.mark_dead(replica)
        raise NoLiveReplicasError(
            f"deployment {self.deployment_name!r}: replicas keep dying"
        )

    # -- restart controller --------------------------------------------------
    def _control_tick(self, backoff: float) -> float:
        """One controller iteration: prune dead replicas, respawn the
        deficit.  Returns the next crash-loop backoff."""
        with self._lock:
            live = [r for r in self._replicas if not _actor_dead(r)]
            pruned = len(self._replicas) - len(live)
            self._replicas = live
            deficit = self._app.deployment.num_replicas - len(live)
        if pruned:
            backoff = 0.25  # fresh death: reset the crash-loop backoff
        if deficit <= 0 or self._restarts_left == 0:
            return backoff
        replica = None
        try:
            replica = _spawn_replica(self._app)
            core_api.get(replica.ping.remote(), timeout=60.0)
            with self._lock:
                if self._stop.is_set():
                    # _retire snapshotted-and-killed the rotation while we
                    # were pinging: this fresh replica must not outlive it
                    raise NoLiveReplicasError("handle retired during respawn")
                self._replicas.append(replica)
            if self._restarts_left > 0:
                self._restarts_left -= 1
            return 0.25
        except Exception:  # noqa: BLE001 — crash loop: back off, retry
            if replica is not None:
                # a replica that failed/timed-out its ping still holds a
                # worker process + lease — it must not leak per attempt
                from tpu_air.core.remote import kill

                try:
                    kill(replica)
                except Exception:  # noqa: BLE001 — best-effort kill; replica may already be dead
                    pass
            self._stop.wait(backoff)
            return min(backoff * 2, 10.0)

    def stop(self):
        self._stop.set()


def _controller_main(handle_ref) -> None:
    """Controller thread body.  Re-derefs the weakref each tick so a handle
    with no other referents is GC'd and the thread exits."""
    backoff = 0.25
    while True:
        handle = handle_ref()
        if handle is None:
            return
        stop_evt = handle._stop
        del handle  # don't pin the handle across the wait
        if stop_evt.wait(0.25):
            return
        handle = handle_ref()
        if handle is None:
            return
        try:
            backoff = handle._control_tick(backoff)
        finally:
            del handle


def _spawn_replica(app: Application):
    from tpu_air.core.remote import remote

    d = app.deployment
    actor_cls = remote(num_cpus=d.num_cpus, num_chips=d.num_chips)(_Replica)
    return actor_cls.remote(d.func_or_class, app.init_args, app.init_kwargs)


def start_replicas(app: Application) -> DeploymentHandle:
    """Instantiate the application's replica actors and wait until live."""
    replicas = [_spawn_replica(app) for _ in range(app.deployment.num_replicas)]
    core_api.get([r.ping.remote() for r in replicas])  # surface init errors now
    return DeploymentHandle(app, replicas)
