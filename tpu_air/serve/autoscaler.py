"""Gauge-driven replica autoscaling for a serve deployment.

The :class:`Autoscaler` is a DRIVER-SIDE control loop (a daemon thread in
the proxy's process, NOT an actor — nothing here blocks a worker message
loop) that scales one deployment between ``min_replicas`` and
``max_replicas`` on signals from the live engine gauges:

* **queue pressure** — mean engine admission-queue depth per live replica
  at or above ``scale_up_queue_depth`` means arrivals outrun service:
  add a replica (a new actor + chip lease through the runtime's normal
  placement path — ``DeploymentHandle.scale_up``).
* **TTFT budget** — when ``ttft_budget_s`` is set and the interactive
  class's observed p99 TTFT exceeds it, scale up even if queues look
  shallow (latency is the SLO, queue depth only its proxy).
* **SLO burn rate** — when an airscope SLO monitor reports an objective
  burning on every evaluation window (observability/slo.py), scale up:
  the burn-rate signal fires on *error-budget spend velocity*, which
  catches a slow degradation a raw p99 threshold misses and stays quiet
  through brief spikes a p99 threshold would overreact to.
  ``slo_source`` is injectable like ``gauge_source``; by default the
  process-wide installed monitor (``observability.slo.install``) is
  consulted, so wiring a monitor up is enough.
* **anomaly detection** — when airwatch (observability/watch.py) is
  installed, a recent ``watch.anomaly`` on any fleet metric is a third
  scale-up signal of equal rank: the detector catches step changes
  (a replica death's throughput cliff, a queue-depth spike) one scrape
  after they happen, before a burn-rate window can confirm them.
  ``anomaly_source`` is injectable the same way; off ⇒ one global read.

Scale-DOWN is deliberately timid: only after ``scale_down_idle_ticks``
CONSECUTIVE ticks with empty queues and zero slot occupancy, and never
below ``min_replicas``.  A scale-down drains the victim replica first
(``DeploymentHandle.scale_down`` → drain → lease release), so in-flight
streams never notice.  ``cooldown_s`` separates consecutive scaling
actions in either direction — one decision gets to take effect before
the next is made.

``gauge_source`` is injectable (any callable returning
``DeploymentHandle.engine_stats``-shaped snapshots), which is how the
unit tests drive :meth:`tick` against synthetic gauges without replicas.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from time import monotonic
from typing import Any, Callable, Dict, Iterable, Optional, Tuple


def _installed_monitor_burning() -> Tuple[str, ...]:
    """Default ``slo_source``: sample + evaluate the process-wide airscope
    SLO monitor, empty when none is installed."""
    from tpu_air.observability import slo as _slo

    mon = _slo.monitor()
    if mon is None:
        return ()
    mon.observe()
    return tuple(mon.burning())


def _installed_watch_anomalies() -> Tuple[str, ...]:
    """Default ``anomaly_source``: metrics the installed airwatch detector
    flagged inside its hold window; empty when airwatch is off (the
    zero-cost-off path is one module-global read)."""
    from tpu_air.observability import watch as _watch

    if not _watch.enabled():
        return ()
    return tuple(_watch.anomalous())


@dataclass(frozen=True)
class AutoscalerConfig:
    """Dials for one deployment's autoscaler.

    * ``min_replicas`` / ``max_replicas`` — the scaling envelope.
    * ``scale_up_queue_depth`` — mean queued requests per live replica
      that triggers a scale-up.
    * ``ttft_budget_s`` — optional interactive p99 TTFT ceiling; observed
      p99 above it also triggers a scale-up.  None disables the signal.
    * ``scale_down_idle_ticks`` — consecutive idle ticks (queues empty,
      slots empty) before one replica is drained away.
    * ``tick_s`` — control-loop period.
    * ``cooldown_s`` — minimum spacing between scaling actions.
    """

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_queue_depth: float = 8.0
    ttft_budget_s: Optional[float] = None
    scale_down_idle_ticks: int = 10
    tick_s: float = 0.5
    cooldown_s: float = 5.0


class Autoscaler:
    """One deployment's scaling loop (see module doc)."""

    def __init__(self, handle, config: Optional[AutoscalerConfig] = None, *,
                 gauge_source: Optional[Callable[[], Dict[str, Any]]] = None,
                 slo_source: Optional[Callable[[], Iterable[str]]] = None,
                 anomaly_source: Optional[Callable[[],
                                                   Iterable[str]]] = None):
        self._handle = handle
        self.config = config or AutoscalerConfig()
        if self.config.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.config.max_replicas < self.config.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        self._gauge_source = gauge_source or handle.engine_stats
        # returns the names of SLOs currently burning (scale-up signal);
        # default reads whatever monitor the app installed process-wide
        self._slo_source = slo_source or _installed_monitor_burning
        # third scale signal: metrics the airwatch anomaly detector
        # flagged recently (observability/watch.py) — a detected step
        # change in fleet behavior ranks with queue depth and SLO burn
        self._anomaly_source = anomaly_source or _installed_watch_anomalies
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # decision state below is written by the tick thread and read by
        # stats() from arbitrary proxy threads — every access goes through
        # _lock (the blocking scale_up/scale_down calls stay outside it)
        self._lock = threading.Lock()
        self._idle_ticks = 0
        self._last_action_at = -1e18  # monotonic stamp of the last scale
        self.scale_ups = 0
        self.scale_downs = 0
        self.preemption_scale_ups = 0
        self.last_decision = "hold"
        self.last_burning: tuple = ()
        self.last_anomalies: tuple = ()

    # -- pure policy ----------------------------------------------------------
    def decide(self, snapshots: Dict[str, Dict[str, Any]],
               replicas: int, burning: Iterable[str] = (),
               anomalies: Iterable[str] = ()) -> str:
        """``"up"`` / ``"down"`` / ``"hold"`` for one tick's gauges.  Pure
        (no side effects, no cooldown) — the unit-testable core.

        ``burning`` names SLOs whose error budget is burning on every
        evaluation window (observability/slo.py); ``anomalies`` names
        metrics the airwatch detector flagged (observability/watch.py).
        Any entry in either is a scale-up signal of equal rank with queue
        depth and the p99 budget.

        The idle streak that gates scale-down is tracked by :meth:`tick`;
        this method only answers whether THIS tick looks idle (``"down"``
        here means "idle and above min", which tick() demotes to hold
        until the streak is long enough)."""
        cfg = self.config
        if replicas < cfg.min_replicas:
            return "up"
        depth = sum(int(s.get("queue_depth", 0)) for s in snapshots.values())
        occupancy = sum(int(s.get("slot_occupancy", 0))
                        for s in snapshots.values())
        if replicas < cfg.max_replicas:
            if depth / max(replicas, 1) >= cfg.scale_up_queue_depth:
                return "up"
            if any(True for _ in burning):
                return "up"
            if any(True for _ in anomalies):
                return "up"
            if cfg.ttft_budget_s is not None:
                p99 = self._interactive_p99(snapshots)
                if p99 is not None and p99 > cfg.ttft_budget_s:
                    return "up"
        if replicas > cfg.min_replicas and depth == 0 and occupancy == 0:
            return "down"
        return "hold"

    @staticmethod
    def _interactive_p99(snapshots: Dict[str, Dict[str, Any]]
                         ) -> Optional[float]:
        """Worst interactive-class p99 TTFT across replicas, None when no
        replica has interactive samples yet."""
        worst = None
        for s in snapshots.values():
            d = ((s.get("priority") or {}).get("interactive") or {}).get(
                "ttft_s") or {}
            if d.get("count"):
                p99 = float(d["p99"])
                worst = p99 if worst is None else max(worst, p99)
        return worst

    # -- the loop -------------------------------------------------------------
    def tick(self) -> str:
        """One control iteration: scrape, decide, maybe act.  Returns the
        ACTION taken (``"up"`` / ``"down"`` / ``"hold"``)."""
        cfg = self.config
        try:
            snapshots = self._gauge_source() or {}
        except Exception:  # noqa: BLE001 — a failed scrape must not kill the loop
            snapshots = {}
        replicas = self._handle.num_replicas()
        try:
            burning = tuple(self._slo_source() or ())
        except Exception:  # noqa: BLE001 — a broken SLO source must not kill the loop
            burning = ()
        try:
            anomalies = tuple(self._anomaly_source() or ())
        except Exception:  # noqa: BLE001 — a broken detector must not kill the loop
            anomalies = ()
        decision = self.decide(snapshots, replicas, burning, anomalies)
        # the idle streak: only an unbroken run of idle ticks earns a
        # scale-down; any non-idle tick resets it
        with self._lock:
            if decision == "down":
                self._idle_ticks += 1
                if self._idle_ticks < cfg.scale_down_idle_ticks:
                    decision = "hold"
            else:
                self._idle_ticks = 0
            self.last_decision = decision
            self.last_burning = burning
            self.last_anomalies = anomalies
            if decision == "hold":
                return "hold"
            if monotonic() - self._last_action_at < cfg.cooldown_s:
                return "hold"
        if decision == "up":
            if self._handle.scale_up():
                with self._lock:
                    self.scale_ups += 1
                    self._last_action_at = monotonic()
                return "up"
            return "hold"
        # down: drain + release; blocking here is fine (driver-side thread)
        if self._handle.scale_down():
            with self._lock:
                self.scale_downs += 1
                self._idle_ticks = 0
                self._last_action_at = monotonic()
            return "down"
        return "hold"

    def notice_scale_up(self) -> bool:
        """Preemption signal (serve/supervisor.py PreemptionWatcher): a
        lease-revocation notice means capacity is about to LEAVE, which is
        a stronger fact than any gauge — add a replica immediately,
        bypassing the cooldown (the cooldown paces reactions to noisy
        load signals, not to announced capacity loss) and the idle
        streak.  No-op at ``max_replicas``.  Returns whether a replica
        was added."""
        if self._handle.num_replicas() >= self.config.max_replicas:
            return False
        if self._handle.scale_up():
            with self._lock:
                self.scale_ups += 1
                self.preemption_scale_ups += 1
                self._idle_ticks = 0
                self._last_action_at = monotonic()
                self.last_decision = "up"
            return True
        return False

    def _loop(self) -> None:
        # Event.wait as the tick timer: stop() interrupts a sleeping loop
        # immediately instead of waiting out the period
        while not self._stop.wait(self.config.tick_s):
            self.tick()

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"serve-autoscaler-{self._handle.deployment_name}",
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- observability --------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        replicas = self._handle.num_replicas()  # foreign call: outside _lock
        with self._lock:
            return {
                "min_replicas": self.config.min_replicas,
                "max_replicas": self.config.max_replicas,
                "replicas": replicas,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "preemption_scale_ups": self.preemption_scale_ups,
                "idle_ticks": self._idle_ticks,
                "last_decision": self.last_decision,
                "burning_slos": list(self.last_burning),
                "anomalies": list(self.last_anomalies),
            }
