"""Live weights: a versioned store over the shm object store, plus the
health-gated canary controller that moves a serving fleet onto them.

Three layers, bottom-up:

* :class:`WeightStore` — versioned full/adapter checkpoints.  Tensor
  payloads live in an :class:`~tpu_air.core.object_store.ObjectStore`
  (atomic seal per object); a JSON manifest per version records each
  tensor's object id, shape, dtype and crc32.  The manifest is written
  LAST via tmp+rename, so a version EXISTS only once every shard it
  names is sealed — a publisher killed mid-publish leaves orphan shards
  and no manifest, never a half-version (the ``weights.publish`` chaos
  test pins this).  Reads re-checksum every tensor: a corrupt shard
  raises :class:`WeightsIntegrityError` instead of serving garbage.
  Version ids are monotone per store; retain-N GC deletes old full
  versions' objects and manifests.

* probe helpers — a publish can pin a greedy probe: a fixed prompt set,
  its expected tokens and a sha256 fingerprint (optionally last-position
  logits + a tolerance for quantized bases, where exact token match is
  too strict).  :func:`offline_greedy` is the reference decode loop the
  fingerprint is computed with — deliberately independent of the engine
  (plain per-token ``model.apply``), the same anchor the engine parity
  tests pin against.

* :class:`WeightsController` — the canary state machine over a
  :class:`~tpu_air.serve.deployment.DeploymentHandle`.  ``promote()``
  swaps ONE replica, runs the probe gate, holds a soak window in which
  SLO burn (observability/slo.py) must stay quiet, and only then swaps
  the rest of the fleet; any gate failure rolls the canary back to the
  prior version (an engine-held device tree — rollback never reads the
  store, so it survives a corrupt or GC'd publish) and surfaces the
  failure in ``/-/stats`` (``weights`` section) and
  ``tpu_air_weights_*`` metrics.  Adapter versions promote through the
  same gate as cheap sub-swaps (bank row writes, not full-tree swaps).

Concurrency: the store is single-writer by contract (the trainer);
readers only ever see sealed objects + renamed manifests.  Controller
state is guarded by one lock; all replica RPCs happen OUTSIDE it (a
slow replica must not wedge ``stats()``).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_air.core.object_store import ObjectStore
from tpu_air.faults import plan as _faults

__all__ = [
    "GateFailedError",
    "TornPublishError",
    "WeightStore",
    "WeightsController",
    "WeightsIntegrityError",
    "attach_weights",
    "compute_probe",
    "controller_stats",
    "install_controller",
    "offline_greedy",
    "probe_fingerprint",
]


class TornPublishError(Exception):
    """A publish died before its manifest landed.  The version does not
    exist: readers never see it, a retry re-publishes under the same
    number (sealed shards are overwritten via rename)."""


class WeightsIntegrityError(Exception):
    """A restore-path read failed validation: missing shard, shape/dtype
    drift, or a crc32 mismatch against the manifest."""


class GateFailedError(Exception):
    """The canary health gate rejected a version (probe mismatch, SLO
    burn during soak, or the swap RPC itself failing)."""


# ---------------------------------------------------------------------------
# param tree <-> flat tensor list
# ---------------------------------------------------------------------------

def _flatten(tree: Dict[str, Any], prefix: str = "") -> List[Tuple[str, Any]]:
    """Nested-dict params to sorted ``(path, leaf)`` pairs ("/"-joined
    paths — fine in manifests; object ids never contain them)."""
    out: List[Tuple[str, Any]] = []
    for k in sorted(tree):
        v = tree[k]
        path = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.extend(_flatten(v, path))
        else:
            out.append((path, v))
    return out


def _unflatten(pairs: List[Tuple[str, Any]]) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    for path, leaf in pairs:
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return root


# ---------------------------------------------------------------------------
# the versioned store
# ---------------------------------------------------------------------------

_MANIFEST_RE = re.compile(r"^manifest-(\d{6})\.json$")


class WeightStore:
    """Versioned weight checkpoints over the shm object store.

    ``root`` holds the manifests; tensor objects live in a private
    :class:`ObjectStore` at ``root/objects`` unless ``store`` hands in a
    shared one (object ids are ``w{version:06d}-{idx:04d}`` — no path
    separators, unique per store root).  Single writer (the trainer);
    any number of readers.
    """

    def __init__(self, root: str, store: Optional[ObjectStore] = None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._store = store or ObjectStore(
            os.path.join(root, "objects"), create=True)

    # -- version bookkeeping -------------------------------------------------
    def _manifest_path(self, version: int) -> str:
        return os.path.join(self.root, f"manifest-{version:06d}.json")

    def versions(self) -> List[int]:
        """Published (manifest-sealed) versions, ascending.  Unparsable
        manifest files are skipped, not fatal — one bad file must not
        take down every reader."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in names:
            m = _MANIFEST_RE.match(name)
            if not m:
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    json.load(f)
            except (OSError, ValueError):
                continue
            out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> Optional[int]:
        vs = self.versions()
        return vs[-1] if vs else None

    def manifest(self, version: int) -> Dict[str, Any]:
        try:
            with open(self._manifest_path(version)) as f:
                return json.load(f)
        except OSError as e:
            raise KeyError(f"no published version {version}") from e

    # -- publish -------------------------------------------------------------
    def _next_version(self) -> int:
        # scan-max + 1: monotone over PUBLISHED versions.  A torn publish
        # never sealed a manifest, so a retry reuses its number; the
        # publisher deletes each orphan shard id before re-putting it
        # (objects are immutable — a bare put over an existing id keeps
        # the OLD bytes and the manifest checksum would then lie).
        return (self.latest_version() or 0) + 1

    def publish(self, params: Dict[str, Any], *,
                metadata: Optional[Dict[str, Any]] = None,
                probe: Optional[Dict[str, Any]] = None) -> int:
        """Publish a full weight tree; returns the new version id.

        Order is the whole integrity story: every tensor object is put
        (and atomically sealed) FIRST, the manifest naming them is
        renamed into place LAST.  Fault hooks (site ``weights.publish``,
        keyed by tensor path, then ``manifest``): ``kill`` aborts before
        the manifest (torn publish — raises :class:`TornPublishError`),
        ``corrupt`` flips a tensor's VALUES before checksumming (loads
        cleanly, decodes wrong — the canary gate's quarry), ``delay``
        stalls in place."""
        flat = _flatten(params)
        version = self._next_version()
        tensors = []
        for i, (path, leaf) in enumerate(flat):
            arr = np.asarray(leaf)
            if _faults.enabled():
                spec = _faults.perturb("weights.publish", key=path)
                if spec is not None and spec.action == "kill":
                    raise TornPublishError(
                        f"airfault: publisher killed before shard {i} "
                        f"({path}) of version {version}; no manifest "
                        f"written")
                if spec is not None and spec.action == "corrupt":
                    # bad VALUES with a valid checksum: sign-flip + shift
                    # survives every dtype and changes greedy argmaxes
                    arr = (arr * -1 + 1).astype(arr.dtype)
            oid = f"w{version:06d}-{i:04d}"
            tensors.append({
                "path": path,
                "object_id": oid,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
            })
            # evict a torn predecessor's orphan shard first: objects are
            # immutable, so putting over a live id would keep its bytes
            self._store.delete(oid)
            self._store.put(arr, oid)  # aircrash: data weights-manifest
        manifest = {
            "version": version,
            "kind": "full",
            "tensors": tensors,
            "metadata": dict(metadata or {}),
            "probe": probe,
            "created_at": time.time(),
        }
        if _faults.enabled():
            _faults.perturb("weights.publish", key="manifest")
        self._write_manifest(version, manifest)
        return version

    def publish_adapter(self, name: str, a, b, *,
                        metadata: Optional[Dict[str, Any]] = None,
                        probe: Optional[Dict[str, Any]] = None) -> int:
        """Publish one tenant's LoRA head delta (``a``: [d, r], ``b``:
        [r, V]) as an adapter version — same manifest/checksum/atomicity
        discipline as :meth:`publish`, tiny payload."""
        return self._publish_kind(
            {"a": np.asarray(a, np.float32), "b": np.asarray(b, np.float32)},
            kind="adapter",
            metadata={**(metadata or {}), "adapter": str(name)},
            probe=probe)

    def _publish_kind(self, tree, *, kind, metadata, probe) -> int:
        flat = _flatten(tree)
        version = self._next_version()
        tensors = []
        for i, (path, leaf) in enumerate(flat):
            arr = np.asarray(leaf)
            if _faults.enabled():
                spec = _faults.perturb("weights.publish", key=path)
                if spec is not None and spec.action == "kill":
                    raise TornPublishError(
                        f"airfault: publisher killed mid-publish of "
                        f"{kind} version {version}")
                if spec is not None and spec.action == "corrupt":
                    arr = (arr * -1 + 1).astype(arr.dtype)
            oid = f"w{version:06d}-{i:04d}"
            tensors.append({
                "path": path,
                "object_id": oid,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "crc32": zlib.crc32(np.ascontiguousarray(arr).tobytes()),
            })
            self._store.delete(oid)  # same orphan-shard eviction as publish()
            self._store.put(arr, oid)  # aircrash: data weights-manifest
        manifest = {
            "version": version,
            "kind": kind,
            "tensors": tensors,
            "metadata": dict(metadata or {}),
            "probe": probe,
            "created_at": time.time(),
        }
        if _faults.enabled():
            _faults.perturb("weights.publish", key="manifest")
        self._write_manifest(version, manifest)
        return version

    def _write_manifest(self, version: int, manifest: Dict[str, Any]) -> None:
        path = self._manifest_path(version)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        # aircrash: commits weights-manifest
        os.rename(tmp, path)  # manifest-written-LAST: airlint CS003 proves
        # every shard put precedes this rename in all publish flows

    # -- restore -------------------------------------------------------------
    def load(self, version: Optional[int] = None) -> Dict[str, Any]:
        """Restore a version's tensors as a nested param dict, validating
        EVERY read against the manifest (shape, dtype, crc32) — the
        restore path never trusts ``get()`` to have returned the bytes
        the publisher wrote."""
        if version is None:
            version = self.latest_version()
            if version is None:
                raise KeyError(f"weight store {self.root} has no "
                               f"published versions")
        man = self.manifest(version)
        pairs = []
        for t in man["tensors"]:
            try:
                arr = np.asarray(self._store.get(t["object_id"], timeout=10.0))
            except TimeoutError as e:
                raise WeightsIntegrityError(
                    f"version {version}: shard {t['object_id']} "
                    f"({t['path']}) missing from the object store") from e
            if (list(arr.shape) != list(t["shape"])
                    or str(arr.dtype) != t["dtype"]):
                raise WeightsIntegrityError(
                    f"version {version}: shard {t['path']} is "
                    f"{arr.dtype}{list(arr.shape)}, manifest says "
                    f"{t['dtype']}{t['shape']}")
            crc = zlib.crc32(np.ascontiguousarray(arr).tobytes())
            if crc != int(t["crc32"]):
                raise WeightsIntegrityError(
                    f"version {version}: shard {t['path']} crc32 "
                    f"{crc:#x} != manifest {int(t['crc32']):#x}")
            pairs.append((t["path"], arr))
        return _unflatten(pairs)

    def load_adapter(self, version: int) -> Tuple[str, np.ndarray, np.ndarray]:
        """Restore an adapter version: ``(tenant_name, a, b)``."""
        man = self.manifest(version)
        if man.get("kind") != "adapter":
            raise ValueError(f"version {version} is kind "
                             f"{man.get('kind')!r}, not an adapter")
        tree = self.load(version)
        return str(man["metadata"]["adapter"]), tree["a"], tree["b"]

    # -- retention -----------------------------------------------------------
    def gc(self, keep: int = 2) -> List[int]:
        """Delete all but the newest ``keep`` FULL versions (objects and
        manifests; adapter versions are evicted explicitly via the
        controller, not by retention).  Returns the versions removed."""
        full = [v for v in self.versions()
                if self.manifest(v).get("kind") == "full"]
        doomed = full[:-keep] if keep > 0 else full
        for v in doomed:
            try:
                man = self.manifest(v)
            except KeyError:
                continue
            for t in man.get("tensors", ()):
                try:
                    self._store.delete(t["object_id"])
                except OSError:
                    pass
            try:
                os.remove(self._manifest_path(v))
            except OSError:
                pass
        return doomed


# ---------------------------------------------------------------------------
# greedy probes
# ---------------------------------------------------------------------------

def probe_fingerprint(token_lists: Sequence[Sequence[int]]) -> str:
    """Canonical sha256 over a probe's greedy outputs."""
    canon = json.dumps([[int(t) for t in seq] for seq in token_lists],
                       separators=(",", ":"))
    return hashlib.sha256(canon.encode()).hexdigest()


def offline_greedy(model, params, prompt: Sequence[int], max_new: int,
                   adapter_a=None, adapter_b=None) -> List[int]:
    """Reference greedy decode, one token per ``model.apply`` — the
    independent loop probe fingerprints are pinned with (and the adapter
    parity tests compare against).  Emits EOS inclusive then stops,
    matching the engine's stream contract.  ``adapter_a``/``adapter_b``
    apply a LoRA head delta ``logits += (h @ a) @ b``."""
    import jax.numpy as jnp

    from tpu_air.models.lm.config import LMConfig
    from tpu_air.models.lm.generate import init_cache
    from tpu_air.models.lm.modeling import CausalLM, head_weight

    prompt = [int(t) for t in prompt]
    cfg = model.config
    total = len(prompt) + max_new
    dmodel = CausalLM(LMConfig.from_dict(
        {**cfg.to_dict(), "max_seq_len": total}))
    cache = init_cache(dmodel, 1)
    lp = len(prompt)
    ids = jnp.asarray([prompt], jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(lp, dtype=jnp.int32), (1, lp))
    hidden, vars_ = dmodel.apply(
        {"params": params, "cache": cache}, ids, positions,
        decode=True, return_hidden=True, mutable=["cache"])
    head_w = head_weight(params, cfg).astype(jnp.float32)
    a = None if adapter_a is None else jnp.asarray(adapter_a, jnp.float32)
    b = None if adapter_b is None else jnp.asarray(adapter_b, jnp.float32)

    def pick(h):
        logits = h @ head_w
        if a is not None:
            logits = logits + (h @ a) @ b
        return int(jnp.argmax(logits))

    tok = pick(hidden[0, -1].astype(jnp.float32))
    out = [tok]
    eos = cfg.eos_token_id
    cache, pos = vars_["cache"], lp
    while len(out) < max_new and (eos is None or tok != eos):
        hidden, vars_ = dmodel.apply(
            {"params": params, "cache": cache},
            jnp.asarray([[tok]], jnp.int32),
            jnp.full((1, 1), pos, jnp.int32),
            decode=True, return_hidden=True, mutable=["cache"])
        cache, pos = vars_["cache"], pos + 1
        tok = pick(hidden[0, -1].astype(jnp.float32))
        out.append(tok)
    return out


def probe_logits(model, params, prompts: Sequence[Sequence[int]]
                 ) -> List[List[float]]:
    """Last-prompt-position logits per probe prompt (fp32 lists) — the
    tolerance-compare surface for quantized bases, where exact greedy
    token match across a requantize is too strict."""
    import jax.numpy as jnp

    from tpu_air.models.lm.config import LMConfig
    from tpu_air.models.lm.generate import init_cache
    from tpu_air.models.lm.modeling import CausalLM, head_weight

    cfg = model.config
    out = []
    for prompt in prompts:
        prompt = [int(t) for t in prompt]
        lp = len(prompt)
        dmodel = CausalLM(LMConfig.from_dict(
            {**cfg.to_dict(), "max_seq_len": lp}))
        cache = init_cache(dmodel, 1)
        ids = jnp.asarray([prompt], jnp.int32)
        positions = jnp.broadcast_to(
            jnp.arange(lp, dtype=jnp.int32), (1, lp))
        hidden, _ = dmodel.apply(
            {"params": params, "cache": cache}, ids, positions,
            decode=True, return_hidden=True, mutable=["cache"])
        head_w = head_weight(params, cfg).astype(jnp.float32)
        logits = hidden[0, -1].astype(jnp.float32) @ head_w
        out.append([float(x) for x in np.asarray(logits)])
    return out


def compute_probe(model, params, prompts: Sequence[Sequence[int]],
                  max_new: int = 8, *, adapter_a=None, adapter_b=None,
                  with_logits: bool = False,
                  logit_tolerance: Optional[float] = None
                  ) -> Dict[str, Any]:
    """Pin a probe for a publish: run the fixed prompt set greedily under
    the candidate weights and fingerprint the outputs.  The canary gate
    replays these prompts through the SERVING engine and requires the
    fingerprint to match exactly — or, with ``with_logits`` +
    ``logit_tolerance`` (quantized bases), the last-position logits to
    stay within tolerance."""
    toks = [offline_greedy(model, params, p, max_new,
                           adapter_a=adapter_a, adapter_b=adapter_b)
            for p in prompts]
    probe: Dict[str, Any] = {
        "prompts": [[int(t) for t in p] for p in prompts],
        "max_new": int(max_new),
        "tokens": [[int(t) for t in seq] for seq in toks],
        "fingerprint": probe_fingerprint(toks),
    }
    if with_logits:
        probe["logits"] = probe_logits(model, params, probe["prompts"])
        probe["logit_tolerance"] = (None if logit_tolerance is None
                                    else float(logit_tolerance))
    return probe


# ---------------------------------------------------------------------------
# the canary controller
# ---------------------------------------------------------------------------

class WeightsController:
    """Health-gated promotion of store versions onto a serving fleet.

    ``promote(version)`` drives the canary state machine::

        idle -> canary(swap replica 0) -> probe gate -> soak(SLO quiet)
             -> promote(rest of fleet) -> serving
                      \\-- any failure --> rollback(canary) -> idle

    Gate knobs: ``soak_s`` (how long SLO burn must stay quiet on the
    canary before fleet-wide promotion), ``soak_poll_s`` (burn poll
    cadence), ``probe_timeout_s`` (per-probe engine budget).  The probe
    itself rides in the version's manifest (``WeightStore.publish(...,
    probe=compute_probe(...))``); versions published without one pass a
    liveness-only gate (the probe prompts must merely decode) when
    ``probe_prompts`` is set, else skip straight to soak.
    """

    def __init__(self, handle, store_root: str, *,
                 probe_prompts: Optional[Sequence[Sequence[int]]] = None,
                 probe_max_new: int = 8,
                 soak_s: float = 0.5,
                 soak_poll_s: float = 0.05,
                 probe_timeout_s: float = 60.0):
        self._handle = handle
        self.store = WeightStore(store_root)
        self._probe_prompts = ([[int(t) for t in p] for p in probe_prompts]
                               if probe_prompts else None)
        self._probe_max_new = int(probe_max_new)
        self.soak_s = float(soak_s)
        self.soak_poll_s = float(soak_poll_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._lock = threading.Lock()
        self._state = "idle"
        self._current_version: Optional[int] = None
        self._promotions = 0
        self._rollbacks = 0
        self._gate_failures: Dict[str, int] = {}
        self._last_error: Optional[str] = None
        self._last_stall_ms = 0.0

    # -- replica RPC plumbing ------------------------------------------------
    def _replicas(self) -> list:
        with self._handle._lock:
            return list(self._handle._replicas)

    @staticmethod
    def _call(replica, method: str, *args, **kwargs):
        from tpu_air.core import api as core_api

        return core_api.get(
            replica.handle.remote(method, tuple(args), dict(kwargs)))

    def _set_state(self, state: str) -> None:
        with self._lock:
            self._state = state

    def _record_gate_failure(self, reason: str, err: str) -> None:
        with self._lock:
            self._gate_failures[reason] = (
                self._gate_failures.get(reason, 0) + 1)
            self._last_error = err
            self._state = "idle"

    # -- the gate ------------------------------------------------------------
    def _probe_gate(self, replica, man: Dict[str, Any],
                    adapter_id: Optional[str] = None) -> None:
        probe = (man or {}).get("probe")
        prompts = ((probe or {}).get("prompts") or self._probe_prompts)
        if not prompts:
            return  # nothing pinned and no liveness prompts configured
        max_new = int((probe or {}).get("max_new", self._probe_max_new))
        toks = self._call(replica, "weights_probe", prompts, max_new,
                          adapter_id=adapter_id,
                          timeout_s=self.probe_timeout_s)
        if probe is None:
            return  # liveness-only: the prompts decoded without error
        tol = probe.get("logit_tolerance")
        if probe.get("logits") is not None and tol is not None:
            got = self._call(replica, "weights_probe_logits", prompts)
            worst = 0.0
            for g, want in zip(got, probe["logits"]):
                worst = max(worst, max(
                    abs(float(x) - float(y)) for x, y in zip(g, want)))
            if worst > float(tol):
                raise GateFailedError(
                    f"probe logits drifted {worst:.3e} > tolerance {tol}")
            return
        got_fp = probe_fingerprint(toks)
        if got_fp != probe["fingerprint"]:
            raise GateFailedError(
                f"probe fingerprint mismatch: canary {got_fp[:12]} != "
                f"pinned {probe['fingerprint'][:12]}")

    def _soak_gate(self) -> None:
        """SLO burn must stay quiet for the whole soak window.  No
        monitor installed -> time-only soak (the window still gives the
        burn monitor a chance to be installed/fed by the harness)."""
        from tpu_air.observability import slo as _slo

        deadline = time.monotonic() + self.soak_s
        while time.monotonic() < deadline:
            mon = _slo.monitor()
            if mon is not None:
                burning = mon.burning()
                if burning:
                    raise GateFailedError(
                        f"SLO burning during soak: {burning}")
            time.sleep(self.soak_poll_s)

    # -- promotion -----------------------------------------------------------
    def promote(self, version: Optional[int] = None) -> Dict[str, Any]:
        """Canary-promote a store version across the fleet.  Returns a
        result dict (``promoted`` bool, ``version``, ``reason`` on
        failure); raises only on misuse (no replicas, no versions)."""
        if version is None:
            version = self.store.latest_version()
            if version is None:
                raise KeyError(
                    f"weight store {self.store.root} has no versions")
        man = self.store.manifest(version)
        replicas = self._replicas()
        if not replicas:
            raise RuntimeError("no live replicas to promote onto")
        if man.get("kind") == "adapter":
            return self._promote_adapter(version, man, replicas)
        return self._promote_full(version, man, replicas)

    def _promote_full(self, version: int, man: Dict[str, Any],
                      replicas: list) -> Dict[str, Any]:
        canary, rest = replicas[0], replicas[1:]
        self._set_state("canary")
        try:
            stall = self._call(canary, "weights_swap", self.store.root,
                               version)
            self._set_state("soaking")
            self._probe_gate(canary, man)
            self._soak_gate()
        except Exception as e:  # noqa: BLE001 — every gate failure rolls back
            reason = ("probe" if isinstance(e, GateFailedError)
                      else "swap_failed")
            try:
                self._call(canary, "weights_rollback")
            except Exception:  # noqa: BLE001 — replica may be gone; its
                pass           # restart recipe rebuilds from original params
            with self._lock:
                self._rollbacks += 1
            self._record_gate_failure(reason, f"v{version}: {e}")
            return {"promoted": False, "version": version,
                    "reason": str(e)}
        self._set_state("promoting")
        stalls = [stall]
        for replica in rest:
            try:
                stalls.append(self._call(replica, "weights_swap",
                                         self.store.root, version))
            except Exception as e:  # noqa: BLE001 — a dead replica's restart
                # recipe rebuilds it; surface, don't fail the promotion
                with self._lock:
                    self._last_error = (f"fleet swap on "
                                        f"{replica._actor_id}: {e}")
        with self._lock:
            self._state = "serving"
            self._current_version = version
            self._promotions += 1
            self._last_stall_ms = max(float(s) for s in stalls)
        return {"promoted": True, "version": version,
                "max_stall_ms": max(float(s) for s in stalls)}

    def _promote_adapter(self, version: int, man: Dict[str, Any],
                         replicas: list) -> Dict[str, Any]:
        """Adapter sub-swap under the same gate: load on the canary,
        probe UNDER the adapter, soak, then load fleet-wide.  Rollback
        is an unload — the shared base was never touched."""
        name, a, b = self.store.load_adapter(version)
        canary, rest = replicas[0], replicas[1:]
        self._set_state("canary")
        try:
            self._call(canary, "weights_load_adapter", name,
                       np.asarray(a), np.asarray(b))
            self._set_state("soaking")
            self._probe_gate(canary, man, adapter_id=name)
            self._soak_gate()
        except Exception as e:  # noqa: BLE001 — same rollback contract
            try:
                self._call(canary, "weights_unload_adapter", name)
            except Exception:  # noqa: BLE001 — best-effort unload
                pass
            with self._lock:
                self._rollbacks += 1
            self._record_gate_failure("adapter", f"adapter v{version}: {e}")
            return {"promoted": False, "version": version,
                    "adapter": name, "reason": str(e)}
        self._set_state("promoting")
        for replica in rest:
            try:
                self._call(replica, "weights_load_adapter", name,
                           np.asarray(a), np.asarray(b))
            except Exception as e:  # noqa: BLE001 — surface, don't fail
                with self._lock:
                    self._last_error = (f"adapter load on "
                                        f"{replica._actor_id}: {e}")
        with self._lock:
            self._state = "serving"
            self._promotions += 1
        return {"promoted": True, "version": version, "adapter": name}

    def evict_adapter(self, name: str) -> int:
        """Unload a tenant adapter fleet-wide; returns replicas evicted."""
        n = 0
        for replica in self._replicas():
            try:
                if self._call(replica, "weights_unload_adapter", name):
                    n += 1
            except Exception:  # noqa: BLE001 — replica may be mid-restart
                continue
        return n

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "current_version": self._current_version,
                "latest_published": self.store.latest_version(),
                "promotions": self._promotions,
                "rollbacks": self._rollbacks,
                "gate_failures": dict(self._gate_failures),
                "last_error": self._last_error,
                "last_stall_ms": self._last_stall_ms,
            }


# ---------------------------------------------------------------------------
# registry (the /-/stats "weights" section)
# ---------------------------------------------------------------------------

_controllers: Dict[str, WeightsController] = {}
_controllers_lock = threading.Lock()


def install_controller(route_prefix: str,
                       ctl: WeightsController) -> WeightsController:
    with _controllers_lock:
        _controllers[route_prefix] = ctl
    return ctl


def uninstall_controller(route_prefix: str) -> None:
    with _controllers_lock:
        _controllers.pop(route_prefix, None)


def controller_stats() -> Dict[str, Any]:
    with _controllers_lock:
        ctls = dict(_controllers)
    return {prefix: ctl.stats() for prefix, ctl in ctls.items()}


def attach_weights(route_prefix: str, store_root: str,
                   **gate_kw: Any) -> WeightsController:
    """Bind a :class:`WeightsController` to a deployed route: looks the
    route's handle up in the running proxy and registers the controller
    so its state shows under ``/-/stats`` -> ``weights``."""
    from tpu_air.serve import proxy as _proxy

    with _proxy._state.lock:
        handle = _proxy._state.routes.get(route_prefix)
    if handle is None:
        raise KeyError(f"no deployment at route {route_prefix!r}")
    return install_controller(
        route_prefix, WeightsController(handle, store_root, **gate_kw))
