"""HTTP body adapters: raw request bytes → the replica's input type.

The reference uses ``ray.serve.http_adapters.pandas_read_json``
(Introduction_to_Ray_AI_Runtime.ipynb:cc-70-71) so clients can POST a list of
row dicts and the Predictor receives a DataFrame.
"""

from __future__ import annotations

import json
from typing import Any


def json_request(body: bytes) -> Any:
    """Parse the request body as JSON, passed through unchanged."""
    return json.loads(body) if body else None


def pandas_read_json(body: bytes):
    """JSON list-of-rows (or dict-of-columns) → pandas DataFrame."""
    import io

    import pandas as pd

    obj = json.loads(body)
    if isinstance(obj, dict):
        # single record or column-oriented dict
        if all(not isinstance(v, (list, dict)) for v in obj.values()):
            return pd.DataFrame([obj])
        return pd.DataFrame(obj)
    return pd.read_json(io.StringIO(json.dumps(obj)), orient="records")
