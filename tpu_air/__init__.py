"""tpu_air — a TPU-native distributed ML framework.

Provides the capability surface of the `ray-project/anyscale-workshop-nyc-2023`
reference stack (Ray Core / Data / Train / Tune / AIR predictors / Serve — see
SURVEY.md), re-designed TPU-first: JAX/XLA SPMD over device meshes for compute,
XLA collectives over ICI/DCN instead of NCCL, chip/sub-mesh leases instead of
GPU scheduling, and a shared-memory host object store for the data plane.

Top-level API mirrors the names the reference workloads call::

    import tpu_air

    tpu_air.init()
    ref = tpu_air.put(big_array)

    @tpu_air.remote
    def f(x): ...
    results = tpu_air.get([f.remote(ref) for _ in range(8)])
    tpu_air.shutdown()

Subsystem layers live in submodules, imported lazily to keep worker startup
light: ``tpu_air.data``, ``tpu_air.train``, ``tpu_air.tune``,
``tpu_air.predict``, ``tpu_air.serve``, ``tpu_air.engine``,
``tpu_air.parallel``, ``tpu_air.models``.
"""

from tpu_air._version import __version__
from tpu_air.core import (
    ActorDiedError,
    ActorHandle,
    ActorPool,
    ObjectRef,
    RemoteError,
    TpuAirError,
    get,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)

_LAZY_SUBMODULES = (
    "data",
    "train",
    "tune",
    "predict",
    "serve",
    "engine",
    "parallel",
    "models",
    "ops",
    "job",
    "observability",
    "utils",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        module = importlib.import_module(f"tpu_air.{name}")
        globals()[name] = module
        return module
    raise AttributeError(f"module 'tpu_air' has no attribute '{name}'")


__all__ = [
    "ActorDiedError",
    "ActorHandle",
    "ActorPool",
    "ObjectRef",
    "RemoteError",
    "TpuAirError",
    "__version__",
    "get",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
    *_LAZY_SUBMODULES,
]
