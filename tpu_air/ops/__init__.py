"""tpu_air.ops — Pallas TPU kernels + distributed attention primitives.

The custom-kernel layer of the stack (SURVEY.md §2B: ATen/CUDA kernels →
"XLA:TPU kernels via jit; Pallas for anything custom").  Long-context
support (ring attention over a sequence mesh axis) lives here too.
"""

from .flash_attention import flash_attention, flash_attention_with_lse
from .ring_attention import ring_attention, ring_attention_sharded

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "ring_attention",
    "ring_attention_sharded",
]
