"""Single-token decode attention over FLAT K/V cache slabs.

The decode hot loop (reference hot path: predictor.py:102 — W3 batch
generation) is HBM-bandwidth-bound: every emitted token re-reads the whole
K/V cache.  Round 5 profiled the XLA einsum decode at 290 GB/s of the
v5e's 819 GB/s roofline and found the chip was NOT slow — the 4-D
``[b, L, h, d]`` slab layout was: TPU tiles the last two dims (12, 64) up
to (16, 128), a 2.67x physical-byte inflation, and XLA streamed those
padded bytes at ~92% of the roofline.  The fix is layout + formulation,
not a bespoke kernel:

* ``flat_decode_attention`` — the DEFAULT path (pure XLA): caches stored
  flat ``[b, L, h*d]`` (768 = six clean (8, 128) tiles, zero padding),
  all heads riding ONE batched MXU matmul per contraction via
  block-diagonal expansion.  Measured 732 GB/s = 89% of roofline in
  isolation; end-to-end it cut the W3 decode step ~2x (bf16) / ~3.2x
  (int8) vs the padded einsum.
* ``decode_attention`` — the same computation as a fused Pallas kernel
  (online softmax over L-chunks, int8 dequant folded into operands so
  int8 slabs stay int8 into VMEM).  Measured SLOWER than the flat XLA
  path (229 GB/s isolated; per-program overhead at b=256 x 1-chunk
  grids dominates) — kept as the measured alternative and as the
  scaffold for shapes XLA fuses badly, selectable via
  ``T5Config.decode_attention_impl="pallas"``.

Quantization contract (both paths): int8 slabs carry scales that FOLD
into the math — per-channel (cross-attn, ``[b, 1, h*d]``) into q before
the score matmul / into the context after; per-position (self-attn,
``[b, L, h]``) into the scores / probabilities.  No dequantized slab is
ever materialized; the HBM traffic for an int8 cache IS the int8 bytes.

Masking contract: ``bias`` is an additive f32 ``[h, L]`` that already
includes any causal/validity masking (the T5 decode path's relative-
position bias + causal row collapse to exactly this); ``kv_mask`` is the
per-batch key-padding mask.  A fully-masked ROW (no valid key at all)
does NOT yield a zero context vector: every score sits at the same mask
floor, the softmax degenerates to UNIFORM, and the output is the plain
mean of V over all (masked) positions — finite, NaN-free, but carrying
no information.  Decode rows always have >=1 valid key (self: position
0; cross: a non-empty prompt), so this is a don't-care guarded against
NaN; callers that could produce an all-masked row must treat its output
as undefined rather than zero (ADVICE r5).

f32 score/softmax math, MXU-dtype (bf16 on chip) operands — the same
precision budget as the dense path it replaces.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_MASK_FLOOR = -1e20
_NEG_INF_DENSE = -1e9


def _kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, ks_ref, vs_ref,
            out_ref, m_ref, l_ref, acc_ref, *, h, d, k_kind, v_kind,
            compute_dtype):
    ci = pl.program_id(1)
    nc = pl.num_programs(1)
    hd = h * d

    @pl.when(ci == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _MASK_FLOOR)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    qv = q_ref[0].astype(jnp.float32)            # [1, hd]
    if k_kind == "chan":
        qv = qv * ks_ref[0]                      # fold per-channel K scale
    # Qexp[r, c] = qv[r] iff head_of(r) == c: one [C,hd]x[hd,h] MXU matmul
    # computes every head's q.k row instead of h tiny matvecs.
    rows = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
    head_sel = rows // d == cols                 # [hd, h] block diagonal
    # transposed selector built from its own iotas: Mosaic cannot
    # transpose an i1 vector (failed-to-legalize tpu.transpose)
    sel_t = (jax.lax.broadcasted_iota(jnp.int32, (h, hd), 1) // d
             == jax.lax.broadcasted_iota(jnp.int32, (h, hd), 0))
    qexp = jnp.where(head_sel, qv.reshape(hd, 1), 0.0).astype(compute_dtype)

    k = k_ref[0].astype(compute_dtype)           # [C, hd]
    s = jax.lax.dot_general(                     # [C, h] f32
        k, qexp, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if k_kind == "pos":
        s = s * ks_ref[0]                        # [C, h] per-position scale
    if bias_ref is not None:
        s = s + bias_ref[...]                    # [C, h] additive (f32)
    if mask_ref is not None:
        s = s + mask_ref[0]                      # [C, 1] additive (f32)

    m_prev = m_ref[...]                          # [1, h]
    m_new = jnp.maximum(jnp.max(s, axis=0, keepdims=True), m_prev)
    m_new = jnp.maximum(m_new, _MASK_FLOOR)      # fully-masked chunk guard
    alpha = jnp.exp(m_prev - m_new)              # [1, h]
    p = jnp.exp(s - m_new)                       # [C, h]
    l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
    m_ref[...] = m_new

    if v_kind == "pos":
        p = p * vs_ref[0]                        # fold per-position V scale
    v = v_ref[0].astype(compute_dtype)           # [C, hd]
    ctx_h = jax.lax.dot_general(                 # [h, hd] f32
        p.astype(compute_dtype), v, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # the block diagonal of ctx_h is the per-head context; sel_t masks it
    # out and the h-row reduce flattens to [1, hd]
    contrib = jnp.sum(jnp.where(sel_t, ctx_h, 0.0), axis=0,
                      keepdims=True)
    # alpha/l are per-head; expand to per-column through the same selector
    alpha_exp = jnp.sum(jnp.where(sel_t, alpha.reshape(h, 1), 0.0),
                        axis=0, keepdims=True)   # [1, hd]
    acc_ref[...] = acc_ref[...] * alpha_exp + contrib

    @pl.when(ci == nc - 1)
    def _finish():
        l_exp = jnp.sum(
            jnp.where(sel_t, l_ref[...].reshape(h, 1), 0.0),
            axis=0, keepdims=True,
        )
        out = acc_ref[...] / jnp.maximum(l_exp, 1e-20)
        if v_kind == "chan":
            out = out * vs_ref[0]                # fold per-channel V scale
        out_ref[0] = out.astype(out_ref.dtype)


def _pick_block(L: int) -> int:
    if L <= 512:
        return L
    for c in (512, 256, 128):
        if L % c == 0:
            return c
    if L <= 2048:
        return L
    raise ValueError(f"decode_attention: unsupported cache length {L}")


def decode_attention(
    q: jax.Array,                   # [b, 1, h, d] (or [b, h, d])
    k: jax.Array,                   # [b, L, h, d] or flat [b, L, h*d]
    v: jax.Array,                   # same; bf16/f32 or int8
    *,
    bias: Optional[jax.Array] = None,     # [h, L] or [1, h, 1, L] additive
    kv_mask: Optional[jax.Array] = None,  # [b, L] 1=attend
    k_scale: Optional[jax.Array] = None,  # [b, L, h, 1] or [b, 1, h, d] f32
    v_scale: Optional[jax.Array] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-query-token attention over a cached K/V slab.  Returns the
    context in q's layout ``[b, 1, h, d]`` (model dtype).  See module
    docstring for the masking and quantization contracts."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    squeeze = q.ndim == 4
    if squeeze:
        if q.shape[1] != 1:
            raise ValueError(f"decode_attention wants qlen==1, got {q.shape}")
        q = q[:, 0]
    b, h, d = q.shape
    L = k.shape[1]
    hd = h * d
    C = block_k or _pick_block(L)
    if L % C != 0:
        raise ValueError(f"block_k {C} must divide cache length {L}")
    out_dtype = q.dtype if q.dtype != jnp.int8 else jnp.float32
    compute_dtype = q.dtype

    def _scale_kind(s, name):
        if s is None:
            return None
        if s.shape in ((b, L, h, 1), (b, L, h)):
            return "pos"
        if s.shape in ((b, 1, h, d), (b, 1, hd)):
            return "chan"
        raise ValueError(f"{name} shape {s.shape} is neither per-position "
                         f"[b,L,h,1] nor per-channel [b,1,h,d] (or their "
                         f"flat forms)")

    k_kind = _scale_kind(k_scale, "k_scale")
    v_kind = _scale_kind(v_scale, "v_scale")

    grid = (b, L // C)
    # the Mosaic block rule constrains the last TWO dims of every block:
    # per-batch vectors ride as [b, 1, hd] so their (1, hd) tail equals
    # the array dims exactly
    qf = q.reshape(b, 1, hd)
    kf = k.reshape(b, L, hd)
    vf = v.reshape(b, L, hd)

    in_specs = [
        pl.BlockSpec((1, 1, hd), lambda bi, ci: (bi, 0, 0)),
        pl.BlockSpec((1, C, hd), lambda bi, ci: (bi, ci, 0)),
        pl.BlockSpec((1, C, hd), lambda bi, ci: (bi, ci, 0)),
    ]
    args = [qf, kf, vf]

    if bias is not None:
        if bias.ndim == 4:                       # [1, h, 1, L]
            bias = bias[0, :, 0, :]
        bias_t = bias.astype(jnp.float32).T      # [L, h]
        in_specs.append(pl.BlockSpec((C, h), lambda bi, ci: (ci, 0)))
        args.append(bias_t)
    else:
        in_specs.append(None)
        args.append(None)

    if kv_mask is not None:
        madd = jnp.where(kv_mask.astype(jnp.float32) > 0, 0.0, _MASK_FLOOR)
        in_specs.append(pl.BlockSpec((1, C, 1), lambda bi, ci: (bi, ci, 0)))
        args.append(madd.reshape(b, L, 1))
    else:
        in_specs.append(None)
        args.append(None)

    for s, kind in ((k_scale, k_kind), (v_scale, v_kind)):
        if kind == "pos":
            in_specs.append(pl.BlockSpec((1, C, h), lambda bi, ci: (bi, ci, 0)))
            args.append(s.astype(jnp.float32).reshape(b, L, h))
        elif kind == "chan":
            in_specs.append(pl.BlockSpec((1, 1, hd), lambda bi, ci: (bi, 0, 0)))
            args.append(s.astype(jnp.float32).reshape(b, 1, hd))
        else:
            in_specs.append(None)
            args.append(None)

    live_specs = [sp for sp in in_specs if sp is not None]
    live_args = [a for a in args if a is not None]

    def wrapped(*refs):
        it = iter(refs[: len(live_specs)])
        full = [next(it) if sp is not None else None for sp in in_specs]
        out_ref = refs[len(live_specs)]
        scratch = refs[len(live_specs) + 1:]
        _kernel(*full, out_ref, *scratch, h=h, d=d, k_kind=k_kind,
                v_kind=v_kind, compute_dtype=compute_dtype)

    out = pl.pallas_call(
        wrapped,
        grid=grid,
        in_specs=live_specs,
        out_specs=pl.BlockSpec((1, 1, hd), lambda bi, ci: (bi, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1, hd), out_dtype),
        scratch_shapes=[
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, h), jnp.float32),
            pltpu.VMEM((1, hd), jnp.float32),
        ],
        interpret=interpret,
    )(*live_args)
    out = out.reshape(b, h, d)
    return out[:, None] if squeeze else out


def decode_attention_reference(q, k, v, *, bias=None, kv_mask=None,
                               k_scale=None, v_scale=None):
    """jnp reference with identical semantics (tests; non-TPU fallbacks)."""
    squeeze = q.ndim == 4
    if squeeze:
        q = q[:, 0]
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if k_scale is not None:
        kf = kf * k_scale.astype(jnp.float32)
    if v_scale is not None:
        vf = vf * v_scale.astype(jnp.float32)
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32), kf)
    if bias is not None:
        if bias.ndim == 4:
            bias = bias[0, :, 0, :]
        s = s + bias.astype(jnp.float32)[None]
    if kv_mask is not None:
        s = s + jnp.where(kv_mask > 0, 0.0, _MASK_FLOOR)[:, None, :]
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,blhd->bhd", p, vf).astype(
        q.dtype if q.dtype != jnp.int8 else jnp.float32)
    return out[:, None] if squeeze else out


def gather_pages(pool: jax.Array, block_table: jax.Array) -> jax.Array:
    """Assemble per-slot flat K/V slabs from a paged pool.

    ``pool`` ``[P, page_len, h*d]`` — the engine's physical KV pages (page 0
    is the pinned null page); ``block_table`` ``[S, pages_per_slot]`` int32 —
    each slot's logical pages in position order.  Returns
    ``[S, pages_per_slot * page_len, h*d]``: position ``p`` of slot ``s``
    lives at ``(block_table[s, p // page_len], p % page_len)``, so the
    gathered result is exactly the flat slab :func:`flat_decode_attention`
    consumes — the paged pool changes WHERE pages live, not the layout
    attention streams.  Pages keep the ``[*, page_len, h*d]`` last-two-dims
    contract from the r5 roofline study: with ``page_len`` a multiple of 8
    and h*d a multiple of 128 every page is whole (8, 128) f32 tiles, so
    paging adds zero tile padding over the slab layout it replaces.
    Entries pointing at the null page gather don't-care bytes that the
    caller's validity mask (``position <= cache_index``) hides."""
    s, npg = block_table.shape
    _, page_len, hd = pool.shape
    return pool[block_table].reshape(s, npg * page_len, hd)


def flat_decode_attention(q, kf, vf, bias_hl, kv_mask, k_scale, v_scale,
                           num_heads, dtype):
    """Single-token attention over FLAT cache slabs ``[b, L, h*d]`` —
    the r5 decode fix.  All heads ride ONE batched MXU matmul per
    contraction via block-diagonal expansion (selector ``E``), so the
    slab streams from HBM exactly once in its unpadded storage layout:
    measured 732 GB/s (89% of v5e roofline) vs 283 GB/s logical for the
    padded 4-D einsum it replaces.  int8 scales fold into the math
    (cross per-channel -> q / context; self per-position -> scores /
    probs) — the dequantized slab is never materialized.

    q [b, 1, h, d]; bias_hl additive f32 [h, L] (carries causal masking);
    kv_mask [b, L]; k_scale/v_scale None or [b, 1, h*d] (per-channel) or
    [b, L, h] (per-position).  Returns [b, 1, h, d] in model dtype."""
    b, L, hd = kf.shape
    h, d = num_heads, hd // num_heads
    qv = q.reshape(b, hd).astype(jnp.float32)
    k_chan = k_scale is not None and k_scale.shape[1] == 1
    v_chan = v_scale is not None and v_scale.shape[1] == 1
    if k_chan:
        qv = qv * k_scale[:, 0, :]
    sel = jnp.arange(hd)[:, None] // d == jnp.arange(h)[None, :]  # [hd, h]
    qexp = jnp.where(sel[None], qv[:, :, None], 0.0).astype(dtype)
    s = jnp.einsum("blf,bfh->blh", kf.astype(dtype), qexp,
                   preferred_element_type=jnp.float32)
    if k_scale is not None and not k_chan:
        s = s * k_scale
    if bias_hl is not None:
        s = s + bias_hl.T[None]
    if kv_mask is not None:
        s = s + jnp.where(kv_mask > 0, 0.0, _NEG_INF_DENSE)[:, :, None]
    p = jax.nn.softmax(s, axis=1)
    if v_scale is not None and not v_chan:
        p = p * v_scale
    ctx2 = jnp.einsum("blh,blf->bhf", p.astype(dtype), vf.astype(dtype),
                      preferred_element_type=jnp.float32)
    ctx = jnp.sum(jnp.where(sel.T[None], ctx2, 0.0), axis=1)  # [b, hd]
    if v_chan:
        ctx = ctx * v_scale[:, 0, :]
    return ctx.reshape(b, 1, h, d).astype(dtype)
