"""Flash attention — Pallas TPU kernel with blockwise online softmax.

The hot op of the model layer (SURVEY.md §2B ATen row → "Pallas for anything
custom").  Blockwise streaming over K/V keeps the (Lq, Lk) score matrix out
of HBM: VMEM holds one (BQ, BK) tile at a time and the MXU sees back-to-back
(BQ,D)x(D,BK) and (BQ,BK)x(BK,D) matmuls; running max/sum statistics ride in
VMEM scratch across the sequentially-iterated k grid dimension (TPU grid
order is row-major, so the innermost k axis revisits the same q tile's
scratch).

Broadcast-aware operands — the reason a stock kernel doesn't fit T5:
* ``bias``: additive scores of shape (1|H|B·H, Lq, Lk).  T5's relative-
  position bias is per-head but batch-shared (H, Lq, Lk); the BlockSpec
  index map replays the same head tile for every batch element instead of
  materializing a (B·H, Lq, Lk) array in HBM.
* ``kv_mask``: per-batch key-padding mask (B, Lk), 1 = attend.  Expanded to
  a (1, BK) additive tile inside VMEM, never an (Lq, Lk) matrix.
* ``causal``: masking from block-local iota, zero HBM.

f32 accumulation regardless of input dtype.  Backward is an XLA recompute of
the reference attention (correct VJP for q/k/v/bias; the forward's HBM
savings are where long-context wins live).  Both the attention output and
the logsumexp are differentiable, so ring attention (ring_attention.py) can
train through the merged stats.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


# --------------------------------------------------------------------------
# kernel
# --------------------------------------------------------------------------


def _kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, out_ref, lse_ref,
            acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32)  # (BQ, D)
    k = k_ref[0].astype(jnp.float32)  # (BK, D)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # (BQ, BK)
    if bias_ref is not None:
        s = s + bias_ref[0].astype(jnp.float32)
    if mask_ref is not None:
        # (1, BK) additive key-padding row, broadcast over queries
        s = s + mask_ref[0].astype(jnp.float32)
    if causal:
        i = pl.program_id(1)
        qi = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kj = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(qi >= kj, s, _NEG_INF)

    m_prev = m_ref[:, :1]  # (BQ, 1)
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)  # (BQ, BK)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        # NB: masking uses finite -1e30, so a fully-masked row has p=exp(0)=1
        # per entry and l == klen, never 0 — such rows yield mean(V), matching
        # the dense softmax reference path.  The guard below only protects
        # against division by zero for degenerate zero-length tiles.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = (acc_ref[:] / safe_l).astype(out_ref.dtype)
        # lse block is (1, BQ, 1) — column layout keeps the sublane dim a
        # multiple of 8 as the TPU lowering requires
        lse_ref[0] = m_ref[:, :1] + jnp.log(safe_l)


def _kernel_nb(q, k, v, m, o, lse, acc, mr, lr, **kw):
    _kernel(q, k, v, None, m, o, lse, acc, mr, lr, **kw)


def _kernel_nm(q, k, v, b, o, lse, acc, mr, lr, **kw):
    _kernel(q, k, v, b, None, o, lse, acc, mr, lr, **kw)


def _kernel_nbm(q, k, v, o, lse, acc, mr, lr, **kw):
    _kernel(q, k, v, None, None, o, lse, acc, mr, lr, **kw)


def _bias_index_map(bias_b: int, bh: int):
    if bias_b == bh:
        return lambda b, i, j: (b, i, j)
    if bias_b == 1:
        return lambda b, i, j: (0, i, j)
    if bh % bias_b == 0:
        # per-head, batch-shared: grid b = batch*H + head, bias_b == H
        return lambda b, i, j: (b % bias_b, i, j)
    raise ValueError(f"bias leading dim {bias_b} incompatible with batch·heads {bh}")


_BLOCK_CANDIDATES = (1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
# Measured on TPU v5e (BH=48, D=64, bf16, slope-timed): (128, 128) runs at
# 6-8 TF/s while (512, 1024) reaches 48-80 TF/s — 3-5x FASTER than XLA's
# dense path at L >= 2048 and ~parity at L = 512.  Bigger k tiles amortize
# the per-block online-softmax rescale; bigger q tiles amortize k/v streams.
_AUTO_BLOCK_Q_CAP = 512
_AUTO_BLOCK_K_CAP = 1024


def _auto_block(length: int, cap: int) -> int:
    """Largest power-of-two-ish tile <= cap that divides ``length``."""
    for s in _BLOCK_CANDIDATES:
        if s <= cap and s <= length and length % s == 0:
            return s
    return 1


def _pallas_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k, interpret):
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _auto_block(lq, _AUTO_BLOCK_Q_CAP) if block_q is None else min(block_q, lq)
    block_k = _auto_block(lk, _AUTO_BLOCK_K_CAP) if block_k is None else min(block_k, lk)
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"sequence lengths ({lq}, {lk}) must divide block sizes "
            f"({block_q}, {block_k}); pad inputs first"
        )
    grid = (bh, lq // block_q, lk // block_k)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, block_q, block_k), _bias_index_map(bias.shape[0], bh))
        )
        args.append(bias)
    if kv_mask is not None:
        nb = kv_mask.shape[0]
        if nb == 1:
            mask_map = lambda b, i, j: (0, 0, j)  # noqa: E731
        else:
            h_per = bh // nb
            mask_map = lambda b, i, j: (b // h_per, 0, j)  # noqa: E731
        # carried as (B, 1, Lk): the singleton sublane dim must equal the
        # array dim for the TPU lowering (a (1, block_k) block over (B, Lk)
        # is rejected — sublane 1 neither divides 8 nor equals B)
        in_specs.append(pl.BlockSpec((1, 1, block_k), mask_map))
        args.append(kv_mask[:, None, :])

    if bias is not None and kv_mask is not None:
        kernel = _kernel
    elif bias is not None:
        kernel = _kernel_nm
    elif kv_mask is not None:
        kernel = _kernel_nb
    else:
        kernel = _kernel_nbm

    out, lse = pl.pallas_call(
        functools.partial(
            kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse as a (bh, lq, 1) column: block (1, block_q, 1) satisfies the
            # TPU (sublane, lane) tiling rules where a (1, block_q) block over
            # (bh, lq) does not
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (lane-bcast)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum (lane-bcast)
        ],
        interpret=interpret,
    )(*args)
    return out, lse[..., 0]


# --------------------------------------------------------------------------
# reference (oracle for tests; recompute target for the backward pass)
# --------------------------------------------------------------------------


def _expand_bias(bias, bh, lq, lk):
    if bias is None:
        return None
    b0 = bias.shape[0]
    if b0 == bh:
        return bias
    if b0 == 1:
        return jnp.broadcast_to(bias, (bh, lq, lk))
    reps = bh // b0
    return jnp.broadcast_to(bias[None], (reps, b0, lq, lk)).reshape(bh, lq, lk)


def _reference_pair(q, k, v, bias, kv_mask, scale, causal):
    bh, lq, d = q.shape
    lk = k.shape[1]
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    bias = _expand_bias(bias, bh, lq, lk)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if kv_mask is not None:
        h_per = bh // kv_mask.shape[0]
        m = jnp.repeat(kv_mask.astype(jnp.float32), h_per, axis=0)  # (bh, lk)
        s = s + m[:, None, :]
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        kj = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where(qi >= kj, s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)
    return out, lse


def _reference_attention(q, k, v, bias, scale, causal, kv_mask=None):
    return _reference_pair(q, k, v, bias, kv_mask, scale, causal)[0]


# --------------------------------------------------------------------------
# differentiable entry (custom VJP over both outputs)
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_pair(q, k, v, bias, kv_mask, scale, causal, block_q, block_k, interpret):
    return _pallas_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k,
                       interpret)


def _flash_pair_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k,
                    interpret):
    out = _pallas_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k,
                      interpret)
    return out, (q, k, v, bias, kv_mask)


def _flash_pair_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, bias, kv_mask = res

    def f(q, k, v, bias):
        return _reference_pair(q, k, v, bias, kv_mask, scale, causal)

    _, vjp = jax.vjp(f, q, k, v, bias)
    dq, dk, dv, dbias = vjp(g)
    dmask = None if kv_mask is None else jnp.zeros_like(kv_mask)
    return dq, dk, dv, dbias, dmask


_flash_pair.defvjp(_flash_pair_fwd, _flash_pair_bwd)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def _normalize(q, k, v, bias):
    """Accept (B, H, L, D) or (B·H, L, D); fold heads into batch."""
    if q.ndim == 4:
        b, h, lq, d = q.shape
        q = q.reshape(b * h, lq, d)
        k = k.reshape(b * h, k.shape[2], d)
        v = v.reshape(b * h, v.shape[2], d)
        if bias is not None:
            if bias.ndim != 4:
                raise ValueError("bias must be 4D when q/k/v are 4D")
            bb, bh_, blq, blk = bias.shape
            if bb == 1:
                bias = bias.reshape(bh_, blq, blk)  # (H|1, Lq, Lk)
            else:
                bias = jnp.broadcast_to(bias, (b, h, blq, blk)).reshape(
                    b * h, blq, blk
                )
        return q, k, v, bias, (b, h)
    return q, k, v, bias, None


def flash_attention(
    q,
    k,
    v,
    bias: Optional[jax.Array] = None,
    *,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Blockwise attention.

    q/k/v: (B·H, L, D) or (B, H, L, D).  bias: additive scores, leading dim
    1, H, or B·H (T5 passes its (1, H, Lq, Lk) relative-position bias
    directly — it is NOT expanded to batch size).  kv_mask: (B, Lk) with
    1 = attend, 0 = masked (key padding).  scale defaults to 1/sqrt(D);
    pass 1.0 for T5.  On non-TPU backends runs in Pallas interpret mode so
    the same code path tests on the CPU mesh (SURVEY.md §4.3).
    """
    q, k, v, bias, fold = _normalize(q, k, v, bias)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    addmask = None
    if kv_mask is not None:
        addmask = (1.0 - kv_mask.astype(jnp.float32)) * _NEG_INF
    out, _ = _flash_pair(q, k, v, bias, addmask, float(scale), bool(causal),
                         block_q, block_k, bool(interpret))
    if fold is not None:
        b, h = fold
        out = out.reshape(b, h, out.shape[1], out.shape[2])
    return out


def flash_attention_with_lse(
    q, k, v, bias=None, *, kv_mask=None, scale=None, causal=False,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(out, logsumexp) variant — ring attention merges partial softmaxes
    across devices with the lse.  Differentiable in both outputs."""
    q, k, v, bias, fold = _normalize(q, k, v, bias)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    addmask = None
    if kv_mask is not None:
        addmask = (1.0 - kv_mask.astype(jnp.float32)) * _NEG_INF
    out, lse = _flash_pair(q, k, v, bias, addmask, float(scale), bool(causal),
                           block_q, block_k, bool(interpret))
    if fold is not None:
        b, h = fold
        out = out.reshape(b, h, out.shape[1], out.shape[2])
        lse = lse.reshape(b, h, lse.shape[1])
    return out, lse
