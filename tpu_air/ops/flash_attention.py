"""Flash attention — Pallas TPU kernel with blockwise online softmax.

The hot op of the model layer (SURVEY.md §2B ATen row → "Pallas for anything
custom").  Blockwise streaming over K/V keeps the (Lq, Lk) score matrix out
of HBM: VMEM holds one (BQ, BK) tile at a time and the MXU sees back-to-back
(BQ,D)x(D,BK) and (BQ,BK)x(BK,D) matmuls; running max/sum statistics ride in
VMEM scratch across the sequentially-iterated k grid dimension (TPU grid
order is row-major, so the innermost k axis revisits the same q tile's
scratch).

Broadcast-aware operands — the reason a stock kernel doesn't fit T5:
* ``bias``: additive scores of shape (1|H|B·H, Lq, Lk).  T5's relative-
  position bias is per-head but batch-shared (H, Lq, Lk); the BlockSpec
  index map replays the same head tile for every batch element instead of
  materializing a (B·H, Lq, Lk) array in HBM.
* ``kv_mask``: per-batch key-padding mask (B, Lk), 1 = attend.  Expanded to
  a (1, BK) additive tile inside VMEM, never an (Lq, Lk) matrix.
* ``causal``: masking from block-local iota, zero HBM.

f32 accumulation regardless of input dtype.  BACKWARD is blockwise Pallas
too (``_pallas_bwd``: a dq pass and a dk/dv pass over saved (out, lse)) —
O(L) memory end to end, which is what makes long-context TRAINING feasible,
not just the forward.  Exception: when an additive ``bias`` is present
(T5's learned relative-position bias) the VJP falls back to an XLA
recompute of the reference attention, since dbias is dense (H, Lq, Lk)
regardless.  Both the attention output and the logsumexp are
differentiable — the lse cotangent folds into the backward's delta term —
so ring attention (ring_attention.py) trains through merged stats on the
kernel path.

Fully-masked rows (a query whose ``kv_mask`` hides EVERY key): the forward
emits mean(V) — matching the dense reference, whose softmax over an all
-masked row degenerates to uniform weights — but the custom VJP defines the
gradient of such a row as exactly ZERO dq/dk/dv, where autodiff of the
computed function would give a nonzero uniform dv.  This is deliberate:
a fully-masked row is padding, and padding must not train.  SP/ring users
who pad whole rows get zero gradients for them by contract (see
``_bwd_p``).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


# --------------------------------------------------------------------------
# kernel
# --------------------------------------------------------------------------


def _kernel(q_ref, k_ref, v_ref, bias_ref, mask_ref, out_ref, lse_ref,
            acc_ref, m_ref, l_ref, *, scale, causal, block_q, block_k):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Skip tiles entirely above the causal diagonal: p is identically zero
    # there, so both matmuls and the softmax update are dead work (~2x at
    # large L).
    live = _causal_live(i, j, block_q, block_k) if causal else True

    @pl.when(live)
    def _body():
        # Matmul operands stay in the INPUT dtype (bf16 on chip runs the
        # MXU at ~4x its f32 rate — the r5 tile sweep measured the f32
        # kernel at 29 TF/s vs 80 for XLA dense at seq 512); accumulation
        # and every softmax statistic remain f32, the same precision
        # budget as the dense einsum path (bf16 operands, f32 softmax).
        q = q_ref[0]  # (BQ, D)
        k = k_ref[0]  # (BK, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (BQ, BK)
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if mask_ref is not None:
            # (1, BK) additive key-padding row, broadcast over queries
            s = s + mask_ref[0].astype(jnp.float32)
        if causal:
            qi = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kj = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qi >= kj, s, _NEG_INF)

        m_prev = m_ref[:, :1]  # (BQ, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)  # (BQ, BK)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        # NB: masking uses finite -1e30, so a fully-masked row has p=exp(0)=1
        # per entry and l == klen, never 0 — such rows yield mean(V), matching
        # the dense softmax reference path.  The guard below only protects
        # against division by zero for degenerate zero-length tiles.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0] = (acc_ref[:] / safe_l).astype(out_ref.dtype)
        # lse block is (1, BQ, 1) — column layout keeps the sublane dim a
        # multiple of 8 as the TPU lowering requires
        lse_ref[0] = m_ref[:, :1] + jnp.log(safe_l)


def _kernel_nb(q, k, v, m, o, lse, acc, mr, lr, **kw):
    _kernel(q, k, v, None, m, o, lse, acc, mr, lr, **kw)


def _kernel_nm(q, k, v, b, o, lse, acc, mr, lr, **kw):
    _kernel(q, k, v, b, None, o, lse, acc, mr, lr, **kw)


def _kernel_nbm(q, k, v, o, lse, acc, mr, lr, **kw):
    _kernel(q, k, v, None, None, o, lse, acc, mr, lr, **kw)


def _bias_index_map(bias_b: int, bh: int):
    if bias_b == bh:
        return lambda b, i, j: (b, i, j)
    if bias_b == 1:
        return lambda b, i, j: (0, i, j)
    if bh % bias_b == 0:
        # per-head, batch-shared: grid b = batch*H + head, bias_b == H
        return lambda b, i, j: (b % bias_b, i, j)
    raise ValueError(f"bias leading dim {bias_b} incompatible with batch·heads {bh}")


_BLOCK_CANDIDATES = (1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1)
# Measured on TPU v5e (BH=48, D=64, bf16, slope-timed): (128, 128) runs at
# 6-8 TF/s while (512, 1024) reaches 48-80 TF/s — 3-5x FASTER than XLA's
# dense path at L >= 2048 and ~parity at L = 512.  Bigger k tiles amortize
# the per-block online-softmax rescale; bigger q tiles amortize k/v
# streams.  r5 re-sweep with bf16 matmul operands (halved VMEM tiles):
# (1024, 1024) beats (512, 1024) at every length — 52.9 vs 49.0 TF/s at
# L=1024, 62.7 vs 55.9 at 2048, 66.4 vs 57.7 at 4096 (4.26x dense);
# (1024, 4096) and (2048, 2048) exceed VMEM.  The r5 512-seq tile sweep
# (tools/tune_flash_tiles.py) also RE-confirmed the einsum crossover:
# best flash tiling at L=512 is 29 TF/s vs 80 for XLA dense, so
# flash_min_seq_len=1024 stands on data.
_AUTO_BLOCK_Q_CAP = 1024
_AUTO_BLOCK_K_CAP = 1024


def _auto_block(length: int, cap: int) -> int:
    """Largest power-of-two-ish tile <= cap that divides ``length``."""
    for s in _BLOCK_CANDIDATES:
        if s <= cap and s <= length and length % s == 0:
            return s
    return 1


def auto_dispatch_ok(qlen: int, klen: int) -> bool:
    """Should attention_impl="auto" route this shape to the flash kernel?

    Two gates beyond the caller's seq-length crossover check:
    * backend must be TPU — off-TPU the kernel runs in Pallas INTERPRET
      mode, orders of magnitude slower than einsum regardless of length;
    * the auto tiling must find real tiles — an awkward length (no
      power-of-two-ish divisor) degrades to 1-wide tiles, the ~1/8-MXU-rate
      cliff, so einsum wins there too.
    """
    import jax

    if jax.default_backend() != "tpu":
        return False
    return (_auto_block(qlen, _AUTO_BLOCK_Q_CAP) >= 128
            and _auto_block(klen, _AUTO_BLOCK_K_CAP) >= 128)


def _pallas_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k, interpret):
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _auto_block(lq, _AUTO_BLOCK_Q_CAP) if block_q is None else min(block_q, lq)
    block_k = _auto_block(lk, _AUTO_BLOCK_K_CAP) if block_k is None else min(block_k, lk)
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"sequence lengths ({lq}, {lk}) must divide block sizes "
            f"({block_q}, {block_k}); pad inputs first"
        )
    grid = (bh, lq // block_q, lk // block_k)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, block_q, block_k), _bias_index_map(bias.shape[0], bh))
        )
        args.append(bias)
    if kv_mask is not None:
        nb = kv_mask.shape[0]
        if nb == 1:
            mask_map = lambda b, i, j: (0, 0, j)  # noqa: E731
        else:
            h_per = bh // nb
            mask_map = lambda b, i, j: (b // h_per, 0, j)  # noqa: E731
        # carried as (B, 1, Lk): the singleton sublane dim must equal the
        # array dim for the TPU lowering (a (1, block_k) block over (B, Lk)
        # is rejected — sublane 1 neither divides 8 nor equals B)
        in_specs.append(pl.BlockSpec((1, 1, block_k), mask_map))
        args.append(kv_mask[:, None, :])

    if bias is not None and kv_mask is not None:
        kernel = _kernel
    elif bias is not None:
        kernel = _kernel_nm
    elif kv_mask is not None:
        kernel = _kernel_nb
    else:
        kernel = _kernel_nbm

    out, lse = pl.pallas_call(
        functools.partial(
            kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # lse as a (bh, lq, 1) column: block (1, block_q, 1) satisfies the
            # TPU (sublane, lane) tiling rules where a (1, block_q) block over
            # (bh, lq) does not
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max (lane-bcast)
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum (lane-bcast)
        ],
        interpret=interpret,
    )(*args)
    return out, lse[..., 0]


# --------------------------------------------------------------------------
# reference (oracle for tests; recompute target for the backward pass)
# --------------------------------------------------------------------------


def _expand_bias(bias, bh, lq, lk):
    if bias is None:
        return None
    b0 = bias.shape[0]
    if b0 == bh:
        return bias
    if b0 == 1:
        return jnp.broadcast_to(bias, (bh, lq, lk))
    reps = bh // b0
    return jnp.broadcast_to(bias[None], (reps, b0, lq, lk)).reshape(bh, lq, lk)


def _reference_pair(q, k, v, bias, kv_mask, scale, causal):
    bh, lq, d = q.shape
    lk = k.shape[1]
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    bias = _expand_bias(bias, bh, lq, lk)
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if kv_mask is not None:
        h_per = bh // kv_mask.shape[0]
        m = jnp.repeat(kv_mask.astype(jnp.float32), h_per, axis=0)  # (bh, lk)
        s = s + m[:, None, :]
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        kj = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where(qi >= kj, s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)
    return out, lse


def _reference_attention(q, k, v, bias, scale, causal, kv_mask=None):
    return _reference_pair(q, k, v, bias, kv_mask, scale, causal)[0]


# --------------------------------------------------------------------------
# backward kernels (blockwise, O(L) memory — no (Lq, Lk) materialization)
# --------------------------------------------------------------------------
#
# Standard flash-attention backward from the saved (out, lse) statistics:
#   p_ij  = exp(s_ij - lse_i)
#   dv_j  = Σ_i p_ij^T · do_i
#   dp_ij = do_i · v_j^T
#   ds_ij = p_ij · (dp_ij - Δ_i)        Δ_i = rowsum(do_i ∘ o_i) - glse_i
#   dq_i  = Σ_j ds_ij · k_j · scale
#   dk_j  = Σ_i ds_ij^T · q_i · scale
# The logsumexp cotangent folds into Δ (∂lse_i/∂s_ij = p_ij), which is what
# lets ring attention train through merged softmax stats with no extra pass.
# Two kernels because the two accumulations run over different grid axes:
# dq accumulates across j (j innermost revisits the q tile's scratch), dk/dv
# across i.  The bias path keeps the XLA recompute backward — T5's learned
# relative-position bias needs a dense (H, Lq, Lk) dbias regardless.


def _bwd_p(s, lse):
    """exp(s - lse), with MASKED entries hard-zeroed.  f32 can't represent
    -1e30 + log(klen), so a fully-masked row's lse rounds back to -1e30 and
    the naive exp gives 1 per entry — klen-times the forward's
    normalization.  Zeroing keeps such degenerate rows' gradients at 0."""
    return jnp.where(s <= 0.5 * _NEG_INF, 0.0, jnp.exp(s - lse))


def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
              i, j, scale, causal, block_q, block_k):
    """Shared per-tile backward computation: recompute scores with the SAME
    masking as the forward (single source of truth), then p and ds.
    Returns (q, k, do, p, ds): operands q/k/do in their INPUT dtype
    (bf16 matmuls on chip — see the forward kernel's precision note),
    p/ds f32."""
    q = q_ref[0]
    k = k_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if mask_ref is not None:
        s = s + mask_ref[0].astype(jnp.float32)
    if causal:
        qi = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        kj = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(qi >= kj, s, _NEG_INF)
    p = _bwd_p(s, lse_ref[0])                        # (BQ, BK)
    do = do_ref[0]                                   # (BQ, D)
    dp = jax.lax.dot_general(
        do.astype(v_ref.dtype), v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                # (BQ, BK)
    ds = p * (dp - delta_ref[0])
    return q, k, do, p, ds


def _causal_live(i, j, block_q, block_k):
    """False iff the (i, j) tile is ENTIRELY above the causal diagonal
    (max query index < min key index) — its p is identically zero, so both
    backward matmuls and the exp can be skipped (~2x at large L)."""
    return (i + 1) * block_q - 1 >= j * block_k


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = _causal_live(i, j, block_q, block_k) if causal else True

    @pl.when(live)
    def _body():
        _, k, _, _, ds = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                   delta_ref, mask_ref, i, j, scale, causal,
                                   block_q, block_k)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dq_nm(q, k, v, do, lse, delta, dq, acc, **kw):
    _bwd_dq_kernel(q, k, v, do, lse, delta, None, dq, acc, **kw)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k):
    j = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = _causal_live(i, j, block_q, block_k) if causal else True

    @pl.when(live)
    def _body():
        q, _, do, p, ds = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                    delta_ref, mask_ref, i, j, scale, causal,
                                    block_q, block_k)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # (BK, D)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                    # (BK, D)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dkv_nm(q, k, v, do, lse, delta, dk, dv, dka, dva, **kw):
    _bwd_dkv_kernel(q, k, v, do, lse, delta, None, dk, dv, dka, dva, **kw)


def _pallas_bwd(q, k, v, kv_mask, out, lse, do, glse, scale, causal,
                block_q, block_k, interpret):
    """dq/dk/dv via the blockwise backward.  ``kv_mask`` here is the
    ADDITIVE form (as in the forward).  Returns f32 grads in input dtype."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    block_q = _auto_block(lq, _AUTO_BLOCK_Q_CAP) if block_q is None else min(block_q, lq)
    block_k = _auto_block(lk, _AUTO_BLOCK_K_CAP) if block_k is None else min(block_k, lk)

    # Δ_i = rowsum(do ∘ o) - glse_i: O(L·D) precompute, carried as a column
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )
    if glse is not None:
        delta = delta - glse.astype(jnp.float32)[..., None]
    lse_col = lse.astype(jnp.float32)[..., None]     # (bh, lq, 1)

    def mask_spec_args(block_first):
        if kv_mask is None:
            return [], []
        nb = kv_mask.shape[0]
        if nb == 1:
            mmap = (lambda b, x, y: (0, 0, y)) if block_first else \
                   (lambda b, x, y: (0, 0, x))
        else:
            h_per = bh // nb
            mmap = (lambda b, x, y: (b // h_per, 0, y)) if block_first else \
                   (lambda b, x, y: (b // h_per, 0, x))
        return ([pl.BlockSpec((1, 1, block_k), mmap)], [kv_mask[:, None, :]])

    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k)

    # pass 1: dq — grid (bh, i, j), j innermost accumulates into dq scratch
    mspecs, margs = mask_spec_args(block_first=True)
    dq_kernel = _bwd_dq_kernel if kv_mask is not None else _bwd_dq_nm
    (dq,) = pl.pallas_call(
        functools.partial(dq_kernel, **kw),
        grid=(bh, lq // block_q, lk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),   # v
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),   # do
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # lse
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),   # delta
            *mspecs,
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, lq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse_col, delta, *margs)

    # pass 2: dk/dv — grid (bh, j, i), i innermost accumulates into scratch
    mspecs, margs = mask_spec_args(block_first=False)
    dkv_kernel = _bwd_dkv_kernel if kv_mask is not None else _bwd_dkv_nm
    dk, dv = pl.pallas_call(
        functools.partial(dkv_kernel, **kw),
        grid=(bh, lk // block_k, lq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # q
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),   # v
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),   # do
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),   # lse
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),   # delta
            *mspecs,
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, lk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, lk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_col, delta, *margs)
    return dq, dk, dv


# --------------------------------------------------------------------------
# differentiable entry (custom VJP over both outputs)
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_pair(q, k, v, bias, kv_mask, scale, causal, block_q, block_k, interpret):
    return _pallas_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k,
                       interpret)


def _flash_pair_fwd(q, k, v, bias, kv_mask, scale, causal, block_q, block_k,
                    interpret):
    out, lse = _pallas_fwd(q, k, v, bias, kv_mask, scale, causal, block_q,
                           block_k, interpret)
    return (out, lse), (q, k, v, bias, kv_mask, out, lse)


def _flash_pair_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, bias, kv_mask, out, lse = res
    do, glse = g

    if bias is None:
        # blockwise backward: O(L) memory, no (Lq, Lk) materialization —
        # this is what makes long-context training (ring attention / SP)
        # memory-feasible, not just the forward
        dq, dk, dv = _pallas_bwd(
            q, k, v, kv_mask, out, lse, do, glse, scale, causal,
            block_q, block_k, interpret,
        )
        dmask = None if kv_mask is None else jnp.zeros_like(kv_mask)
        return dq, dk, dv, None, dmask

    # bias path (T5 relative-position bias): the learned bias needs a dense
    # (H, Lq, Lk) gradient anyway — recompute through the XLA reference
    def f(q, k, v, bias):
        return _reference_pair(q, k, v, bias, kv_mask, scale, causal)

    _, vjp = jax.vjp(f, q, k, v, bias)
    dq, dk, dv, dbias = vjp(g)
    dmask = None if kv_mask is None else jnp.zeros_like(kv_mask)
    return dq, dk, dv, dbias, dmask


_flash_pair.defvjp(_flash_pair_fwd, _flash_pair_bwd)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------


def _normalize(q, k, v, bias):
    """Accept (B, H, L, D) or (B·H, L, D); fold heads into batch."""
    if q.ndim == 4:
        b, h, lq, d = q.shape
        q = q.reshape(b * h, lq, d)
        k = k.reshape(b * h, k.shape[2], d)
        v = v.reshape(b * h, v.shape[2], d)
        if bias is not None:
            if bias.ndim != 4:
                raise ValueError("bias must be 4D when q/k/v are 4D")
            bb, bh_, blq, blk = bias.shape
            if bb == 1:
                bias = bias.reshape(bh_, blq, blk)  # (H|1, Lq, Lk)
            else:
                bias = jnp.broadcast_to(bias, (b, h, blq, blk)).reshape(
                    b * h, blq, blk
                )
        return q, k, v, bias, (b, h)
    return q, k, v, bias, None


def flash_attention(
    q,
    k,
    v,
    bias: Optional[jax.Array] = None,
    *,
    kv_mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Blockwise attention.

    q/k/v: (B·H, L, D) or (B, H, L, D).  bias: additive scores, leading dim
    1, H, or B·H (T5 passes its (1, H, Lq, Lk) relative-position bias
    directly — it is NOT expanded to batch size).  kv_mask: (B, Lk) with
    1 = attend, 0 = masked (key padding).  scale defaults to 1/sqrt(D);
    pass 1.0 for T5.  On non-TPU backends runs in Pallas interpret mode so
    the same code path tests on the CPU mesh (SURVEY.md §4.3).
    """
    q, k, v, bias, fold = _normalize(q, k, v, bias)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    addmask = None
    if kv_mask is not None:
        addmask = (1.0 - kv_mask.astype(jnp.float32)) * _NEG_INF
    out, _ = _flash_pair(q, k, v, bias, addmask, float(scale), bool(causal),
                         block_q, block_k, bool(interpret))
    if fold is not None:
        b, h = fold
        out = out.reshape(b, h, out.shape[1], out.shape[2])
    return out


def flash_attention_with_lse(
    q, k, v, bias=None, *, kv_mask=None, scale=None, causal=False,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(out, logsumexp) variant — ring attention merges partial softmaxes
    across devices with the lse.  Differentiable in both outputs."""
    q, k, v, bias, fold = _normalize(q, k, v, bias)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    addmask = None
    if kv_mask is not None:
        addmask = (1.0 - kv_mask.astype(jnp.float32)) * _NEG_INF
    out, lse = _flash_pair(q, k, v, bias, addmask, float(scale), bool(causal),
                           block_q, block_k, bool(interpret))
    if fold is not None:
        b, h = fold
        out = out.reshape(b, h, out.shape[1], out.shape[2])
        lse = lse.reshape(b, h, lse.shape[1])
    return out, lse
