"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context first-class support: the (Lq, Lk) attention problem is sharded
so each device owns an L/P slice of Q, K, V.  K/V blocks rotate around the
ring via ``jax.lax.ppermute`` (ICI neighbor exchange — the XLA-collective
equivalent of the published ring-attention schedule), and each device folds
the incoming block into its running blockwise softmax using the (out, lse)
pair from the local flash kernel.  P steps later every device holds its
exact attention output — no device ever materializes more than
O((L/P)² ) scores, and the rotation overlaps with compute under XLA's
async collective scheduling.

Causal masking works by HOP TYPE: shards are contiguous global slices, so
each ring step is either the diagonal (local causal mask inside the
kernel), fully visible (no mask), or fully masked (kernel skipped
entirely — and its ~P/2 of the hops' compute saved).  No additive bias is
ever built, which keeps the blockwise Pallas backward on the training path.

Composable with data/tensor parallelism: just name a ``sequence`` axis in
the mesh and shard L over it (see tests/test_ops.py for the shard_map
harness on the 8-device CPU mesh).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .flash_attention import flash_attention_with_lse


def _merge(out_a, lse_a, out_b, lse_b):
    """Combine two partial-softmax results (flash's streaming rule, applied
    across devices instead of across VMEM tiles)."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)[..., None]
    wb = jnp.exp(lse_b - m)[..., None]
    out = (out_a.astype(jnp.float32) * wa + out_b.astype(jnp.float32) * wb) / (wa + wb)
    lse = m + jnp.log(jnp.exp(lse_a - m) + jnp.exp(lse_b - m))
    return out, lse


def ring_attention(
    q,
    k,
    v,
    *,
    axis_name: str,
    scale: Optional[float] = None,
    causal: bool = False,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Attention over sequence-sharded q/k/v inside shard_map/pmap.

    ``q/k/v``: (batch·heads, L_local, head_dim) — the local sequence shard.
    Must run inside a mapped context where ``axis_name`` is a mesh axis of
    size P; returns the local (batch·heads, L_local, head_dim) output shard.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    p = jax.lax.psum(1, axis_name)  # ring size
    my = jax.lax.axis_index(axis_name)
    l_local = q.shape[1]

    def step(carry, _):
        out, lse, kv_k, kv_v, owner = carry
        # Causality by HOP TYPE, not by an additive bias: the shards are
        # contiguous global slices, so a hop is (a) the diagonal
        # (owner == my: plain local causal), (b) fully visible (owner < my),
        # or (c) fully masked (owner > my: skip the kernel entirely).
        # Keeping ``bias=None`` is load-bearing — the bias path falls back
        # to the dense-recompute VJP, while these branches keep the
        # blockwise Pallas BACKWARD (O(L) memory) on the training path.
        kw = dict(scale=scale, block_q=block_q, block_k=block_k)

        def diagonal(q, kk, vv):
            return flash_attention_with_lse(q, kk, vv, causal=True, **kw)

        def visible(q, kk, vv):
            return flash_attention_with_lse(q, kk, vv, causal=False, **kw)

        def masked(q, kk, vv):
            return (jnp.zeros(q.shape, q.dtype),
                    jnp.full(q.shape[:2], -1e30, jnp.float32))

        if causal:
            branch = jnp.where(owner == my, 0, jnp.where(owner < my, 1, 2))
            o_i, lse_i = jax.lax.switch(branch, [diagonal, visible, masked],
                                        q, kv_k, kv_v)
        else:
            o_i, lse_i = visible(q, kv_k, kv_v)
        out, lse = _merge(out, lse, o_i, lse_i)
        # rotate K/V to the next device on the ring (neighbor ICI hop)
        perm = [(i, (i + 1) % p) for i in range(p)]
        kv_k = jax.lax.ppermute(kv_k, axis_name, perm)
        kv_v = jax.lax.ppermute(kv_v, axis_name, perm)
        owner = (owner - 1) % p
        return (out, lse, kv_k, kv_v, owner), None

    out0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full(q.shape[:2], -1e30, jnp.float32)
    (out, lse, _, _, _), _ = jax.lax.scan(
        step, (out0, lse0, k, v, my), None, length=p
    )
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = "sequence",
                           causal: bool = False, scale=None,
                           block_q: Optional[int] = None, block_k: Optional[int] = None):
    """Convenience wrapper: shard (bh, L, d) arrays over ``axis_name`` of
    ``mesh`` and run ring attention via shard_map."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_air.parallel.shardmap_compat import shard_map_unchecked

    spec = P(None, axis_name, None)
    body = functools.partial(
        ring_attention, axis_name=axis_name, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k,
    )
    fn = shard_map_unchecked(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    sharding = NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)
