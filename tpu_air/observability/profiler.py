"""Profiling hooks (SURVEY.md §5 tracing: "per-step timing in the trainer
loop, JAX profiler hooks (xplane traces)").

* ``step_timer`` — lightweight wall/step accounting used by the trainer loops
  (the reference's only in-repo tracing is %%time cells and time.time deltas,
  Overview_of_Ray.ipynb:cc-18,24,47 — this is the structured version).
* ``profile_trace`` — context manager around ``jax.profiler.trace`` producing
  xplane/perfetto traces viewable in TensorBoard or ui.perfetto.dev.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

from . import tracing as _tracing
from .perf import Histogram


class step_timer:
    """Accumulates per-step wall times; cheap enough for every train step.

    >>> t = step_timer()
    >>> with t.step():  # around each train_step
    ...     ...
    >>> t.summary()  # {'steps': N, 'mean_s': ..., 'p50_s': ..., 'p95_s': ...}

    Quantiles come from an airscope log-bucketed :class:`Histogram` — the
    same estimator the engine metrics use, so a trainer's p95 and the
    dashboard's p95 agree on method (the raw ``durations`` list stays
    available for exact math downstream).

    With ``span_name`` set AND tracing enabled, every step additionally
    lands as an airtrace span (parented under the ambient context) so the
    same numbers show up on the request/trial timeline; the default path
    stays a bare perf_counter delta.
    """

    def __init__(self, span_name: Optional[str] = None):
        self.durations: list = []
        self._hist = Histogram()
        self._span_name = span_name

    @contextlib.contextmanager
    def step(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.durations.append(dt)
            self._hist.observe(dt)
            if self._span_name is not None and _tracing.enabled():
                end = _tracing.now_ns()
                ctx = _tracing.current_context()
                _tracing.record_span(
                    self._span_name,
                    trace_id=ctx.trace_id if ctx else None,
                    parent_id=ctx.span_id if ctx else None,
                    start_ns=end - int(dt * 1e9),
                    end_ns=end,
                    attrs={"step": len(self.durations)},
                )

    def summary(self) -> Dict[str, Any]:
        s = self._hist.summary()
        if not s.get("count"):
            return {"steps": 0}
        return {
            "steps": s["count"],
            "total_s": s["sum"],
            "mean_s": s["mean"],
            "p50_s": s["p50"],
            "p95_s": s["p95"],
            "max_s": s["max"],
        }


@contextlib.contextmanager
def profile_trace(log_dir: str, host_tracer_level: Optional[int] = None) -> Iterator[None]:
    """JAX xplane trace around a region — open the resulting directory in
    TensorBoard's profile plugin (tensorboardX is in the pinned stack,
    requirements.txt:156-equivalent).

    When tracing is enabled, the region also lands as an airtrace span whose
    ``log_dir`` attr points at the xplane dump — the trace id is the join
    key between the host-side timeline and the on-chip profile."""
    import jax

    opts = {}
    if host_tracer_level is not None:
        # jax>=0.4.x takes tracer levels via ProfileOptions, not a kwarg
        try:
            po = jax.profiler.ProfileOptions()
            po.host_tracer_level = host_tracer_level
            opts["profiler_options"] = po
        except AttributeError:  # older jax: legacy kwarg
            opts["host_tracer_level"] = host_tracer_level
    t0 = _tracing.now_ns() if _tracing.enabled() else 0
    try:
        with jax.profiler.trace(log_dir, **opts):
            yield
    finally:
        if t0:
            ctx = _tracing.current_context()
            _tracing.record_span(
                "profiler.xplane_trace",
                trace_id=ctx.trace_id if ctx else None,
                parent_id=ctx.span_id if ctx else None,
                start_ns=t0,
                end_ns=_tracing.now_ns(),
                attrs={"log_dir": log_dir},
            )
