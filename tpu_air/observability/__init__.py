"""tpu_air.observability — dashboard, cluster state, profiling hooks.

The reference stack promotes the Ray Dashboard at 127.0.0.1:8265 as "a vital
observability tool" (Model_finetuning…ipynb:cc-9; Install_locally.md:64-67).
The TPU-native equivalent is a JSON status service + prometheus text
endpoint over the driver runtime's live state (SURVEY.md §2B dashboard row,
§5 tracing notes).
"""

from .dashboard import start_dashboard, stop_dashboard, snapshot
from .profiler import profile_trace, step_timer
from . import perf
from . import postmortem
from . import slo
from . import timeseries
from . import tracing
from . import trace_export
from . import watch

__all__ = [
    "perf",
    "postmortem",
    "profile_trace",
    "slo",
    "snapshot",
    "start_dashboard",
    "step_timer",
    "stop_dashboard",
    "timeseries",
    "trace_export",
    "tracing",
    "watch",
]
