"""airscope — the perf pillar of tpu_air observability.

Three pieces, each usable alone:

* :class:`Histogram` — a thread-safe log-bucketed streaming histogram.
  Buckets grow by ``2**(1/4)`` (≤ ~9% relative error per bucket), counts
  are a sparse ``{bucket_index: count}`` dict so two histograms — or two
  serialized snapshots from different replicas — merge by adding counts.
  Each bucket optionally carries an OpenMetrics-style *exemplar*: the
  airtrace ``trace_id`` of the bucket's worst recent sample, so a p99 on
  the dashboard is one ``/api/traces?trace_id=`` click from its span tree.
  This replaces the seed's 256-sample deques + sorted-index quantiles:
  quantiles here are unwindowed and unbiased to bucket resolution.

* :class:`LMCostModel` — an analytic flops/bytes model for the engine's
  compiled programs (paged decode step, prefill chunk, train step),
  derived from model geometry the way the pjit/TPUv4 scaling work does it
  (PAPERS.md, arXiv:2204.06514): costs come from the shapes the machine
  actually executes (fixed S×slot_len decode, ``[1, page_len]`` chunks),
  not from per-request token counts.

* :class:`PerfLedger` — accumulates ``(cost, seconds)`` per program kind
  into achieved flops/s and bytes/s, a roofline fraction against a
  detected-or-configured peak (:func:`detect_peak` — CPU fallback
  constants keep tier-1 meaningful everywhere), and a goodput split of
  emitted tokens into useful vs. wasted work (shed-after-prefill,
  re-prefilled-on-cache-miss, dead-stream; spec-decode rejections plug in
  as just another category).
"""

from __future__ import annotations

import math
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional

# -- histogram ---------------------------------------------------------------

# bucket upper bounds are _BASE**i for integer i (i may be negative);
# bucket i covers (_BASE**(i-1), _BASE**i].  2**(1/4) keeps relative
# quantile error under ~9% while a seconds-scale latency range
# (1e-6 .. 1e3) still spans only ~120 live buckets.
_BASE = 2.0 ** 0.25
_LN_BASE = math.log(_BASE)
# values at or below this clamp into the bottom bucket (latencies are
# positive; 1ns is far below anything a host-side timer can resolve)
_MIN_VALUE = 1e-9
# an exemplar older than this loses its slot to ANY newer sample, even a
# smaller one — "worst recent", not "worst ever"
_EXEMPLAR_TTL_S = 300.0


def bucket_index(value: float) -> int:
    """The histogram bucket a value lands in: smallest integer ``i`` with
    ``value <= _BASE**i`` (epsilon keeps exact bounds in their own bucket)."""
    v = max(float(value), _MIN_VALUE)
    return math.ceil(math.log(v) / _LN_BASE - 1e-9)


def bucket_upper(index: int) -> float:
    """Inclusive upper bound of bucket ``index``."""
    return math.exp(index * _LN_BASE)


class Histogram:
    """Streaming log-bucketed histogram with mergeable buckets and
    per-bucket trace exemplars.  All methods are thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[int, int] = {}
        self._exemplars: Dict[int, Dict[str, Any]] = {}
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    # -- recording -----------------------------------------------------------
    def observe(self, value: float, trace_id: Optional[str] = None) -> None:
        v = float(value)
        idx = bucket_index(v)
        now = time.time()
        with self._lock:
            self._buckets[idx] = self._buckets.get(idx, 0) + 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if trace_id:
                ex = self._exemplars.get(idx)
                if (ex is None or v >= ex["value"]
                        or now - ex["ts"] > _EXEMPLAR_TTL_S):
                    self._exemplars[idx] = {
                        "value": v, "trace_id": trace_id, "ts": now}

    def merge_state(self, state: Dict[str, Any]) -> None:
        """Fold a serialized snapshot (:meth:`to_dict` of another instance,
        possibly from another process) into this histogram."""
        if not state or not state.get("count"):
            return
        with self._lock:
            for key, n in (state.get("buckets") or {}).items():
                idx = int(key)
                self._buckets[idx] = self._buckets.get(idx, 0) + int(n)
            for key, ex in (state.get("exemplars") or {}).items():
                idx = int(key)
                mine = self._exemplars.get(idx)
                if mine is None or ex["value"] >= mine["value"]:
                    self._exemplars[idx] = dict(ex)
            self._count += int(state["count"])
            self._sum += float(state.get("sum", 0.0))
            if "min" in state:
                self._min = min(self._min, float(state["min"]))
            if "max" in state:
                self._max = max(self._max, float(state["max"]))

    def merge(self, other: "Histogram") -> None:
        # sequential lock holds (other's, then ours) — never nested
        self.merge_state(other.to_dict())

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._exemplars.clear()
            self._count = 0
            self._sum = 0.0
            self._min = math.inf
            self._max = -math.inf

    # -- reading -------------------------------------------------------------
    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def quantile(self, q: float) -> float:
        with self._lock:
            return self._quantile_locked(q)

    def _quantile_locked(self, q: float) -> float:
        if self._count == 0:
            return 0.0
        rank = q * self._count
        if rank <= 0:
            return self._min
        cum = 0
        for idx in sorted(self._buckets):
            c = self._buckets[idx]
            cum += c
            if cum >= rank:
                hi = bucket_upper(idx)
                lo = bucket_upper(idx - 1)
                frac = (rank - (cum - c)) / c
                v = lo + frac * (hi - lo)
                # observed extremes are exact — clamp the interpolation
                return min(max(v, self._min), self._max)
        return self._max

    def to_dict(self) -> Dict[str, Any]:
        """Serializable state: str bucket keys (JSON round-trips), plus the
        summary scalars.  ``from_dict``/``merge_state`` accept it back."""
        with self._lock:
            out: Dict[str, Any] = {
                "count": self._count,
                "sum": self._sum,
                "buckets": {str(i): c for i, c in sorted(self._buckets.items())},
            }
            if self._count:
                out["min"] = self._min
                out["max"] = self._max
            if self._exemplars:
                out["exemplars"] = {
                    str(i): dict(ex)
                    for i, ex in sorted(self._exemplars.items())
                }
            return out

    @classmethod
    def from_dict(cls, state: Dict[str, Any]) -> "Histogram":
        h = cls()
        h.merge_state(state or {})
        return h

    def summary(self) -> Dict[str, Any]:
        """The engine-snapshot distribution dict.  Superset of the seed's
        ``_dist`` keys (count/mean/p50/p95/p99/max) so every existing
        consumer keeps working; ``buckets``/``sum``/``exemplars`` make it
        mergeable and exemplar-linked downstream."""
        with self._lock:
            if self._count == 0:
                return {"count": 0}
            out = {
                "count": self._count,
                "mean": self._sum / self._count,
                "p50": self._quantile_locked(0.50),
                "p95": self._quantile_locked(0.95),
                "p99": self._quantile_locked(0.99),
                "min": self._min,
                "max": self._max,
                "sum": self._sum,
                "buckets": {str(i): c for i, c in sorted(self._buckets.items())},
            }
            if self._exemplars:
                out["exemplars"] = {
                    str(i): dict(ex)
                    for i, ex in sorted(self._exemplars.items())
                }
            return out

    def cumulative_buckets(self) -> List[Any]:
        """``[(upper_bound, cumulative_count, exemplar_or_None), ...]`` over
        the non-empty buckets, ascending — the prometheus ``_bucket`` series
        (caller appends the ``+Inf`` bound = count)."""
        with self._lock:
            out = []
            cum = 0
            for idx in sorted(self._buckets):
                cum += self._buckets[idx]
                out.append((bucket_upper(idx), cum, self._exemplars.get(idx)))
            return out


def cumulative_from_summary(summary: Dict[str, Any]) -> List[Any]:
    """``[(upper_bound, cumulative_count, exemplar_or_None), ...]`` from a
    SERIALIZED distribution dict — the prometheus ``_bucket`` series for
    snapshots that already crossed a process boundary."""
    buckets = (summary or {}).get("buckets") or {}
    exemplars = (summary or {}).get("exemplars") or {}
    out = []
    cum = 0
    for idx in sorted(int(k) for k in buckets):
        cum += int(buckets[str(idx)])
        out.append((bucket_upper(idx), cum, exemplars.get(str(idx))))
    return out


def merge_summaries(summaries: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge distribution dicts (``Histogram.summary()`` outputs, possibly
    JSON-round-tripped from other replicas) into one summary.  Entries
    without ``buckets`` (a pre-airscope snapshot, or a synthetic test dict)
    degrade gracefully: their counts still add and the merged max/p99 are
    at least as large as theirs."""
    h = Histogram()
    legacy_count = 0
    legacy_floor: Dict[str, float] = {}
    for s in summaries:
        if not s or not s.get("count"):
            continue
        if s.get("buckets"):
            h.merge_state(s)
        else:
            legacy_count += int(s["count"])
            for k in ("p50", "p95", "p99", "max", "mean"):
                if k in s:
                    legacy_floor[k] = max(legacy_floor.get(k, 0.0),
                                          float(s[k]))
    out = h.summary()
    if legacy_count:
        out["count"] = out.get("count", 0) + legacy_count
        for k, v in legacy_floor.items():
            out[k] = max(out.get(k, 0.0), v)
    return out


def exemplar_trace_id(summary: Dict[str, Any],
                      q: float = 0.99) -> Optional[str]:
    """The trace id joined to the tail of a distribution: the exemplar of
    the highest bucket at or below the q-quantile's bucket (falling back to
    the worst exemplar present).  None when the summary carries none."""
    exemplars = (summary or {}).get("exemplars") or {}
    if not exemplars:
        return None
    best_idx = max(int(i) for i in exemplars)
    return exemplars[str(best_idx)]["trace_id"]


# -- peak detection ----------------------------------------------------------

# bf16 peak FLOPs/s and HBM bytes/s per chip by PJRT device_kind (public
# spec sheets; same tables bench.py steers its on-chip headlines with)
_PEAK_FLOPS: Dict[str, float] = {
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}
_PEAK_HBM_BYTES: Dict[str, float] = {
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}
# CPU fallback: a nominal desktop-class core complex (placeholder so the
# roofline fraction is nonzero and stable in CPU tier-1/bench runs; the
# absolute value is NOT a hardware claim — the `source` field says so)
_CPU_PEAK_FLOPS = 5e11
_CPU_PEAK_BYTES = 5e10


@dataclass(frozen=True)
class PeakSpec:
    """The roofline ceiling the ledger divides by."""

    flops_per_s: float
    bytes_per_s: float
    source: str  # "env" | device_kind | "cpu-fallback"


def detect_peak() -> PeakSpec:
    """Resolve the peak spec: env overrides (``TPU_AIR_PEAK_FLOPS``,
    ``TPU_AIR_PEAK_BYTES``) win; otherwise the accelerator's device_kind
    table; otherwise CPU fallback constants."""
    env_f = os.environ.get("TPU_AIR_PEAK_FLOPS")
    env_b = os.environ.get("TPU_AIR_PEAK_BYTES")
    if env_f or env_b:
        return PeakSpec(
            flops_per_s=float(env_f) if env_f else _CPU_PEAK_FLOPS,
            bytes_per_s=float(env_b) if env_b else _CPU_PEAK_BYTES,
            source="env",
        )
    kind = ""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform == "tpu":
            kind = dev.device_kind
    except Exception:  # noqa: BLE001 — no backend at all: fall back
        kind = ""
    if kind:
        for k in sorted(_PEAK_FLOPS, key=len, reverse=True):
            if kind.startswith(k):
                return PeakSpec(
                    flops_per_s=_PEAK_FLOPS[k],
                    bytes_per_s=_PEAK_HBM_BYTES.get(k, _CPU_PEAK_BYTES),
                    source=k,
                )
    return PeakSpec(_CPU_PEAK_FLOPS, _CPU_PEAK_BYTES, source="cpu-fallback")


# -- analytic cost model -----------------------------------------------------

_DTYPE_BYTES = {
    "float32": 4, "f32": 4, "float64": 8,
    "bfloat16": 2, "bf16": 2, "float16": 2, "f16": 2,
    "int8": 1, "uint8": 1,
}


@dataclass(frozen=True)
class ProgramCost:
    """What one execution of a compiled program costs the machine."""

    flops: float
    hbm_bytes: float
    tokens: int = 0

    def scaled(self, n: float) -> "ProgramCost":
        return ProgramCost(self.flops * n, self.hbm_bytes * n,
                           int(self.tokens * n))


class LMCostModel:
    """Flops/bytes for the decoder-only LM's compiled programs.

    Geometry (``D`` d_model, ``H`` heads, ``Dh`` head_dim, ``F`` d_ff,
    ``L`` layers, ``V`` vocab, ``b`` dtype bytes) gives the exact formulas
    the unit tests hand-compute:

    * matmul params/layer: ``4*D*H*Dh`` (q,k,v,o) + ``3*D*F`` (SwiGLU
      gate/up/down); lm head compute ``D*V`` per token (params stored only
      when untied; embedding lookup adds no matmul flops).
    * linear flops/token: ``2 * (L*(4*D*H*Dh + 3*D*F) + D*V)``.
    * attention flops: ``4*H*Dh*P`` per layer for a token attending ``P``
      positions (QK^T and AV, 2 flops/MAC each).
    * KV bytes/position: ``L * 2*H*Dh * b`` (K and V, all layers).

    Norms, rotary embeddings and softmax are omitted (≪1% of the matmul
    budget at any real geometry); the model is deliberately closed-form so
    identical claims can be recomputed anywhere (arXiv:2204.06514 §4).
    """

    def __init__(self, config):
        self.d_model = int(config.d_model)
        self.n_layers = int(config.n_layers)
        self.n_heads = int(config.n_heads)
        self.head_dim = int(config.head_dim)
        self.d_ff = int(config.d_ff)
        self.vocab_size = int(config.vocab_size)
        self.tie_embeddings = bool(getattr(config, "tie_embeddings", True))
        self.dtype_bytes = _DTYPE_BYTES.get(
            str(getattr(config, "dtype", "float32")), 4)

    # -- derived geometry ----------------------------------------------------
    @property
    def matmul_params(self) -> int:
        hd = self.n_heads * self.head_dim
        return self.n_layers * (
            4 * self.d_model * hd + 3 * self.d_model * self.d_ff)

    @property
    def param_count(self) -> int:
        n = self.vocab_size * self.d_model + self.matmul_params
        if not self.tie_embeddings:
            n += self.d_model * self.vocab_size
        return n

    @property
    def param_bytes(self) -> int:
        return self.param_count * self.dtype_bytes

    @property
    def linear_flops_per_token(self) -> float:
        return 2.0 * (self.matmul_params
                      + self.d_model * self.vocab_size)

    @property
    def kv_bytes_per_position(self) -> float:
        return self.n_layers * 2 * self.n_heads * self.head_dim \
            * self.dtype_bytes

    def attention_flops(self, attended_positions: float) -> float:
        """Per ONE token attending over ``attended_positions``."""
        return self.n_layers * 4.0 * self.n_heads * self.head_dim \
            * attended_positions

    # -- program costs -------------------------------------------------------
    def decode_step_cost(self, rows: int, attended: int) -> ProgramCost:
        """One fixed-shape pool decode step: ``rows`` slots each computing
        one token and attending the COMPILED context length (the paged
        gather reads ``attended = slot_len`` positions per row regardless
        of occupancy — that is what the machine executes)."""
        flops = rows * (self.linear_flops_per_token
                        + self.attention_flops(attended))
        hbm = (self.param_bytes
               + rows * attended * self.kv_bytes_per_position   # KV read
               + rows * self.kv_bytes_per_position)             # KV write
        return ProgramCost(flops=flops, hbm_bytes=hbm, tokens=rows)

    def prefill_chunk_cost(self, chunk_len: int,
                           start_pos: int) -> ProgramCost:
        """One ``[1, chunk_len]`` prefill chunk starting at ``start_pos``:
        token ``t`` of the chunk attends ``start_pos + t + 1`` positions, so
        the chunk's attended-position total is
        ``chunk_len*start_pos + chunk_len*(chunk_len+1)/2``."""
        c = int(chunk_len)
        attended_sum = c * start_pos + c * (c + 1) / 2.0
        flops = (c * self.linear_flops_per_token
                 + self.attention_flops(attended_sum))
        hbm = (self.param_bytes
               + (start_pos + c) * self.kv_bytes_per_position   # prefix read
               + c * self.kv_bytes_per_position)                # KV write
        return ProgramCost(flops=flops, hbm_bytes=hbm, tokens=c)

    def train_step_cost(self, batch: int, seq_len: int) -> ProgramCost:
        """One train step over ``[batch, seq_len]``: backward ≈ 2× forward
        (the standard 3× multiplier), bytes ≈ 3 weight-sized streams
        (params + grads + optimizer update) plus activation KV traffic."""
        tokens = batch * seq_len
        attended_sum = batch * seq_len * (seq_len + 1) / 2.0
        fwd = (tokens * self.linear_flops_per_token
               + self.attention_flops(attended_sum))
        hbm = 3.0 * self.param_bytes \
            + 2.0 * tokens * self.kv_bytes_per_position
        return ProgramCost(flops=3.0 * fwd, hbm_bytes=hbm, tokens=tokens)


# -- the ledger --------------------------------------------------------------

# wasted-token categories the engine reports today; the set is open —
# ledger.record_tokens accepts any string (spec-decode rejections land as
# "spec_rejected" without a ledger change)
WASTED_CATEGORIES = ("shed_after_prefill", "reprefill_cache_miss",
                     "dead_stream")


class PerfLedger:
    """Per-engine accumulator: program costs → achieved rates + roofline
    fraction; token categories → goodput ratio.  Thread-safe."""

    def __init__(self, peak: Optional[PeakSpec] = None):
        self._lock = threading.Lock()
        self._peak = peak or detect_peak()
        self._programs: Dict[str, Dict[str, float]] = {}
        self._tokens: Dict[str, int] = {}

    def record_program(self, kind: str, cost: ProgramCost,
                       seconds: float, calls: int = 1) -> None:
        with self._lock:
            p = self._programs.setdefault(
                kind, {"calls": 0, "flops": 0.0, "bytes": 0.0,
                       "seconds": 0.0, "tokens": 0})
            p["calls"] += int(calls)
            p["flops"] += cost.flops
            p["bytes"] += cost.hbm_bytes
            p["seconds"] += max(float(seconds), 0.0)
            p["tokens"] += cost.tokens

    def record_tokens(self, category: str, n: int) -> None:
        """Goodput accounting: ``category`` is ``"useful"`` or a wasted
        class (``WASTED_CATEGORIES`` or any future string)."""
        if n <= 0:
            return
        with self._lock:
            self._tokens[category] = self._tokens.get(category, 0) + int(n)

    def reset(self) -> None:
        """Clear accumulators (bench steady-state windows)."""
        with self._lock:
            self._programs.clear()
            self._tokens.clear()

    def _ideal_seconds(self, flops: float, nbytes: float) -> float:
        return max(flops / self._peak.flops_per_s,
                   nbytes / self._peak.bytes_per_s)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            programs: Dict[str, Any] = {}
            tot_flops = tot_bytes = tot_seconds = 0.0
            tot_ideal = 0.0
            for kind, p in sorted(self._programs.items()):
                secs = p["seconds"]
                ideal = self._ideal_seconds(p["flops"], p["bytes"])
                programs[kind] = {
                    "calls": int(p["calls"]),
                    "flops": p["flops"],
                    "bytes": p["bytes"],
                    "seconds": secs,
                    "tokens": int(p["tokens"]),
                    "flops_per_s": p["flops"] / secs if secs else 0.0,
                    "bytes_per_s": p["bytes"] / secs if secs else 0.0,
                    "roofline_fraction": ideal / secs if secs else 0.0,
                }
                tot_flops += p["flops"]
                tot_bytes += p["bytes"]
                tot_seconds += secs
                tot_ideal += ideal
            useful = self._tokens.get("useful", 0)
            wasted = sum(n for cat, n in self._tokens.items()
                         if cat != "useful")
            total = useful + wasted
            return {
                "peak": {
                    "flops_per_s": self._peak.flops_per_s,
                    "bytes_per_s": self._peak.bytes_per_s,
                    "source": self._peak.source,
                },
                "programs": programs,
                "totals": {
                    "flops": tot_flops,
                    "bytes": tot_bytes,
                    "seconds": tot_seconds,
                    "flops_per_s": tot_flops / tot_seconds
                    if tot_seconds else 0.0,
                    "bytes_per_s": tot_bytes / tot_seconds
                    if tot_seconds else 0.0,
                    "roofline_fraction": tot_ideal / tot_seconds
                    if tot_seconds else 0.0,
                },
                "goodput": {
                    **{cat: int(n) for cat, n in sorted(self._tokens.items())},
                    "total": total,
                    "wasted": wasted,
                    "goodput_ratio": useful / total if total else 1.0,
                },
            }


def merge_ledger_snapshots(snaps: Iterable[Dict[str, Any]]
                           ) -> Dict[str, Any]:
    """Fleet view: sum program accumulators and token categories across
    ledger snapshots (rates/fractions recomputed from the sums; the peak
    of the FIRST snapshot wins — replicas share hardware)."""
    snaps = [s for s in snaps if s]
    if not snaps:
        return {}
    peak = snaps[0].get("peak") or {
        "flops_per_s": _CPU_PEAK_FLOPS, "bytes_per_s": _CPU_PEAK_BYTES,
        "source": "cpu-fallback"}
    ledger = PerfLedger(PeakSpec(peak["flops_per_s"], peak["bytes_per_s"],
                                 peak.get("source", "merged")))
    for s in snaps:
        for kind, p in (s.get("programs") or {}).items():
            ledger.record_program(
                kind,
                ProgramCost(p.get("flops", 0.0), p.get("bytes", 0.0),
                            int(p.get("tokens", 0))),
                p.get("seconds", 0.0), calls=int(p.get("calls", 1)))
        for cat, n in (s.get("goodput") or {}).items():
            if cat in ("total", "wasted", "goodput_ratio"):
                continue
            ledger.record_tokens(cat, int(n))
    return ledger.snapshot()
