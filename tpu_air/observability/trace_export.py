"""Chrome-trace / Perfetto JSON export for recorded spans.

The output loads directly in ``chrome://tracing`` and
https://ui.perfetto.dev — each span becomes one complete duration event
(``ph="X"``) with microsecond ``ts``/``dur``, laid out per process
(``pid``) and thread (``tid``), and its trace/span/parent ids carried in
``args`` so a trace can be reassembled from the export alone.  Served at
``GET /api/traces/export`` on the dashboard; written to disk by
``tools/trace_dump.py``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

from .tracing import Span, recorder

# event-phase / field names per the Trace Event Format spec (the subset
# chrome://tracing and Perfetto both accept)
_PH_COMPLETE = "X"
_PH_METADATA = "M"


def span_to_event(span: Span) -> Dict[str, Any]:
    """One span → one complete-duration trace event."""
    args: Dict[str, Any] = dict(span.attrs)
    args["trace_id"] = span.trace_id
    args["span_id"] = span.span_id
    if span.parent_id:
        args["parent_id"] = span.parent_id
    if span.status != "ok":
        args["status"] = span.status
    return {
        "name": span.name,
        "cat": span.name.split(".", 1)[0] or "span",
        "ph": _PH_COMPLETE,
        "ts": span.start_ns / 1e3,                      # microseconds
        "dur": max(span.end_ns - span.start_ns, 0) / 1e3,
        "pid": span.pid,
        "tid": span.tid,
        "args": args,
    }


def to_chrome_trace(
    spans: Optional[Iterable[Span]] = None,
    trace_id: Optional[str] = None,
) -> Dict[str, Any]:
    """Render spans (default: the whole process recorder; or one trace via
    ``trace_id``) as a Trace-Event-Format object."""
    if spans is None:
        rec = recorder()
        spans = rec.for_trace(trace_id) if trace_id else rec.recent(0)
    spans = list(spans)
    events: List[Dict[str, Any]] = []
    seen_pids = {}
    for sp in spans:
        if sp.pid not in seen_pids:
            seen_pids[sp.pid] = True
            events.append({
                "name": "process_name",
                "ph": _PH_METADATA,
                "pid": sp.pid,
                "tid": 0,
                "args": {"name": f"tpu_air pid {sp.pid}"},
            })
        events.append(span_to_event(sp))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "tpu_air airtrace", "spans": len(spans)},
    }


def export_json(
    spans: Optional[Iterable[Span]] = None,
    trace_id: Optional[str] = None,
) -> str:
    return json.dumps(to_chrome_trace(spans, trace_id=trace_id))


def export_file(
    path: str,
    spans: Optional[Iterable[Span]] = None,
    trace_id: Optional[str] = None,
) -> int:
    """Write the chrome-trace JSON to ``path``; returns the span count."""
    doc = to_chrome_trace(spans, trace_id=trace_id)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc["otherData"]["spans"]
