"""Flight-recorder postmortems: a JSON dump of observability state at the
moment something died.

When ``TPU_AIR_POSTMORTEM_DIR`` is set, the runtime calls :func:`dump` on
every worker death (core/runtime.py ``_on_worker_death`` — the same event
that turns outstanding tasks into ``WorkerCrashed`` sentinels).  The dump
captures what a human would immediately ask for and can no longer scrape
once the process group is gone:

* the crash context (worker id/pid, actor, in-flight task ids, trace ids),
* the cluster snapshot and per-engine metrics (including the perf ledger's
  roofline/goodput state),
* the SLO monitor's burn-rate state,
* recent trace summaries PLUS the full span trees of every trace the dead
  worker had in flight.

Render one with ``python tools/trace_dump.py --postmortem <file>``.

:func:`dump` never raises and is cheap to call — with the env var unset it
is a single dict lookup.  Files are written ``tmp + os.replace`` so a crash
mid-dump never leaves a truncated JSON behind.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

SCHEMA = "tpu-air-postmortem/1"
ENV_DIR = "TPU_AIR_POSTMORTEM_DIR"


def enabled() -> bool:
    return bool(os.environ.get(ENV_DIR))


def _collect(reason: str, context: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "schema": SCHEMA,
        "reason": reason,
        "unix_time": time.time(),
        "context": context or {},
    }
    # every section is best-effort: a postmortem with a missing section
    # beats no postmortem, and the recorder must never take the driver down
    try:
        from . import dashboard

        out["cluster"] = dashboard.snapshot()
    except Exception as e:  # noqa: BLE001 — best-effort section
        out["cluster"] = {"error": str(e)}
    try:
        from . import dashboard

        out["engines"] = dashboard.engine_stats()
    except Exception as e:  # noqa: BLE001 — best-effort section
        out["engines"] = {"error": str(e)}
    try:
        from . import slo as slo_mod

        mon = slo_mod.monitor()
        out["slo"] = {"slos": mon.state(), "burning": list(mon.burning())} \
            if mon is not None else None
    except Exception as e:  # noqa: BLE001 — best-effort section
        out["slo"] = {"error": str(e)}
    try:
        from . import tracing

        rec = tracing.recorder()
        out["traces"] = {
            "recorder": rec.stats(),
            "recent": tracing.trace_summaries(32),
        }
        spans: Dict[str, Any] = {}
        for tid in (context or {}).get("trace_ids") or []:
            spans[tid] = [s.to_dict() for s in rec.for_trace(tid)]
        out["traces"]["spans"] = spans
    except Exception as e:  # noqa: BLE001 — best-effort section
        out["traces"] = {"error": str(e)}
    return out


def dump(reason: str, context: Optional[Dict[str, Any]] = None,
         directory: Optional[str] = None) -> Optional[str]:
    """Write ``postmortem-<ms>.json`` and return its path, or None when the
    recorder is disabled (no ``directory`` argument and no env var) or the
    write failed.  Never raises."""
    try:
        target = directory or os.environ.get(ENV_DIR)
        if not target:
            return None
        os.makedirs(target, exist_ok=True)
        payload = _collect(reason, context)
        name = f"postmortem-{int(time.time() * 1000)}.json"
        path = os.path.join(target, name)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, default=str)
            f.flush()
            os.fsync(f.fileno())  # airlint CS002: a postmortem that can be
            # torn by the same power loss that made it worth writing is
            # useless — fsync before the seal (still inside the outer
            # try, so the never-raises guarantee holds)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — the flight recorder must never crash its host
        return None


def load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != SCHEMA:
        raise ValueError(f"not a tpu-air postmortem (schema={data.get('schema')!r})")
    return data
