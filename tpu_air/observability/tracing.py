"""airtrace — span-based distributed tracing for the tpu_air stack.

Every observability surface before this module was point-in-time
(``EngineMetrics`` gauges, ``/api/*`` snapshots, ``step_timer`` summaries).
This module adds the *per-request timeline*: W3C-style trace/span IDs, a
process-local lock-protected ring-buffer :class:`SpanRecorder`, and context
propagation across every boundary the stack has —

* HTTP proxy → replica actor: ``serve/proxy.py`` opens a root span per
  request (honoring an inbound ``traceparent`` header) and returns the trace
  ID in a response header;
* driver → worker: ``core/remote.py`` captures the active context into each
  ``_TaskSpec`` / actor-method payload, ``core/runtime.py`` opens a
  worker-side span around execution and ships finished spans back to the
  driver recorder piggybacked on the ``done`` control message;
* engine internals: ``engine/scheduler.py`` + ``engine/engine.py`` stamp
  queue-wait / prefill / per-slot decode residency and emit the request's
  span tree at retirement (no hot-loop work — see "cost story" below);
* train: ``train/session.py`` emits per-iteration spans so ``step_timer``
  numbers land in the same timeline, and ``profiler.profile_trace`` records
  a span carrying its xplane log dir for on-chip correlation.

Cost story — **zero-cost when off** (the default): the module-level flag is
read by :func:`enabled`; every instrumentation site either guards on it or
calls :func:`span`, which returns the singleton :data:`_NOOP` span without
allocating.  No span objects, no timestamps, no lock traffic on the disabled
path.  Enable with ``TPU_AIR_TRACE=1`` in the environment (inherited by
worker processes) or :func:`enable` at runtime.

Export: :mod:`tpu_air.observability.trace_export` renders the recorder to
Chrome-trace/Perfetto JSON (``/api/traces/export`` on the dashboard,
``tools/trace_dump.py`` from the CLI).  See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import contextvars
import os
import re
import secrets
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "Span",
    "SpanContext",
    "SpanRecorder",
    "current_context",
    "current_propagation",
    "current_trace_id",
    "disable",
    "enable",
    "enabled",
    "extract_traceparent",
    "format_traceparent",
    "new_span_id",
    "new_trace_id",
    "now_ns",
    "record_span",
    "recorder",
    "span",
    "task_span",
]

_ENV_FLAG = "TPU_AIR_TRACE"

_enabled = os.environ.get(_ENV_FLAG, "0") == "1"


def enabled() -> bool:
    """Fast global check — instrumentation sites guard on this."""
    return _enabled


def enable() -> None:
    """Turn tracing on for this process AND export the flag to the
    environment so worker processes spawned from now on inherit it
    (``Runtime._spawn_worker`` ships the driver's current environ)."""
    global _enabled
    _enabled = True
    os.environ[_ENV_FLAG] = "1"


def disable() -> None:
    global _enabled
    _enabled = False
    os.environ[_ENV_FLAG] = "0"


def _sync_from_env() -> None:
    """Re-read the env flag.  Called by worker processes after the driver's
    environ has been applied (forkserver children otherwise keep the flag
    frozen at preload-import time)."""
    global _enabled
    _enabled = os.environ.get(_ENV_FLAG, "0") == "1"


def now_ns() -> int:
    """Span timestamp base: wall-clock ns (consistent across the host's
    processes, which is what cross-process trace assembly needs)."""
    return time.time_ns()


def new_trace_id() -> str:
    return secrets.token_hex(16)  # 32 hex chars, W3C trace-id width


def new_span_id() -> str:
    return secrets.token_hex(8)  # 16 hex chars, W3C parent-id width


# ---------------------------------------------------------------------------
# context + propagation
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SpanContext:
    """The propagatable part of a span: (trace_id, span_id)."""

    trace_id: str
    span_id: str

    def to_dict(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, str]]) -> Optional["SpanContext"]:
        if not d:
            return None
        trace_id, span_id = d.get("trace_id"), d.get("span_id")
        if not trace_id or not span_id:
            return None
        return cls(trace_id, span_id)


_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def format_traceparent(ctx: SpanContext) -> str:
    """W3C ``traceparent`` header value (version 00, sampled)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def extract_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a ``traceparent`` header; None on absence or malformation
    (a bad inbound header must never fail the request)."""
    if not header:
        return None
    m = _TRACEPARENT.match(header.strip().lower())
    if m is None or m.group(1) == "ff":
        return None
    trace_id, span_id = m.group(2), m.group(3)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


_current: contextvars.ContextVar[Optional[SpanContext]] = contextvars.ContextVar(
    "tpu_air_trace_context", default=None
)


def current_context() -> Optional[SpanContext]:
    return _current.get()


def current_trace_id() -> Optional[str]:
    """Trace id of the active span, if any (read regardless of the enable
    flag so error paths inside a force-recorded task span still tag)."""
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None


def current_propagation() -> Optional[Dict[str, str]]:
    """The carrier dict to attach to an outbound task/actor payload — None
    when tracing is off or no span is active (the common case; callers
    attach nothing and the remote side pays nothing)."""
    if not _enabled:
        return None
    ctx = _current.get()
    return None if ctx is None else ctx.to_dict()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


@dataclass
class Span:
    """One finished-or-live span.  Used as a context manager by
    :func:`span`; plain records built by :func:`record_span` never enter
    the context machinery."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    pid: int = 0
    tid: int = 0
    status: str = "ok"
    attrs: Dict[str, Any] = field(default_factory=dict)
    _token: Optional[contextvars.Token] = field(
        default=None, repr=False, compare=False
    )

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def set_status(self, status: str) -> None:
        self.status = status

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "pid": self.pid,
            "tid": self.tid,
            "status": self.status,
            "attrs": dict(self.attrs),
        }

    # -- context-manager protocol -------------------------------------------
    def __enter__(self) -> "Span":
        self._token = _current.set(self.context)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end_ns = now_ns()
        if exc_type is not None and self.status == "ok":
            self.status = f"error:{exc_type.__name__}"
        if self._token is not None:
            _current.reset(self._token)
            self._token = None
        _recorder.record(self)
        return False


class _NoopSpan:
    """Singleton returned by :func:`span` on the disabled path — every
    method is a no-op, ``trace_id`` is None, and nothing is allocated."""

    __slots__ = ()

    trace_id = None
    span_id = None
    parent_id = None
    status = "ok"

    @property
    def context(self):
        return None

    def set_attr(self, key, value):
        pass

    def set_status(self, status):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


def span(name: str, parent: Optional[SpanContext] = None,
         attrs: Optional[Dict[str, Any]] = None, force: bool = False):
    """Open a span as a context manager.

    Parent resolution: explicit ``parent`` wins, else the ambient context
    (contextvar), else this span roots a fresh trace.  While the span is
    live it IS the ambient context, so nested :func:`span` calls and
    outbound ``.remote`` payload capture parent under it.

    Disabled path: returns :data:`_NOOP` (no allocation).  ``force=True``
    records even when the flag is off — used for cross-process continuation
    where the *sender* decided the request is traced (see
    :func:`task_span`).
    """
    if not _enabled and not force:
        return _NOOP
    pctx = parent if parent is not None else _current.get()
    return Span(
        name=name,
        trace_id=pctx.trace_id if pctx is not None else new_trace_id(),
        span_id=new_span_id(),
        parent_id=pctx.span_id if pctx is not None else None,
        start_ns=now_ns(),
        pid=os.getpid(),
        tid=threading.get_ident() & 0xFFFFFFFF,
        attrs=dict(attrs) if attrs else {},
    )


def task_span(name: str, carrier: Optional[Dict[str, str]]):
    """Continue a trace across a process boundary: ``carrier`` is the dict
    produced by :func:`current_propagation` on the sending side.  A non-None
    carrier means the sender had tracing on, so the span records even if
    this process's own flag is off (fork/forkserver timing must not drop
    the worker half of a trace)."""
    ctx = SpanContext.from_dict(carrier)
    if ctx is None:
        return span(name)  # falls through to _NOOP when disabled
    return span(name, parent=ctx, force=True)


def record_span(
    name: str,
    *,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    start_ns: int,
    end_ns: int,
    attrs: Optional[Dict[str, Any]] = None,
    status: str = "ok",
) -> Span:
    """Retroactively record a span from timestamps collected elsewhere (the
    engine's retirement-time emission path).  Returns the span so callers
    can chain children under its ``span_id``."""
    sp = Span(
        name=name,
        trace_id=trace_id or new_trace_id(),
        span_id=new_span_id(),
        parent_id=parent_id,
        start_ns=start_ns,
        end_ns=end_ns,
        pid=os.getpid(),
        tid=threading.get_ident() & 0xFFFFFFFF,
        status=status,
        attrs=dict(attrs) if attrs else {},
    )
    _recorder.record(sp)
    return sp


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


class SpanRecorder:
    """Process-local lock-protected ring buffer of finished spans.

    The driver's recorder is what ``/api/traces`` serves; worker recorders
    are drained into the ``done`` control message and folded into the
    driver's (core/runtime.py), so the dashboard sees one merged timeline.
    """

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._buf: Deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._total = 0

    def record(self, span_: Span) -> None:
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(span_)
            self._total += 1

    def record_many(self, spans: List[Span]) -> None:
        with self._lock:
            for sp in spans:
                if len(self._buf) == self.capacity:
                    self._dropped += 1
                self._buf.append(sp)
                self._total += 1

    def drain(self) -> List[Span]:
        """Remove and return everything buffered (worker → driver ship)."""
        # airlint: disable=CC001 — deliberate lock-free emptiness probe:
        # a racing record() only delays that span to the next drain
        if not self._buf:
            return []
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            return out

    def recent(self, limit: int = 256) -> List[Span]:
        with self._lock:
            if limit <= 0 or limit >= len(self._buf):
                return list(self._buf)
            return list(self._buf)[-limit:]

    def for_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self._buf if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0
            self._total = 0

    def __len__(self) -> int:
        return len(self._buf)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "buffered": len(self._buf),
                "capacity": self.capacity,
                "recorded_total": self._total,
                "dropped": self._dropped,
            }


_recorder = SpanRecorder()


def recorder() -> SpanRecorder:
    return _recorder


def drain_if_any() -> Optional[List[Span]]:
    """Worker-side helper for the ``done`` message: the buffered spans, or
    None (the common case — one truthiness check, no lock) so the control
    message stays a 3-tuple when there is nothing to ship."""
    if not _recorder._buf:
        return None
    return _recorder.drain() or None


def trace_summaries(limit: int = 64) -> List[Dict[str, Any]]:
    """Recent traces grouped from the buffer, newest first: id, root name,
    span count, wall span.  The ``/api/traces`` listing payload."""
    by_trace: Dict[str, List[Span]] = {}
    for sp in _recorder.recent(0):
        by_trace.setdefault(sp.trace_id, []).append(sp)
    out = []
    for trace_id, spans in by_trace.items():
        roots = [s for s in spans if s.parent_id is None]
        start = min(s.start_ns for s in spans)
        end = max(s.end_ns for s in spans)
        name = roots[0].name if roots else spans[0].name
        out.append({
            "trace_id": trace_id,
            "root": name,
            "spans": len(spans),
            "start_ns": start,
            "duration_ms": (end - start) / 1e6,
            "errors": sum(1 for s in spans if s.status != "ok"),
        })
    out.sort(key=lambda t: -t["start_ns"])
    return out[:limit]
