"""airwatch — fleet time-series plane: history, tenant costs, anomalies.

Three pieces on top of the ring-buffer store (timeseries.py):

* :class:`FleetScraper` — a driver-side daemon thread that, every
  ``interval_s``, collects every replica's ``engine_stats`` snapshot (the
  same ``DeploymentHandle`` path the dashboard and admission use), the
  serve plane's ``/-/stats`` control state, and the installed SLO
  monitor's burn state; merges the engine snapshots with the airscope
  histogram-merge machinery (``merge_snapshots``) so fleet quantiles are
  computed over SAMPLES, not max-of-p99s; and feeds the store, the cost
  ledger and the anomaly detector from one pass.

* :class:`CostLedger` — per-tenant cost attribution keyed by
  ``adapter_id`` (``None`` ⇒ the ``"default"`` base-model tenant).  Per
  scrape interval it attributes tokens prefilled/decoded, chip-seconds
  (replica chip count × interval, split by busy fraction and then by each
  tenant's token share), KV-page-seconds resident, migrated pages, sheds
  and quota rejections — cumulative engine/admission counters in, rates
  and totals out, counter resets clamped.  Surfaced as the
  ``tpu_air_tenant_*`` prometheus families, ``/api/tenants``, and the
  ``chip_seconds_per_1k_tokens`` derived headline bench_serve gates on.

* :class:`AnomalyDetector` — online EWMA mean + EWMA absolute deviation
  (a streaming stand-in for median/MAD) over the 1s tier; a sample whose
  robust z-score clears its metric's SEEDED threshold emits a structured
  ``watch.anomaly`` event carrying the metric, window, z-score and the
  worst trace exemplar from the matching airscope histogram bucket (the
  join key into ``/api/traces?trace_id=``).  The detector feeds the
  autoscaler as a third scale signal beside queue depth and SLO burn
  (serve/autoscaler.py), and is queryable at ``/api/watch`` plus
  ``tools/watch_dump.py``.

Zero-cost-off, same contract as airtrace/airfault: no :func:`install`
means no scraper thread exists and every hook is one module-global read
(:func:`enabled`).  The clock is injectable and detector thresholds
derive from ``seed`` alone, so the chaos lane's anomaly assertions are
deterministic under ``TPU_AIR_FAULT_SEED``.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from .perf import exemplar_trace_id
from .timeseries import DEFAULT_TIERS, TimeSeriesStore

__all__ = [
    "AnomalyDetector",
    "CostLedger",
    "DEFAULT_TENANT",
    "FleetScraper",
    "Watch",
    "WatchConfig",
    "anomalous",
    "clear",
    "current",
    "enabled",
    "install",
]

#: the base-model tenant every request without an ``adapter_id`` bills to
DEFAULT_TENANT = "default"

#: metrics the scraper derives from the merged fleet snapshot each tick,
#: and whether the detector sees the raw gauge or the per-tick delta of a
#: cumulative counter (negative deltas are counter resets: state clears,
#: nothing fires)
_FLEET_METRICS: Tuple[Tuple[str, str], ...] = (
    ("fleet.engines", "gauge"),
    ("fleet.queue_depth", "gauge"),
    ("fleet.slot_occupancy", "gauge"),
    ("fleet.tokens_per_s", "gauge"),
    ("fleet.ttft_p99_s", "gauge"),
    ("fleet.requests_completed", "counter"),
    ("fleet.requests_rejected", "counter"),
)
_RECOVERY_METRICS: Tuple[Tuple[str, str], ...] = (
    ("recovery.preemptions", "counter"),
    ("recovery.migration_fallbacks", "counter"),
    ("recovery.journal_evicted_live", "counter"),
    ("recovery.replays", "counter"),
)


@dataclass(frozen=True)
class WatchConfig:
    """Dials for one process's airwatch plane.

    * ``interval_s`` — scrape period (the 1s tier's natural cadence).
    * ``tiers`` — ``(step_s, capacity)`` downsampling tiers for the store.
    * ``seed`` — anomaly-threshold seed; the chaos lane pins it to
      ``TPU_AIR_FAULT_SEED`` so a red run replays bit-identically.
    * ``ewma_alpha`` — smoothing for the detector's mean/deviation (the
      effective window is ``interval_s / ewma_alpha``).
    * ``z_threshold`` — base robust-z trip point; each metric's actual
      threshold is this times a seeded jitter in ``[1, 1.5)`` (no two
      metrics share an exact trip point, and reruns agree).
    * ``warmup`` — samples per metric before the detector may fire.
    * ``anomaly_hold_s`` — per-metric refire spacing, and how long an
      event keeps :func:`anomalous` (the autoscaler signal) hot.
    * ``stale_after_s`` — replica snapshots older than this drop out of
      the scraper's cache (``None`` ⇒ ``3 × interval_s``); between one
      interval and the TTL they carry a ``stale_s`` age-mark.
    * ``max_events`` — anomaly/note ring size.
    """

    interval_s: float = 1.0
    tiers: Tuple[Tuple[float, int], ...] = DEFAULT_TIERS
    seed: int = 0
    ewma_alpha: float = 0.2
    z_threshold: float = 4.0
    warmup: int = 8
    anomaly_hold_s: float = 5.0
    stale_after_s: Optional[float] = None
    max_events: int = 256

    def __post_init__(self):
        if self.interval_s <= 0 or not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"bad watch config: {self}")
        if self.z_threshold <= 0 or self.warmup < 2:
            raise ValueError(f"bad watch config: {self}")

    @property
    def ttl_s(self) -> float:
        return (self.stale_after_s if self.stale_after_s is not None
                else 3.0 * self.interval_s)


class AnomalyDetector:
    """Online EWMA + robust z-score over one stream of samples per metric.

    The deviation estimate is an EWMA of absolute residuals — a streaming
    approximation of MAD that a single outlier moves by at most ``alpha``
    of itself, which is what keeps the spike that FIRES from also wrecking
    the baseline it fired against.  Thresholds are seeded per metric
    (``random.Random(f"{seed}:{metric}")`` — str seeding is hashed with
    SHA-512, stable across processes), so two runs of the same seed trip
    at identical points.  Thread-safe; nothing under the lock blocks."""

    def __init__(self, config: Optional[WatchConfig] = None,
                 now: Callable[[], float] = time.monotonic):
        self.config = config or WatchConfig()
        self._now = now
        self._lock = threading.Lock()
        # metric -> [mean, abs-dev ewma, samples seen, last fire ts]
        self._state: Dict[str, list] = {}

    def threshold_for(self, metric: str) -> float:
        cfg = self.config
        jitter = random.Random(f"{cfg.seed}:{metric}").uniform(0.0, 0.5)
        return cfg.z_threshold * (1.0 + jitter)

    def reset(self, metric: str) -> None:
        """Counter reset (an engine restarted): forget the baseline so the
        discontinuity never fires."""
        with self._lock:
            self._state.pop(metric, None)

    def observe(self, metric: str, value: float,
                ts: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Feed one sample; returns a ``watch.anomaly`` event dict when it
        clears the metric's seeded threshold after warmup, else None."""
        cfg = self.config
        v = float(value)
        t = self._now() if ts is None else float(ts)
        threshold = self.threshold_for(metric)
        event = None
        with self._lock:
            st = self._state.get(metric)
            if st is None:
                st = [v, 0.0, 0, -1e18]
                self._state[metric] = st
            mean, dev, n, fired_at = st
            if n >= cfg.warmup:
                # robust z against the PRE-update baseline; the deviation
                # floor keeps a dead-flat warmup (dev == 0) from dividing
                # to infinity while still letting a clean step change fire
                floor = max(1e-3 * max(1.0, abs(mean)), 1e-9)
                z = abs(v - mean) / max(dev, floor)
                if (z >= threshold
                        and t - fired_at >= cfg.anomaly_hold_s):
                    st[3] = t
                    event = {
                        "event": "watch.anomaly",
                        "metric": metric,
                        "ts": t,
                        "value": v,
                        "mean": mean,
                        "deviation": max(dev, floor),
                        "zscore": z,
                        "threshold": threshold,
                        "window_s": round(cfg.interval_s / cfg.ewma_alpha, 3),
                    }
            # EWMA updates AFTER the test — the sample that fires must not
            # have already pulled the baseline toward itself
            st[0] = mean + cfg.ewma_alpha * (v - mean)
            st[1] = ((1.0 - cfg.ewma_alpha) * dev
                     + cfg.ewma_alpha * abs(v - mean))
            st[2] = n + 1
        return event

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                m: {"mean": st[0], "deviation": st[1], "samples": st[2],
                    "threshold": self.threshold_for(m)}
                for m, st in sorted(self._state.items())
            }


def _tenant_zero() -> Dict[str, float]:
    return {
        "tokens_prefilled": 0.0,
        "tokens_decoded": 0.0,
        "requests_completed": 0.0,
        "chip_seconds": 0.0,
        "kv_page_seconds": 0.0,
        "migrated_pages": 0.0,
        "admitted": 0.0,
        "sheds": 0.0,
        "quota_rejected": 0.0,
    }


class CostLedger:
    """Per-tenant cost attribution from cumulative fleet counters.

    :meth:`update` takes the CURRENT fleet-cumulative per-tenant counters
    (the merged engine ``tenants`` section + the admission controllers'
    per-tenant outcome counters), differences them against the previous
    scrape (negative deltas — a replica died or restarted — clamp to
    zero), and attributes the interval's chip-seconds: each engine
    contributes ``chips × dt``, split into busy (``slot_occupancy /
    num_slots``) and idle; busy chip-seconds divide across tenants by
    their share of the interval's tokens, idle accrues unattributed.
    Thread-safe; pure arithmetic under the lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._totals: Dict[str, Dict[str, float]] = {}
        self._idle_chip_seconds = 0.0
        self._chip_seconds_seen = 0.0
        self._last_engine: Dict[str, Dict[str, float]] = {}
        self._last_admission: Dict[str, Dict[str, float]] = {}
        self._intervals = 0

    @staticmethod
    def _deltas(cur: Dict[str, Dict[str, Any]],
                prev: Dict[str, Dict[str, float]],
                keys: Tuple[str, ...]) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for tenant, counters in cur.items():
            base = prev.get(tenant) or {}
            out[tenant] = {
                k: max(0.0, float(counters.get(k, 0.0))
                       - float(base.get(k, 0.0)))
                for k in keys
            }
        return out

    def update(self, engine_tenants: Dict[str, Dict[str, Any]],
               admission_tenants: Dict[str, Dict[str, Any]],
               busy_chip_seconds: float, total_chip_seconds: float) -> None:
        """Fold one scrape interval into the ledger (see class doc)."""
        eng_keys = ("tokens_prefilled", "tokens_decoded",
                    "requests_completed", "kv_page_seconds",
                    "migrated_pages")
        adm_keys = ("admitted", "sheds", "quota_rejected")
        with self._lock:
            eng_d = self._deltas(engine_tenants or {}, self._last_engine,
                                 eng_keys)
            adm_d = self._deltas(admission_tenants or {},
                                 self._last_admission, adm_keys)
            token_d = {t: d["tokens_prefilled"] + d["tokens_decoded"]
                       for t, d in eng_d.items()}
            tokens_total = sum(token_d.values())
            busy = max(0.0, float(busy_chip_seconds))
            for tenant, d in eng_d.items():
                tot = self._totals.setdefault(tenant, _tenant_zero())
                for k in eng_keys:
                    tot[k] += d[k]
                if tokens_total > 0:
                    tot["chip_seconds"] += (busy * token_d[tenant]
                                            / tokens_total)
            for tenant, d in adm_d.items():
                tot = self._totals.setdefault(tenant, _tenant_zero())
                for k in adm_keys:
                    tot[k] += d[k]
            attributed = busy if tokens_total > 0 else 0.0
            self._chip_seconds_seen += max(0.0, float(total_chip_seconds))
            self._idle_chip_seconds += max(
                0.0, float(total_chip_seconds) - attributed)
            self._last_engine = {
                t: {k: float((c or {}).get(k, 0.0)) for k in eng_keys}
                for t, c in (engine_tenants or {}).items()}
            self._last_admission = {
                t: {k: float((c or {}).get(k, 0.0)) for k in adm_keys}
                for t, c in (admission_tenants or {}).items()}
            self._intervals += 1

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready ledger state: per-tenant totals with the derived
        ``chip_seconds_per_1k_tokens`` and token share, plus the fleet
        headline (total attributed chip-seconds per 1k attributed
        tokens)."""
        with self._lock:
            tenants = {t: dict(v) for t, v in self._totals.items()}
            idle = self._idle_chip_seconds
            seen = self._chip_seconds_seen
            intervals = self._intervals
        tokens_total = sum(v["tokens_prefilled"] + v["tokens_decoded"]
                           for v in tenants.values())
        chip_total = sum(v["chip_seconds"] for v in tenants.values())
        for v in tenants.values():
            toks = v["tokens_prefilled"] + v["tokens_decoded"]
            v["tokens_total"] = toks
            v["token_share"] = (toks / tokens_total) if tokens_total else 0.0
            v["chip_seconds_per_1k_tokens"] = (
                1000.0 * v["chip_seconds"] / toks if toks else 0.0)
        # lane split: the batch runner bills under ``batch:<job_id>``
        # tenants, so summing over that prefix separates offline soak from
        # interactive serving — the "was borrowing actually free?" number
        batch_chip = sum(v["chip_seconds"] for t, v in tenants.items()
                         if t.startswith("batch:"))
        batch_tokens = sum(v["tokens_total"] for t, v in tenants.items()
                           if t.startswith("batch:"))
        return {
            "tenants": tenants,
            "idle_chip_seconds": idle,
            "chip_seconds_seen": seen,
            "intervals": intervals,
            "headline": {
                "tokens_total": tokens_total,
                "chip_seconds_attributed": chip_total,
                "chip_seconds_per_1k_tokens": (
                    1000.0 * chip_total / tokens_total if tokens_total
                    else 0.0),
                "batch_chip_seconds": batch_chip,
                "interactive_chip_seconds": chip_total - batch_chip,
                "batch_tokens": batch_tokens,
                "batch_chip_share": (batch_chip / chip_total
                                     if chip_total else 0.0),
            },
        }


def _default_engine_source() -> Dict[str, Dict[str, Any]]:
    """Driver-local engine registry + every serve replica's snapshot — the
    same two feeds the dashboard's ``/api/engines`` merges."""
    out: Dict[str, Dict[str, Any]] = {}
    try:
        from tpu_air.engine.metrics import snapshot_all
        out.update(snapshot_all())
    except Exception:  # noqa: BLE001 — engine package optional (no jax)
        pass
    try:
        from tpu_air.serve.proxy import replica_engine_stats
        out.update(replica_engine_stats())
    except Exception:  # noqa: BLE001 — serve package optional / not running
        pass
    return out


def _default_serve_source() -> Dict[str, Any]:
    try:
        from tpu_air.serve.proxy import serve_control_stats
        return serve_control_stats()
    except Exception:  # noqa: BLE001 — serve package optional / not running
        return {}


def _slo_burning() -> List[str]:
    try:
        from . import slo as slo_mod
        mon = slo_mod.monitor()
        return list(mon.burning()) if mon is not None else []
    except Exception:  # noqa: BLE001 — burn state is best-effort decoration
        return []


class Watch:
    """One process's airwatch plane: store + ledger + detector + the
    scraper's snapshot cache, all behind :meth:`scrape_once`.

    ``engine_source`` / ``serve_source`` are injectable (the unit tests
    drive synthetic fleets on a fake clock); the defaults read the same
    paths the dashboard does.  The replica-snapshot cache is what fixes
    dashboard merge staleness: entries older than one interval carry a
    ``stale_s`` age-mark, entries older than ``config.ttl_s`` are dropped
    — a dead replica's gauges stop haunting ``/api/engines`` and
    ``/metrics`` one TTL after it stops answering scrapes."""

    def __init__(self, config: Optional[WatchConfig] = None, *,
                 engine_source: Optional[Callable[[], Dict[str, Any]]] = None,
                 serve_source: Optional[Callable[[], Dict[str, Any]]] = None,
                 now: Callable[[], float] = time.monotonic):
        self.config = config or WatchConfig()
        self._now = now
        self._engine_source = engine_source or _default_engine_source
        self._serve_source = serve_source or _default_serve_source
        self.store = TimeSeriesStore(tiers=self.config.tiers, now=now)
        self.ledger = CostLedger()
        self.detector = AnomalyDetector(self.config, now=now)
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = deque(
            maxlen=self.config.max_events)
        self._snap_cache: Dict[str, Tuple[float, Dict[str, Any]]] = {}
        self._counters: Dict[str, float] = {}  # last cumulative per metric
        self._last_scrape_ts: Optional[float] = None
        self._last_exemplar: Optional[str] = None
        self.scrapes = 0
        self.anomalies = 0
        self._scraper: Optional["FleetScraper"] = None

    # -- the scrape ----------------------------------------------------------
    def scrape_once(self) -> Dict[str, Any]:
        """One collection pass: scrape (outside any lock), merge, record,
        attribute, detect.  Returns the merged fleet snapshot."""
        from tpu_air.engine.metrics import merge_snapshots

        ts = self._now()
        try:
            snaps = dict(self._engine_source() or {})
        except Exception:  # noqa: BLE001 — a failed scrape must not kill the loop
            snaps = {}
        try:
            serve = dict(self._serve_source() or {})
        except Exception:  # noqa: BLE001 — a failed scrape must not kill the loop
            serve = {}
        burning = _slo_burning()

        ttl = self.config.ttl_s
        with self._lock:
            for key, snap in snaps.items():
                if snap:
                    self._snap_cache[key] = (ts, snap)
            for key in [k for k, (at, _) in self._snap_cache.items()
                        if ts - at > ttl]:
                del self._snap_cache[key]
            cached = {k: s for k, (_, s) in self._snap_cache.items()}
            dt = (ts - self._last_scrape_ts
                  if self._last_scrape_ts is not None
                  else self.config.interval_s)
            self._last_scrape_ts = ts
            self.scrapes += 1

        merged = merge_snapshots(cached)
        self._record_fleet(merged, serve, snaps, burning, ts)
        self._attribute_costs(merged, serve, snaps, max(dt, 1e-9))
        return merged

    def _record_fleet(self, merged: Dict[str, Any], serve: Dict[str, Any],
                      fresh: Dict[str, Any], burning: List[str],
                      ts: float) -> None:
        ttft = merged.get("ttft_s") or {}
        exemplar = exemplar_trace_id(ttft)
        if exemplar is not None:
            with self._lock:
                self._last_exemplar = exemplar
        values: Dict[str, float] = {
            "fleet.engines": float(len([s for s in fresh.values()
                                        if s and "num_slots" in s])),
            "fleet.queue_depth": float(merged.get("queue_depth", 0)),
            "fleet.slot_occupancy": float(merged.get("slot_occupancy", 0)),
            "fleet.tokens_per_s": float(merged.get("tokens_per_s", 0.0)),
            "fleet.requests_completed": float(
                merged.get("requests_completed", 0)),
            "fleet.requests_rejected": float(
                merged.get("requests_rejected", 0)),
            "fleet.slo_burning": float(len(burning)),
        }
        if ttft.get("count"):
            values["fleet.ttft_p99_s"] = float(ttft.get("p99", 0.0))
        recovery = serve.get("recovery") or {}
        for metric, _kind in _RECOVERY_METRICS:
            key = metric.split(".", 1)[1]
            if key in recovery:
                values[metric] = float(recovery[key])
        for metric, value in values.items():
            self.store.record(metric, value, ts=ts)
        for metric, kind in (_FLEET_METRICS + _RECOVERY_METRICS
                             + (("fleet.slo_burning", "gauge"),)):
            if metric not in values:
                continue
            v = values[metric]
            if kind == "counter":
                with self._lock:
                    prev = self._counters.get(metric)
                    self._counters[metric] = v
                if prev is None:
                    continue
                if v < prev:  # counter reset: re-baseline, never fire
                    self.detector.reset(metric)
                    continue
                v = v - prev
            event = self.detector.observe(metric, v, ts=ts)
            if event is not None:
                with self._lock:
                    event["trace_exemplar"] = self._last_exemplar
                    self._events.append(event)
                    self.anomalies += 1

    def _attribute_costs(self, merged: Dict[str, Any],
                         serve: Dict[str, Any], fresh: Dict[str, Any],
                         dt: float) -> None:
        busy = total = 0.0
        for snap in fresh.values():
            if not snap or "num_slots" not in snap:
                continue  # synthetic partial snapshots carry no capacity
            chips = float((snap.get("topology") or {}).get("mesh_devices", 1))
            slots = max(int(snap.get("num_slots", 0)), 1)
            total += chips * dt
            busy += chips * dt * min(
                1.0, float(snap.get("slot_occupancy", 0)) / slots)
        admission: Dict[str, Dict[str, float]] = {}
        for route, ctl in serve.items():
            if not isinstance(ctl, dict):
                continue
            for tenant, c in ((ctl.get("admission") or {}).get("tenants")
                              or {}).items():
                agg = admission.setdefault(
                    tenant, {"admitted": 0.0, "sheds": 0.0,
                             "quota_rejected": 0.0})
                agg["admitted"] += float(c.get("admitted", 0))
                agg["sheds"] += float(c.get("shed", 0))
                agg["quota_rejected"] += float(c.get("quota_shed", 0))
        self.ledger.update(merged.get("tenants") or {}, admission,
                           busy_chip_seconds=busy, total_chip_seconds=total)

    # -- hooks / queries -----------------------------------------------------
    def note(self, kind: str, **attrs: Any) -> None:
        """Record a structured non-anomaly event (e.g. the preemption
        watcher's recovery notes) into the same ring ``/api/watch``
        serves."""
        event = {"event": kind, "ts": self._now(), **attrs}
        with self._lock:
            self._events.append(event)

    def events(self, limit: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events)
        if kind is not None:
            out = [e for e in out if e.get("event") == kind]
        if limit is not None:
            out = out[-int(limit):]
        return out

    def anomalous(self, hold_s: Optional[float] = None) -> List[str]:
        """Metrics with a ``watch.anomaly`` inside the hold window — the
        autoscaler's third scale signal."""
        hold = self.config.anomaly_hold_s if hold_s is None else hold_s
        horizon = self._now() - hold
        with self._lock:
            return sorted({
                e["metric"] for e in self._events
                if e.get("event") == "watch.anomaly"
                and e.get("ts", 0.0) >= horizon})

    def cached_engine_stats(self) -> Dict[str, Dict[str, Any]]:
        """The scraper's TTL-governed view of replica snapshots: fresh
        entries verbatim, entries older than one interval age-marked with
        ``stale_s``, entries past ``config.ttl_s`` already evicted by the
        scrape loop (and re-filtered here for reads between scrapes)."""
        now = self._now()
        ttl = self.config.ttl_s
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for key, (at, snap) in self._snap_cache.items():
                age = now - at
                if age > ttl:
                    continue
                if age > self.config.interval_s:
                    snap = dict(snap)
                    snap["stale_s"] = round(age, 3)
                out[key] = snap
        return out

    def payload(self) -> Dict[str, Any]:
        """The /api/watch JSON body."""
        with self._lock:
            scrapes = self.scrapes
            anomalies = self.anomalies
            last_ts = self._last_scrape_ts
            events = list(self._events)
        return {
            "enabled": True,
            "config": {
                "interval_s": self.config.interval_s,
                "seed": self.config.seed,
                "z_threshold": self.config.z_threshold,
                "warmup": self.config.warmup,
                "ttl_s": self.config.ttl_s,
            },
            "scrapes": scrapes,
            "last_scrape_ts": last_ts,
            "anomalies": anomalies,
            "events": events,
            "detector": self.detector.stats(),
            "store": self.store.stats(),
            "metrics": self.store.metrics(),
        }

    # -- scraper lifecycle ---------------------------------------------------
    def start_scraper(self) -> "FleetScraper":
        with self._lock:
            if self._scraper is None:
                self._scraper = FleetScraper(self)
            scraper = self._scraper
        scraper.start()
        return scraper

    def stop_scraper(self) -> None:
        with self._lock:
            scraper = self._scraper
            self._scraper = None
        if scraper is not None:
            scraper.stop()


class FleetScraper:
    """The collection loop: a driver-side daemon thread calling
    :meth:`Watch.scrape_once` every ``interval_s`` (Event.wait as the
    timer, so stop() interrupts a sleeping loop immediately — the same
    pattern as the autoscaler and preemption watcher).  All scraping I/O
    happens inside ``scrape_once`` outside any lock."""

    def __init__(self, watch: Watch):
        self._watch = watch
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetScraper":
        with self._lock:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="airwatch-scraper")
                self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:
            thread.join(timeout=10.0)

    @property
    def running(self) -> bool:
        with self._lock:
            return self._thread is not None

    def _loop(self) -> None:
        while not self._stop.wait(self._watch.config.interval_s):
            try:
                self._watch.scrape_once()
            except Exception:  # noqa: BLE001 — one bad scrape must not end history
                pass


# ---------------------------------------------------------------------------
# process-wide registry (zero-cost-off: every hook is one global read)
# ---------------------------------------------------------------------------

_registry_lock = threading.Lock()
_watch: Optional[Watch] = None


def enabled() -> bool:
    """Fast global check — hooks guard on this before doing any work."""
    return _watch is not None


def current() -> Optional[Watch]:
    return _watch


def install(config: Optional[WatchConfig] = None, **kw: Any) -> Watch:
    """Install (and return) the process-wide Watch.  Does NOT start the
    scraper thread — ``serve.run`` starts it when a deployment exists to
    scrape, and tests drive :meth:`Watch.scrape_once` directly."""
    global _watch
    w = Watch(config, **kw)
    with _registry_lock:
        old, _watch = _watch, w
    if old is not None:
        old.stop_scraper()
    return w


def clear() -> None:
    """Tear down: stop the scraper (if running) and drop the Watch."""
    global _watch
    with _registry_lock:
        old, _watch = _watch, None
    if old is not None:
        old.stop_scraper()


def anomalous() -> List[str]:
    """Module-level convenience for the autoscaler's default anomaly
    source: recent anomaly metric names, empty when airwatch is off."""
    w = _watch
    return w.anomalous() if w is not None else []
