"""Dashboard: HTTP status service over the driver runtime.

Endpoints (default 127.0.0.1:8265, the reference's dashboard address —
Install_locally.md:64-67):
  /                 tiny HTML overview
  /api/cluster      resources, workers, actors, queue depth
  /api/objects      object-store + arena stats
  /api/engines      per-engine gauges (queue depth, occupancy, tokens/s, TTFT),
                    driver-local engines merged with serve-replica snapshots
  /api/traces       recent trace summaries; ?trace_id=... for one trace's spans
  /api/traces/export  chrome://tracing-loadable JSON (docs/OBSERVABILITY.md)
  /api/slo          airscope SLO burn-rate state (observability/slo.py),
                    evaluated against the live engine gauges on each GET
  /api/tenants      airwatch per-tenant cost ledger (observability/watch.py):
                    tokens, chip-seconds, KV-page-seconds, sheds, and the
                    chip_seconds_per_1k_tokens headline
  /api/watch        airwatch state: scrape/anomaly counters, recent
                    watch.anomaly events (with trace exemplars), detector
                    baselines, time-series store tiers
  /api/batch        airbatch job progress (tpu_air/batch): rows done/total,
                    rows-per-second, in-flight window, borrowed replicas,
                    shed retries — one entry per registered BatchJob
  /api/version      framework version
  /metrics          prometheus text exposition (OpenMetrics-style HELP/TYPE
                    headers; engine TTFT histograms carry trace exemplars)

When airwatch is installed, ``/api/engines`` and ``/metrics`` read replica
snapshots from its scrape cache instead of re-scraping per GET: snapshots
older than one scrape interval carry a ``stale_s`` age-mark and snapshots
older than the scrape TTL are dropped, so a killed replica's gauges leave
the fleet view instead of freezing at their last values.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional


def snapshot() -> Dict[str, Any]:
    """Point-in-time cluster state (the /api/cluster payload)."""
    from tpu_air.core import runtime as rt_mod

    if not rt_mod.is_initialized():
        return {"initialized": False}
    rt = rt_mod.get_runtime()
    with rt.lock:
        workers = {
            wid: {
                "pid": ws.proc.pid,
                "alive": ws.alive,
                "actor_id": ws.actor_id,
                "busy_task": ws.busy_task,
            }
            for wid, ws in rt.workers.items()
        }
        actors = {
            aid: {
                "name": st.name,
                "worker_id": st.worker.worker_id,
                "chip_ids": list(st.chip_ids),
                "dead": st.dead,
                "pending": st.pending,
            }
            for aid, st in rt.actors.items()
        }
        out = {
            "initialized": True,
            "session_id": rt.session_id,
            "resources": {"cpu": rt.num_cpus, "chip": rt.num_chips,
                          "chips_per_host": rt.chips_per_host},
            "available": dict(rt.avail),
            "free_chips": list(rt.free_chips),
            "queue_depth": len(rt.queue),
            "workers": workers,
            "actors": actors,
        }
    # control-plane membership/liveness (outside the lock: GCS RPC)
    out["gcs"] = {"address": rt.gcs_address, "nodes": rt.nodes()}
    return out


def object_stats() -> Dict[str, Any]:
    import os

    from tpu_air.core import runtime as rt_mod

    if not rt_mod.is_initialized():
        return {"initialized": False}
    rt = rt_mod.get_runtime()
    files = 0
    file_bytes = 0
    try:
        for name in os.listdir(rt.store_root):
            if name.startswith((".", "__")):
                continue
            files += 1
            file_bytes += os.path.getsize(os.path.join(rt.store_root, name))
    except OSError:
        pass
    out: Dict[str, Any] = {
        "store_root": rt.store_root,
        "file_objects": files,
        "file_bytes": file_bytes,
    }
    if rt.store._arena is not None:
        out["arena"] = rt.store._arena.stats()
    out["spill"] = rt.store.spill_stats()
    return out


def engine_stats() -> Dict[str, Any]:
    """Per-engine gauge snapshots (the /api/engines payload): driver-local
    engines (bench/test harness, driver-embedded) merged with serve-replica
    engines scraped over the deployment handles' ``engine_stats`` RPC
    (replica keys: ``deployment/replica-idx/engine-name``).

    With airwatch installed AND scraping, the replica side comes from the
    scraper's TTL-governed cache (see module doc) — stale snapshots age out
    instead of freezing, and a dashboard GET stops costing a fleet scrape."""
    out: Dict[str, Any] = {}
    cache = None
    try:
        from . import watch as watch_mod

        w = watch_mod.current()
        if w is not None and w.scrapes:
            cache = w.cached_engine_stats()
    except Exception:  # noqa: BLE001 — the cache is an optimization, never a 500
        cache = None
    if cache is not None:
        out.update(cache)
    try:
        from tpu_air.engine.metrics import snapshot_all
    except Exception:  # noqa: BLE001 — engine package optional (no jax)
        pass
    else:
        out.update(snapshot_all())  # driver-local: always live, never stale
    if cache is None:
        try:
            from tpu_air.serve.proxy import replica_engine_stats
        except Exception:  # noqa: BLE001 — serve package optional
            pass
        else:
            out.update(replica_engine_stats())
    return out


def serve_stats() -> Dict[str, Any]:
    """Per-route serve-plane control state (the /api/serve payload):
    admission outcomes + gauges and autoscaler decisions, straight from the
    proxy's controllers.  Empty when serve isn't running."""
    try:
        from tpu_air.serve.proxy import serve_control_stats
    except Exception:  # noqa: BLE001 — serve package optional
        return {}
    try:
        return serve_control_stats()
    except Exception:  # noqa: BLE001 — scrape is best-effort
        return {}


def trace_payload(query: Dict[str, Any]) -> Dict[str, Any]:
    """The /api/traces payload: recorder stats + recent trace summaries, or
    one trace's full span list when ``?trace_id=...`` is given."""
    from . import tracing

    trace_id = (query.get("trace_id") or [None])[0]
    rec = tracing.recorder()
    if trace_id:
        return {
            "enabled": tracing.enabled(),
            "trace_id": trace_id,
            "spans": [s.to_dict() for s in rec.for_trace(trace_id)],
        }
    limit = int((query.get("limit") or [64])[0])
    return {
        "enabled": tracing.enabled(),
        "recorder": rec.stats(),
        "traces": tracing.trace_summaries(limit),
    }


def slo_source() -> Dict[str, Any]:
    """Snapshot source for the default SLO monitor: the engine gauges plus
    a ``serve-recovery`` pseudo-snapshot carrying the serve plane's
    self-healing counters (journal, preemption watcher) so the recovery
    SLOs — preemption-recovery, migration-fallbacks, journal-evicted-live —
    are burn-rate-monitorable like any latency objective.  Route prefixes
    always start with ``/`` and engine names never contain one, so the
    bare key cannot collide with a real snapshot."""
    out = dict(engine_stats())
    recovery = (serve_stats() or {}).get("recovery")
    if recovery:
        out["serve-recovery"] = recovery
    return out


def slo_payload() -> Dict[str, Any]:
    """The /api/slo payload: every registered SLO's multi-window burn-rate
    state, freshly evaluated against the live engine gauges.  A scrape IS a
    sample: each GET appends one (good, total) point to the monitor's
    history, so the windows fill at the polling cadence."""
    from . import slo as slo_mod

    mon = slo_mod.ensure_default(slo_source)
    mon.observe()
    return {"slos": mon.state(), "burning": list(mon.burning())}


def tenants_payload() -> Dict[str, Any]:
    """The /api/tenants payload: airwatch's per-tenant cost ledger, or a
    bare ``{"enabled": false}`` when airwatch isn't installed."""
    from . import watch as watch_mod

    w = watch_mod.current()
    if w is None:
        return {"enabled": False, "tenants": {}}
    return {"enabled": True, **w.ledger.snapshot()}


def watch_payload() -> Dict[str, Any]:
    """The /api/watch payload: scrape/anomaly counters, recent events,
    detector baselines and store stats (observability/watch.py)."""
    from . import watch as watch_mod

    w = watch_mod.current()
    if w is None:
        return {"enabled": False}
    return w.payload()


def batch_payload() -> Dict[str, Any]:
    """The /api/batch payload: every registered batch job's progress
    snapshot (tpu_air/batch/job.py ``jobs_stats``)."""
    try:
        from tpu_air.batch import jobs_stats
        jobs = jobs_stats()
    except Exception:  # noqa: BLE001 — the dashboard must render without the lane
        jobs = {}
    return {"jobs": jobs}


# every non-engine family /metrics can emit, with its exposition type and
# HELP text (engine families live in engine/metrics.py next to their data)
_CLUSTER_FAMILIES = [
    ("tpu_air_cpus_total", "gauge", "CPU slots the runtime was initialized with."),
    ("tpu_air_chips_total", "gauge", "Accelerator chips the runtime was initialized with."),
    ("tpu_air_cpus_available", "gauge", "CPU slots not currently leased."),
    ("tpu_air_chips_available", "gauge", "Chips not currently leased."),
    ("tpu_air_queue_depth", "gauge", "Tasks waiting for placement in the driver queue."),
    ("tpu_air_workers", "gauge", "Worker processes registered with the runtime."),
    ("tpu_air_actors", "gauge", "Live actors registered with the runtime."),
    ("tpu_air_store_file_objects", "gauge", "Objects resident in the file-backed store."),
    ("tpu_air_store_file_bytes", "gauge", "Bytes resident in the file-backed store."),
]
_SERVE_FAMILIES = [
    ("tpu_air_serve_admission_admitted", "counter",
     "Requests admitted by the serve proxy, by route and priority class."),
    ("tpu_air_serve_admission_queued", "counter",
     "Requests queued at admission, by route and priority class."),
    ("tpu_air_serve_admission_shed", "counter",
     "Requests shed at admission, by route and priority class."),
    ("tpu_air_serve_queue_depth_per_replica", "gauge",
     "Mean admission-queue depth per live replica, by route."),
    ("tpu_air_serve_replicas", "gauge", "Live replicas, by route."),
    ("tpu_air_serve_scale_ups", "counter", "Autoscaler scale-up actions, by route."),
    ("tpu_air_serve_scale_downs", "counter", "Autoscaler scale-down actions, by route."),
]
# serve-plane self-healing counters (PR-15 recovery gauges), exported so the
# recovery SLOs' raw inputs are scrapeable next to their burn rates
_RECOVERY_FAMILIES = [
    ("tpu_air_recovery_journal_size", "gauge",
     "Replayable streams currently journaled by the serve proxy."),
    ("tpu_air_recovery_replays", "counter",
     "Streams replayed onto a survivor replica after their pin died."),
    ("tpu_air_recovery_replay_failures", "counter",
     "Stream replays that failed terminally."),
    ("tpu_air_recovery_journal_evicted_live", "counter",
     "Live (undelivered) streams evicted from a full journal."),
    ("tpu_air_recovery_preemptions", "counter",
     "Lease-revocation notices orchestrated by the preemption watcher."),
    ("tpu_air_recovery_migrations", "counter",
     "Streams live-migrated off a preempted replica."),
    ("tpu_air_recovery_migrated_pages", "counter",
     "KV pages moved by live migration."),
    ("tpu_air_recovery_migration_fallbacks", "counter",
     "Preemptions that fell back to journal replay instead of migration."),
    ("tpu_air_recovery_preemption_recovery_ms", "gauge",
     "Worst preemption orchestration wall time, notice to out-of-rotation."),
]
# airwatch per-tenant cost ledger (observability/watch.py), by tenant
_TENANT_FAMILIES = [
    ("tpu_air_tenant_tokens_prefilled", "counter",
     "Prompt tokens prefilled, attributed by tenant (adapter_id)."),
    ("tpu_air_tenant_tokens_decoded", "counter",
     "Tokens decoded, attributed by tenant."),
    ("tpu_air_tenant_requests_completed", "counter",
     "Requests retired, by tenant."),
    ("tpu_air_tenant_chip_seconds", "counter",
     "Busy chip-seconds attributed to the tenant by token share."),
    ("tpu_air_tenant_kv_page_seconds", "counter",
     "KV-page-seconds of cache residency, by tenant."),
    ("tpu_air_tenant_migrated_pages", "counter",
     "KV pages live-migrated for the tenant's streams."),
    ("tpu_air_tenant_sheds", "counter",
     "Requests shed at admission, by tenant."),
    ("tpu_air_tenant_quota_rejected", "counter",
     "Requests rejected by tenant quota, by tenant."),
    ("tpu_air_tenant_token_share", "gauge",
     "Tenant's share of all attributed tokens."),
    ("tpu_air_tenant_chip_seconds_per_1k_tokens", "gauge",
     "Attributed chip-seconds per 1000 tokens, by tenant."),
]
# airbatch job progress (tpu_air/batch), labelled by job — the counters
# are per-incarnation (a resumed driver restarts them; rows_done carries
# the epoch-level position via rows_resumed)
_BATCH_FAMILIES = [
    ("tpu_air_batch_rows_total", "gauge",
     "Rows in the batch job's dataset epoch."),
    ("tpu_air_batch_rows_done", "gauge",
     "Rows committed so far (processed this run + resumed from chunks)."),
    ("tpu_air_batch_rows_per_s", "gauge",
     "Rows processed per second by this driver incarnation."),
    ("tpu_air_batch_inflight", "gauge",
     "Rows currently in flight through serve admission."),
    ("tpu_air_batch_window", "gauge",
     "Current in-flight window (widened while borrowing chips)."),
    ("tpu_air_batch_borrowed_replicas", "gauge",
     "Serve replicas currently on loan to the batch job."),
    ("tpu_air_batch_borrows", "counter",
     "Replicas borrowed from idle serve capacity, lifetime."),
    ("tpu_air_batch_borrow_returns", "counter",
     "Borrowed replicas handed back through the preemption drain."),
    ("tpu_air_batch_checkpoints", "counter",
     "Cursor checkpoints journaled to the object store."),
    ("tpu_air_batch_resumes", "counter",
     "1 when this incarnation resumed from a checkpoint."),
    ("tpu_air_batch_shed_retries", "counter",
     "Admission sheds absorbed by backoff (best_effort yielding)."),
]
_WATCH_FAMILIES = [
    ("tpu_air_watch_scrapes", "counter",
     "Fleet scrape passes completed by the airwatch scraper."),
    ("tpu_air_watch_anomalies", "counter",
     "watch.anomaly events emitted by the online detector."),
    ("tpu_air_watch_samples_recorded", "counter",
     "Samples folded into the airwatch time-series store."),
    ("tpu_air_watch_idle_chip_seconds", "counter",
     "Chip-seconds observed with no tokens to attribute them to."),
    ("tpu_air_watch_chip_seconds_per_1k_tokens", "gauge",
     "Fleet headline: attributed chip-seconds per 1000 tokens."),
]


def _prometheus_text() -> str:
    from tpu_air.utils.metrics import ExpositionBuilder, sanitize_metric_name

    b = ExpositionBuilder()
    for fam, mtype, help_text in (_CLUSTER_FAMILIES + _SERVE_FAMILIES
                                  + _RECOVERY_FAMILIES + _TENANT_FAMILIES
                                  + _BATCH_FAMILIES + _WATCH_FAMILIES):
        b.declare(fam, mtype, help_text)
    snap = snapshot()
    lines: list = []
    if snap.get("initialized"):
        b.sample("tpu_air_cpus_total", {}, snap["resources"]["cpu"])
        b.sample("tpu_air_chips_total", {}, snap["resources"]["chip"])
        b.sample("tpu_air_cpus_available", {}, snap["available"].get("cpu", 0))
        b.sample("tpu_air_chips_available", {}, snap["available"].get("chip", 0))
        b.sample("tpu_air_queue_depth", {}, snap["queue_depth"])
        b.sample("tpu_air_workers", {}, len(snap["workers"]))
        b.sample("tpu_air_actors", {}, len(snap["actors"]))
        ost = object_stats()
        b.sample("tpu_air_store_file_objects", {}, ost.get("file_objects", 0))
        b.sample("tpu_air_store_file_bytes", {}, ost.get("file_bytes", 0))
        if "arena" in ost:
            for k, v in ost["arena"].items():
                # arena stat keys are free-form (may carry dots/dashes);
                # they must still land as valid prometheus identifiers
                fam = f"tpu_air_arena_{sanitize_metric_name(k)}"
                b.declare(fam, "gauge", f"Shared-memory arena stat {k}.")
                b.sample(fam, {}, v)
    # engine gauges live OUTSIDE the initialized check: an engine embedded
    # in this process (tests, bench, notebook) exports metrics even when the
    # cluster runtime was never brought up.  engine_stats() also folds in
    # serve-replica snapshots, so /metrics covers both.
    snapshots = engine_stats()
    if snapshots:
        try:
            from tpu_air.engine.metrics import prometheus_lines
        except Exception:  # noqa: BLE001 — engine package optional (no jax)
            pass
        else:
            lines += prometheus_lines(snapshots)
    # serve-plane control gauges: admission outcomes per class and the
    # autoscaler's position, labelled by route
    sstats = serve_stats()
    for route, ctl in sstats.items():
        if not isinstance(ctl, dict) or "admission" not in ctl:
            continue  # "recovery"/"weights" pseudo-routes handled below
        adm = ctl.get("admission") or {}
        for outcome in ("admitted", "queued", "shed"):
            for klass, n in (adm.get(outcome) or {}).items():
                b.sample(f"tpu_air_serve_admission_{outcome}",
                         {"route": route, "priority": klass}, n)
        g = adm.get("gauges") or {}
        if g:
            b.sample("tpu_air_serve_queue_depth_per_replica",
                     {"route": route}, g.get("depth_per_replica", 0))
        sc = ctl.get("autoscaler")
        if sc:
            b.sample("tpu_air_serve_replicas", {"route": route},
                     sc.get("replicas", 0))
            b.sample("tpu_air_serve_scale_ups", {"route": route},
                     sc.get("scale_ups", 0))
            b.sample("tpu_air_serve_scale_downs", {"route": route},
                     sc.get("scale_downs", 0))
    # self-healing counters: the recovery SLOs' raw inputs (satellite of
    # docs/OBSERVABILITY.md "airwatch" — burn rates ride tpu_air_slo_*)
    recovery = sstats.get("recovery") or {}
    for fam, _mtype, _help in _RECOVERY_FAMILIES:
        key = fam[len("tpu_air_recovery_"):]
        if key in recovery:
            b.sample(fam, {}, recovery[key])
    # airbatch: per-job progress gauges, same key-strip pattern as the
    # recovery/tenant families (family name minus prefix == stats key)
    for job_id, jstats in sorted((batch_payload().get("jobs") or {}).items()):
        labels = {"job": job_id}
        for fam, _mtype, _help in _BATCH_FAMILIES:
            key = fam[len("tpu_air_batch_"):]
            if key in jstats:
                b.sample(fam, labels, jstats[key])
    # airwatch: per-tenant cost ledger + the watch plane's own counters
    try:
        from . import watch as watch_mod

        w = watch_mod.current()
    except Exception:  # noqa: BLE001 — /metrics must render without airwatch
        w = None
    if w is not None:
        ledger = w.ledger.snapshot()
        for tenant, tot in sorted(ledger["tenants"].items()):
            labels = {"tenant": tenant}
            for fam, _mtype, _help in _TENANT_FAMILIES:
                key = fam[len("tpu_air_tenant_"):]
                if key in tot:
                    b.sample(fam, labels, tot[key])
        b.sample("tpu_air_watch_scrapes", {}, w.scrapes)
        b.sample("tpu_air_watch_anomalies", {}, w.anomalies)
        b.sample("tpu_air_watch_samples_recorded", {},
                 w.store.stats()["samples_recorded"])
        b.sample("tpu_air_watch_idle_chip_seconds", {},
                 ledger["idle_chip_seconds"])
        b.sample("tpu_air_watch_chip_seconds_per_1k_tokens", {},
                 ledger["headline"]["chip_seconds_per_1k_tokens"])
    # SLO burn-rate families (the monitor is its own exposition source so
    # the /api/slo JSON and the prometheus lines can never disagree); a
    # /metrics scrape doubles as a burn-rate sample, same as /api/slo
    from . import slo as slo_mod

    mon = slo_mod.ensure_default(slo_source)
    mon.observe()
    slo_lines = mon.prometheus_lines()
    out = b.lines() + lines + slo_lines
    return "\n".join(out) + "\n"


_INDEX_HTML = """<!doctype html><html><head><title>tpu_air dashboard</title></head>
<body><h2>tpu_air dashboard</h2>
<p>JSON endpoints: <a href="/api/cluster">/api/cluster</a> ·
<a href="/api/objects">/api/objects</a> ·
<a href="/api/engines">/api/engines</a> ·
<a href="/api/serve">/api/serve</a> ·
<a href="/api/traces">/api/traces</a> ·
<a href="/api/traces/export">/api/traces/export</a> ·
<a href="/api/slo">/api/slo</a> ·
<a href="/api/tenants">/api/tenants</a> ·
<a href="/api/watch">/api/watch</a> ·
<a href="/api/batch">/api/batch</a> ·
<a href="/api/version">/api/version</a> ·
<a href="/metrics">/metrics</a></p>
<pre id="s"></pre>
<script>
async function load(){
  const r = await fetch('/api/cluster');
  document.getElementById('s').textContent = JSON.stringify(await r.json(), null, 2);
}
load(); setInterval(load, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from urllib.parse import parse_qs, urlsplit

        parts = urlsplit(self.path)
        path = parts.path.rstrip("/") or "/"
        query = parse_qs(parts.query)
        try:
            if path == "/":
                self._send(200, _INDEX_HTML.encode(), "text/html")
            elif path == "/api/cluster":
                self._send(200, json.dumps(snapshot()).encode(), "application/json")
            elif path == "/api/objects":
                self._send(200, json.dumps(object_stats()).encode(), "application/json")
            elif path == "/api/engines":
                self._send(200, json.dumps(engine_stats()).encode(), "application/json")
            elif path == "/api/serve":
                self._send(200, json.dumps(serve_stats()).encode(), "application/json")
            elif path == "/api/traces":
                self._send(200, json.dumps(trace_payload(query)).encode(),
                           "application/json")
            elif path == "/api/traces/export":
                from . import trace_export

                trace_id = (query.get("trace_id") or [None])[0]
                self._send(
                    200,
                    trace_export.export_json(trace_id=trace_id).encode(),
                    "application/json",
                )
            elif path == "/api/slo":
                self._send(200, json.dumps(slo_payload()).encode(),
                           "application/json")
            elif path == "/api/tenants":
                self._send(200, json.dumps(tenants_payload()).encode(),
                           "application/json")
            elif path == "/api/watch":
                self._send(200, json.dumps(watch_payload()).encode(),
                           "application/json")
            elif path == "/api/batch":
                self._send(200, json.dumps(batch_payload()).encode(),
                           "application/json")
            elif path == "/api/version":
                import tpu_air

                self._send(
                    200,
                    json.dumps({"version": tpu_air.__version__}).encode(),
                    "application/json",
                )
            elif path == "/metrics":
                self._send(200, _prometheus_text().encode(), "text/plain")
            else:
                self._send(404, b'{"error": "not found"}', "application/json")
        except Exception as e:  # noqa: BLE001 — surface to the client
            self._send(500, json.dumps({"error": str(e)}).encode(), "application/json")


_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None
_lock = threading.Lock()


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> str:
    """Start the dashboard; returns its URL (printed by init, like the
    reference's 'Follow the link … to open the Ray Dashboard')."""
    global _server, _thread
    with _lock:
        if _server is not None:
            return f"http://{_server.server_address[0]}:{_server.server_address[1]}"
        srv = ThreadingHTTPServer((host, port), _Handler)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        _server, _thread = srv, t
        return f"http://{host}:{srv.server_address[1]}"


def stop_dashboard() -> None:
    global _server, _thread
    with _lock:
        if _server is not None:
            _server.shutdown()
            _server.server_close()
            _server = None
            _thread = None
