"""airscope SLO monitor — declarative objectives, multi-window burn rates.

An :class:`SLO` names a latency distribution inside the engine snapshots
(a dotted path like ``"priority.interactive.ttft_s"``), a good-event
threshold (``sample <= threshold_s``) and an objective (e.g. 0.999 = at
most 0.1% of samples over threshold).  The :class:`SLOMonitor` turns the
UNWINDOWED histograms the engines now export into windowed error rates by
remembering timestamped cumulative ``(good, total)`` pairs and differencing
them — the standard trick for deriving rates from counters, which is what
makes the histograms' mergeability matter: the monitor sums buckets across
every engine/replica snapshot before differencing, so the SLO is evaluated
over the FLEET, not per replica.

Burn rate is ``error_rate / error_budget`` where the budget is
``1 - objective``; a burn rate of 1.0 spends the budget exactly at the
objective's horizon.  Each SLO carries several ``(window_s, max_burn)``
pairs and is *burning* only when EVERY window exceeds its threshold —
multi-window multi-burn-rate alerting (Google SRE workbook ch.5): the
short window proves the problem is still happening, the long window proves
it is big enough to matter.

Surfaced at the dashboard's ``/api/slo`` and as ``tpu_air_slo_*``
prometheus lines; the serve autoscaler consumes :func:`burning_slos` as a
scale-up signal alongside raw p99 (serve/autoscaler.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from .perf import bucket_upper

# page-style defaults: 5m fast burn (2h to empty a 30d budget at 14.4x)
# AND 1h slow burn — both must fire
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = (
    (300.0, 14.4),
    (3600.0, 6.0),
)


@dataclass(frozen=True)
class SLO:
    """One objective over one engine-snapshot metric.

    * ``metric`` — dotted path into an engine snapshot.  What it must
      resolve to depends on ``kind``.
    * ``kind`` — how samples become (good, total) events:

      - ``"histogram"`` (default) — the path ends at a distribution dict
        with ``buckets``; every recorded sample is an event, good when at
        or under ``threshold_s``.
      - ``"counter"`` — the path ends at a cumulative NUMBER whose every
        increment is a BAD event (``migration_fallbacks``,
        ``journal_evicted_live`` — gauges that must not move).  Any
        in-window movement spends budget at rate 1.0, so the SLO burns
        exactly while the counter is moving; ``threshold_s`` is unused.
      - ``"gauge"`` — the path ends at a NUMBER sampled once per observe;
        each observation is one event, good when the value is at or
        under ``threshold_s`` IN THE METRIC'S OWN UNITS (e.g.
        ``preemption_recovery_ms`` against a millisecond threshold).

    * ``threshold_s`` — good-event cutoff (seconds for histogram paths,
      the metric's units for gauges).
    * ``objective`` — target good fraction (0.99 → 1% error budget).
    * ``windows`` — ``(window_s, max_burn_rate)`` pairs; ALL must exceed
      for the SLO to report burning.
    """

    name: str
    metric: str
    threshold_s: float
    objective: float = 0.99
    windows: Tuple[Tuple[float, float], ...] = DEFAULT_WINDOWS
    kind: str = "histogram"

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"objective must be in (0,1), got {self.objective}")
        if self.threshold_s <= 0:
            raise ValueError("threshold_s must be positive")
        if not self.windows:
            raise ValueError("at least one (window_s, max_burn) pair required")
        if self.kind not in ("histogram", "counter", "gauge"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")


def count_le(buckets: Dict[str, Any], threshold: float) -> float:
    """Samples at or below ``threshold`` in a serialized bucket dict,
    linearly interpolating inside the straddling bucket (same model the
    quantile uses, so the two are consistent)."""
    good = 0.0
    for key, n in (buckets or {}).items():
        idx = int(key)
        hi = bucket_upper(idx)
        if hi <= threshold:
            good += n
        else:
            lo = bucket_upper(idx - 1)
            if lo < threshold:
                good += n * (threshold - lo) / (hi - lo)
    return good


def _dig(snapshot: Dict[str, Any], path: str) -> Optional[Dict[str, Any]]:
    cur: Any = snapshot
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur if isinstance(cur, dict) else None


def _dig_scalar(snapshot: Dict[str, Any], path: str) -> Optional[float]:
    """Like :func:`_dig` but the path must end at a number (counter and
    gauge SLO kinds)."""
    cur: Any = snapshot
    for part in path.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


@dataclass
class _History:
    # (ts, good, total) cumulative pairs, oldest first
    points: Deque[Tuple[float, float, float]] = field(default_factory=deque)


class SLOMonitor:
    """Evaluates a set of SLOs against an engine-snapshot source.

    ``source`` returns ``{engine_name: snapshot}`` (the shape of
    ``dashboard.engine_stats()`` — driver engines merged with serve
    replicas); the monitor walks each SLO's metric path in EVERY snapshot
    and sums bucket counts, so replicas aggregate before rates are taken.
    ``now`` is injectable for deterministic window tests.
    """

    def __init__(self, slos: List[SLO],
                 source: Optional[Callable[[], Dict[str, Any]]] = None,
                 now: Callable[[], float] = time.monotonic):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.slos = tuple(slos)
        self._source = source
        self._now = now
        self._lock = threading.Lock()
        self._history: Dict[str, _History] = {s.name: _History()
                                              for s in slos}
        # gauge-kind SLOs build their own cumulative (good, total) pairs —
        # one event per observe — since the snapshot only carries the
        # instantaneous value
        self._gauge_acc: Dict[str, List[float]] = {}

    # -- sampling ------------------------------------------------------------
    def observe(self, snapshots: Optional[Dict[str, Any]] = None) -> None:
        """Take one cumulative sample per SLO from ``snapshots`` (or the
        configured source).  Call periodically — the dashboard calls it on
        every /api/slo + /metrics scrape, the autoscaler every tick."""
        if snapshots is None:
            if self._source is None:
                return
            try:
                snapshots = self._source() or {}
            except Exception:  # noqa: BLE001 — a failed scrape must not poison the monitor
                return
        ts = self._now()
        totals: Dict[str, Tuple[float, float]] = {}
        gauge_raw: Dict[str, Optional[float]] = {}
        for slo in self.slos:
            if slo.kind == "counter":
                # every increment of the summed cumulative counter is a
                # bad event: good stays 0, total tracks the counter, so a
                # moving counter burns at rate 1.0 and a still one at 0
                total = 0.0
                for snap in snapshots.values():
                    v = _dig_scalar(snap or {}, slo.metric)
                    if v is not None:
                        total += v
                totals[slo.name] = (0.0, total)
                continue
            if slo.kind == "gauge":
                # worst instantaneous value across snapshots this observe;
                # turned into one cumulative event under the lock below
                worst: Optional[float] = None
                for snap in snapshots.values():
                    v = _dig_scalar(snap or {}, slo.metric)
                    if v is not None:
                        worst = v if worst is None else max(worst, v)
                gauge_raw[slo.name] = worst
                continue
            good = total = 0.0
            for snap in snapshots.values():
                d = _dig(snap or {}, slo.metric)
                if not d or not d.get("count"):
                    continue
                buckets = d.get("buckets")
                if buckets:
                    total += sum(buckets.values())
                    good += count_le(buckets, slo.threshold_s)
            totals[slo.name] = (good, total)
        max_window = max(w for slo in self.slos for w, _ in slo.windows)
        with self._lock:
            for slo in self.slos:
                hist = self._history[slo.name]
                if slo.kind == "gauge":
                    raw = gauge_raw.get(slo.name)
                    if raw is None:
                        continue  # metric absent — no event this observe
                    acc = self._gauge_acc.setdefault(slo.name, [0.0, 0.0])
                    acc[1] += 1.0
                    if raw <= slo.threshold_s:
                        acc[0] += 1.0
                    good, total = acc[0], acc[1]
                else:
                    good, total = totals[slo.name]
                # cumulative counters only move forward; an engine restart
                # (counts drop) resets this SLO's history
                if hist.points and total < hist.points[-1][2]:
                    hist.points.clear()
                hist.points.append((ts, good, total))
                horizon = ts - max_window - 1.0
                while len(hist.points) > 2 and hist.points[1][0] < horizon:
                    hist.points.popleft()

    # -- evaluation ----------------------------------------------------------
    def state(self) -> List[Dict[str, Any]]:
        """Per-SLO burn-rate state (the /api/slo payload)."""
        ts = self._now()
        out = []
        with self._lock:
            for slo in self.slos:
                pts = self._history[slo.name].points
                cur = pts[-1] if pts else (ts, 0.0, 0.0)
                windows = []
                burning = bool(pts)
                budget = 1.0 - slo.objective
                for window_s, max_burn in slo.windows:
                    base = self._point_at(pts, ts - window_s)
                    d_total = cur[2] - base[2]
                    d_err = (cur[2] - cur[1]) - (base[2] - base[1])
                    rate = (d_err / d_total) if d_total > 0 else 0.0
                    burn = rate / budget
                    exceeded = d_total > 0 and burn >= max_burn
                    windows.append({
                        "window_s": window_s,
                        "max_burn": max_burn,
                        "error_rate": rate,
                        "burn_rate": burn,
                        "exceeded": exceeded,
                    })
                    burning = burning and exceeded
                out.append({
                    "name": slo.name,
                    "metric": slo.metric,
                    "threshold_s": slo.threshold_s,
                    "objective": slo.objective,
                    "good": cur[1],
                    "total": cur[2],
                    "windows": windows,
                    "burning": burning,
                })
        return out

    @staticmethod
    def _point_at(pts, cutoff: float) -> Tuple[float, float, float]:
        """Latest cumulative sample at or before ``cutoff`` (the window's
        left edge); the oldest sample when history is shorter than the
        window — the window degrades to 'since monitoring began'."""
        if not pts:
            return (cutoff, 0.0, 0.0)
        best = pts[0]
        for p in pts:
            if p[0] <= cutoff:
                best = p
            else:
                break
        return best

    def burning(self) -> List[str]:
        """Names of SLOs currently burning on every window."""
        return [s["name"] for s in self.state() if s["burning"]]

    def prometheus_lines(self) -> List[str]:
        lines = []
        state = self.state()
        if state:
            lines.append("# HELP tpu_air_slo_burn_rate error budget burn"
                         " rate per evaluation window")
            lines.append("# TYPE tpu_air_slo_burn_rate gauge")
            for s in state:
                for w in s["windows"]:
                    lines.append(
                        f'tpu_air_slo_burn_rate{{slo="{s["name"]}",'
                        f'window="{w["window_s"]:g}s"}} '
                        f'{w["burn_rate"]:.6f}')
            lines.append("# HELP tpu_air_slo_burning 1 when every window"
                         " exceeds its burn threshold")
            lines.append("# TYPE tpu_air_slo_burning gauge")
            for s in state:
                lines.append(
                    f'tpu_air_slo_burning{{slo="{s["name"]}"}} '
                    f'{int(s["burning"])}')
            lines.append("# HELP tpu_air_slo_good_total cumulative good"
                         " events (samples within threshold)")
            lines.append("# TYPE tpu_air_slo_good_total counter")
            for s in state:
                lines.append(
                    f'tpu_air_slo_good_total{{slo="{s["name"]}"}} '
                    f'{s["good"]:.1f}')
            lines.append("# HELP tpu_air_slo_events_total cumulative"
                         " events observed for the objective")
            lines.append("# TYPE tpu_air_slo_events_total counter")
            for s in state:
                lines.append(
                    f'tpu_air_slo_events_total{{slo="{s["name"]}"}} '
                    f'{s["total"]:.1f}')
        return lines


def default_slos() -> List[SLO]:
    """The serve plane's stock objectives: interactive TTFT under 1s at
    99.9%, any-class TTFT under 5s at 99%, plus the PR-15 recovery gauges
    (exported by the supervisor into the ``serve-recovery``
    pseudo-snapshot the dashboard source injects): worst preemption
    recovery under 2s, and the two must-not-move counters — migration
    fallbacks and live journal evictions — whose every increment is an
    error event."""
    return [
        SLO(name="interactive-ttft", threshold_s=1.0, objective=0.999,
            metric="priority.interactive.ttft_s"),
        SLO(name="ttft", threshold_s=5.0, objective=0.99,
            metric="ttft_s"),
        SLO(name="preemption-recovery", threshold_s=2000.0, objective=0.99,
            metric="preemption_recovery_ms", kind="gauge"),
        SLO(name="migration-fallbacks", threshold_s=1.0, objective=0.999,
            metric="migration_fallbacks", kind="counter"),
        SLO(name="journal-evicted-live", threshold_s=1.0, objective=0.999,
            metric="journal_evicted_live", kind="counter"),
    ]


# -- process-wide registry ---------------------------------------------------
# the dashboard and autoscaler read whatever monitor the app installed;
# install(None) tears down (tests)

_installed: Optional[SLOMonitor] = None
_registry_lock = threading.Lock()


def install(monitor: Optional[SLOMonitor]) -> Optional[SLOMonitor]:
    global _installed
    with _registry_lock:
        _installed = monitor
    return monitor


def monitor() -> Optional[SLOMonitor]:
    with _registry_lock:
        return _installed


def ensure_default(source: Callable[[], Dict[str, Any]]) -> SLOMonitor:
    """Install the default SLO set over ``source`` unless a monitor is
    already installed; returns the active monitor either way."""
    global _installed
    with _registry_lock:
        if _installed is None:
            _installed = SLOMonitor(default_slos(), source=source)
        return _installed
