"""airwatch ring-buffer time-series store — fixed-step downsampling tiers.

Every other observability surface in the repo is point-in-time (airtrace
shows one request, airscope snapshots one engine, ``/api/*`` the current
instant).  This module is the HISTORY: a pure-stdlib, process-local store
holding one ring of fixed-step buckets per (metric, tier), so "what did
the fleet look like five minutes ago" is answerable without an external
scrape stack.

Tiers downsample by construction, not by background compaction: a sample
is folded into EVERY tier's current bucket on :meth:`record` (the 1s tier
keeps 10 minutes at full resolution, the 10s tier an hour, the 60s tier a
day — ``DEFAULT_TIERS``).  A bucket aggregates ``count/sum/min/max/last``,
which is everything the anomaly detector (watch.py) and a dashboard
sparkline need; full distributions stay in the airscope histograms the
scraper merges separately.

Rings are ``collections.deque`` with ``maxlen`` — eviction is O(1) and
memory is bounded at ``sum(capacity for _, capacity in tiers)`` buckets
per metric.  The clock is injectable (``now=``) so the downsample tests
drive tier boundaries deterministically.  All methods are thread-safe
behind one lock; nothing under the lock blocks.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

#: (step_s, capacity) per tier: 1s x 600 (10 min) -> 10s x 360 (1 h)
#: -> 60s x 1440 (1 day)
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = (
    (1.0, 600),
    (10.0, 360),
    (60.0, 1440),
)

# bucket list layout (a list, not a dataclass: these are the store's hot
# allocation and rings hold thousands of them)
_START, _COUNT, _SUM, _MIN, _MAX, _LAST = range(6)


class TimeSeriesStore:
    """Per-metric ring buffers over fixed-step downsampling tiers."""

    def __init__(self, tiers: Tuple[Tuple[float, int], ...] = DEFAULT_TIERS,
                 now: Callable[[], float] = time.monotonic):
        if not tiers:
            raise ValueError("at least one (step_s, capacity) tier required")
        for step, cap in tiers:
            if step <= 0 or cap < 1:
                raise ValueError(f"bad tier ({step}, {cap})")
        self.tiers = tuple((float(step), int(cap)) for step, cap in tiers)
        self._now = now
        self._lock = threading.Lock()
        # metric -> [ring per tier]; ring holds bucket lists, oldest first
        self._series: Dict[str, List[Deque[list]]] = {}
        self._recorded = 0

    # -- recording -----------------------------------------------------------
    def record(self, metric: str, value: float,
               ts: Optional[float] = None) -> None:
        """Fold one sample into every tier's bucket at ``ts`` (defaults to
        the injected clock).  Samples older than a tier's newest bucket
        fold into that newest bucket — the store assumes a monotonic
        feeder and degrades gracefully rather than re-sorting."""
        v = float(value)
        t = self._now() if ts is None else float(ts)
        with self._lock:
            rings = self._series.get(metric)
            if rings is None:
                rings = [deque(maxlen=cap) for _, cap in self.tiers]
                self._series[metric] = rings
            self._recorded += 1
            for (step, _cap), ring in zip(self.tiers, rings):
                start = (t // step) * step
                if ring and start <= ring[-1][_START]:
                    b = ring[-1]  # same bucket (or a late sample): aggregate
                    b[_COUNT] += 1
                    b[_SUM] += v
                    if v < b[_MIN]:
                        b[_MIN] = v
                    if v > b[_MAX]:
                        b[_MAX] = v
                    b[_LAST] = v
                else:
                    ring.append([start, 1, v, v, v, v])

    # -- reading -------------------------------------------------------------
    def metrics(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def _ring(self, metric: str, step: Optional[float]) -> Optional[Deque]:
        rings = self._series.get(metric)
        if rings is None:
            return None
        if step is None:
            return rings[0]
        for (tier_step, _cap), ring in zip(self.tiers, rings):
            if tier_step == float(step):
                return ring
        raise KeyError(f"no tier with step {step!r} "
                       f"(have {[s for s, _ in self.tiers]})")

    def series(self, metric: str, step: Optional[float] = None,
               since: Optional[float] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Buckets for one metric on one tier (default: the finest),
        oldest first, as JSON-ready dicts.  ``since`` filters by bucket
        start; ``limit`` keeps the newest N."""
        with self._lock:
            ring = self._ring(metric, step)
            buckets = list(ring) if ring else []
        if since is not None:
            buckets = [b for b in buckets if b[_START] >= since]
        if limit is not None and limit >= 0:
            buckets = buckets[-limit:]
        return [
            {
                "ts": b[_START],
                "count": b[_COUNT],
                "sum": b[_SUM],
                "min": b[_MIN],
                "max": b[_MAX],
                "last": b[_LAST],
                "mean": b[_SUM] / b[_COUNT],
            }
            for b in buckets
        ]

    def latest(self, metric: str) -> Optional[float]:
        """Most recent sample value (finest tier's newest bucket)."""
        with self._lock:
            ring = self._ring(metric, None)
            return ring[-1][_LAST] if ring else None

    def window(self, metric: str, seconds: float,
               step: Optional[float] = None) -> List[float]:
        """Per-bucket LAST values covering the trailing ``seconds`` on one
        tier — the anomaly detector's view (watch.py reads the 1s tier)."""
        horizon = self._now() - float(seconds)
        with self._lock:
            ring = self._ring(metric, step)
            if not ring:
                return []
            return [b[_LAST] for b in ring if b[_START] >= horizon]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "tiers": [{"step_s": s, "capacity": c} for s, c in self.tiers],
                "metrics": len(self._series),
                "samples_recorded": self._recorded,
                "buckets_resident": sum(
                    len(r) for rings in self._series.values() for r in rings),
            }
