"""Flax T5 (encoder-decoder) implemented TPU-first.

Replaces the reference's torch `T5ForConditionalGeneration`
(Model_finetuning…ipynb:cc-25,46; predictor.py:68,102) with a from-scratch
flax.linen implementation designed for XLA:

* static shapes everywhere — one compiled program serves every batch;
* autoregressive `generate` as a `lax.scan` over a pre-allocated KV cache
  (SURVEY.md §7 hard-part 2), jit-compiled end to end, cache constructed
  via `jax.eval_shape` (no throwaway init compute);
* bf16-friendly: activations in `config.dtype`, params fp32;
* matmul-heavy blocks (DenseGeneral projections, gated-GELU MLP) shaped for
  the MXU; sharding is applied externally by the trainer's partitioner
  (tpu_air/parallel) so DP/TP are config choices, not model rewrites.

Architecture notes (T5 v1.1 == FLAN-T5): RMSNorm pre-norm, relative position
bias (bucketed; table hoisted to each stack and shared by its layers), NO
attention score scaling, gated-GELU feed-forward, untied lm_head.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .config import T5Config

Array = jax.Array

NEG_INF = -1e9


def _dtype(config: T5Config):
    return jnp.dtype(config.dtype)


class RMSNorm(nn.Module):
    """T5 LayerNorm: scale-only RMS normalization (no mean, no bias)."""

    eps: float = 1e-6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        weight = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        y = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)
        return (weight * y).astype(self.dtype)


def relative_position_bucket(
    relative_position: Array,
    bidirectional: bool,
    num_buckets: int,
    max_distance: int,
) -> Array:
    """Bucketed relative positions (T5 paper §2.1). ``relative_position`` is
    ``key_position - query_position``."""
    ret = jnp.zeros_like(relative_position)
    n = relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n > 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = -jnp.minimum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / jnp.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class RelativePositionBias(nn.Module):
    """Relative attention bias table → [1, heads, qlen, klen]."""

    config: T5Config
    bidirectional: bool

    @nn.compact
    def __call__(self, query_positions: Array, key_positions: Array) -> Array:
        cfg = self.config
        table = self.param(
            "embedding",
            nn.initializers.normal(stddev=1.0),
            (cfg.relative_attention_num_buckets, cfg.num_heads),
            jnp.float32,
        )
        rel = key_positions[None, :] - query_positions[:, None]  # [q, k]
        buckets = relative_position_bucket(
            rel,
            self.bidirectional,
            cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )
        bias = table[buckets]  # [q, k, heads]
        return jnp.transpose(bias, (2, 0, 1))[None].astype(_dtype(cfg))


class Attention(nn.Module):
    """Multi-head attention with optional pre-allocated decode cache.

    T5 detail: scores are NOT scaled by sqrt(d_kv).
    """

    config: T5Config

    @nn.compact
    def __call__(
        self,
        hidden: Array,
        kv_hidden: Array,
        mask: Optional[Array],           # [*, 1|heads, qlen, klen] additive (dense)
        position_bias: Optional[Array],  # [1, heads, qlen, klen]
        kv_mask: Optional[Array] = None,  # [batch, klen] 1=attend (structured)
        causal: bool = False,             # structured causal flag
        decode: bool = False,
        cross_decode: bool = False,
        deterministic: bool = True,
    ) -> Array:
        cfg = self.config
        dtype = _dtype(cfg)
        init = nn.initializers.normal(stddev=cfg.d_model**-0.5)

        def dense(name):
            return nn.DenseGeneral(
                features=(cfg.num_heads, cfg.d_kv),
                axis=-1, use_bias=False, dtype=dtype, kernel_init=init, name=name,
            )

        q = dense("q")(hidden)           # [b, q, h, d]
        cache_int8 = getattr(cfg, "decode_cache_int8", False)

        def _quant(x):
            # per-(batch, head, channel) scale over the length dim: the
            # length axis is what streams from HBM every step.  Pad
            # positions are zeroed FIRST — they are masked out of the
            # scores anyway, and a pad-position activation outlier would
            # otherwise inflate the scale and coarsen the grid for every
            # valid token in its channel.
            xf = x.astype(jnp.float32)
            if kv_mask is not None:
                xf = xf * kv_mask[:, :, None, None].astype(jnp.float32)
            amax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
            scale = jnp.maximum(amax, 1e-8) / 127.0
            q8 = jnp.clip(jnp.round(xf / scale), -127, 127)
            return q8.astype(jnp.int8), scale

        def _dequant(q8, scale):
            return (q8.astype(jnp.float32) * scale).astype(dtype)

        # Decode caches are stored FLAT: [b, L, h*d], scales [b, 1, h*d]
        # (cross, per-channel) / [b, L, h] (self, per-position).  The r5
        # profile found the 4-D [b, L, h, d] slab layout was the decode
        # bottleneck: TPU tiles the last two dims (12, 64) up to (16, 128)
        # — 2.67x physical HBM bytes — and XLA streamed those padded
        # slabs at ~92% of the roofline, i.e. the chip was fast, the
        # LAYOUT was the waste.  h*d = 768 is six clean (8, 128) tiles,
        # zero padding.  The cached single-token step then attends via
        # the flat block-diagonal formulation (``flat_decode_attention``)
        # or the Pallas kernel, never materializing a [b, L, h, d] copy.
        dk_impl = getattr(cfg, "decode_attention_impl", "auto")
        dk_scales = (None, None)
        cached_step = False    # k/v hold FLAT cache slabs, not [b,k,h,d]

        if cross_decode and self.has_variable("cache", "cached_key"):
            # Cross-attention during cached decode: K/V are an invariant of
            # the encoder output, computed ONCE at cache init.  Recomputing
            # the two 512-token projections per decode step was the dominant
            # cost of W3 generation (~12 layers x 2 projections x the full
            # encoder length, per emitted token).
            k = self.get_variable("cache", "cached_key")       # [b, L, h*d]
            v = self.get_variable("cache", "cached_value")
            cached_step = True
            if cache_int8:
                dk_scales = (
                    self.get_variable("cache", "cached_key_scale"),
                    self.get_variable("cache", "cached_value_scale"),
                )
        else:
            k = dense("k")(kv_hidden)    # [b, k, h, d]
            v = dense("v")(kv_hidden)
            if cross_decode:
                bsz, klv = k.shape[0], k.shape[1]
                if cache_int8:
                    kq, ks = _quant(k)
                    vq, vs = _quant(v)
                    self.variable("cache", "cached_key",
                                  lambda: kq.reshape(bsz, klv, -1))
                    self.variable("cache", "cached_key_scale",
                                  lambda: ks.reshape(bsz, 1, -1))
                    self.variable("cache", "cached_value",
                                  lambda: vq.reshape(bsz, klv, -1))
                    self.variable("cache", "cached_value_scale",
                                  lambda: vs.reshape(bsz, 1, -1))
                    # the init pass itself attends with the dequantized
                    # values so its output matches later steps
                    k = _dequant(kq, ks)
                    v = _dequant(vq, vs)
                else:
                    self.variable("cache", "cached_key",
                                  lambda: k.reshape(bsz, klv, -1))
                    self.variable("cache", "cached_value",
                                  lambda: v.reshape(bsz, klv, -1))

        if decode:
            # Pre-allocated flat self-attention slabs; cache vars are
            # created ahead of time by init_cache (eval_shape) so is_init
            # only occurs there.  With decode_cache_int8 the slabs are
            # int8 with a per-(batch, position, head) scale over the
            # channel dim, quantized incrementally as each step's K/V
            # land — the self-attention half of the decode-bandwidth
            # story (cross is quantized whole at cache init above).
            is_init = not self.has_variable("cache", "cached_key")
            slab_dtype = jnp.int8 if cache_int8 else dtype
            bsz, klv = k.shape[0], k.shape[1]
            hd = cfg.num_heads * cfg.d_kv
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (bsz, klv, hd), slab_dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (bsz, klv, hd), slab_dtype)
            if cache_int8:
                cks = self.variable("cache", "cached_key_scale", jnp.zeros,
                                    (bsz, klv, cfg.num_heads), jnp.float32)
                cvs = self.variable("cache", "cached_value_scale", jnp.zeros,
                                    (bsz, klv, cfg.num_heads), jnp.float32)
            idx = self.variable(
                "cache", "cache_index", lambda: jnp.array(0, dtype=jnp.int32)
            )
            if not is_init:
                cur = idx.value
                if cache_int8:
                    def _quant_pos(x):
                        xf = x.astype(jnp.float32)
                        amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
                        s = jnp.maximum(amax, 1e-8) / 127.0
                        x8 = jnp.clip(jnp.round(xf / s), -127, 127)
                        return x8.astype(jnp.int8), s

                    k8, ks_ = _quant_pos(k)
                    v8, vs_ = _quant_pos(v)
                    ck.value = jax.lax.dynamic_update_slice(
                        ck.value, k8.reshape(bsz, klv, hd), (0, cur, 0))
                    cks.value = jax.lax.dynamic_update_slice(
                        cks.value, ks_.reshape(bsz, klv, -1), (0, cur, 0))
                    cv.value = jax.lax.dynamic_update_slice(
                        cv.value, v8.reshape(bsz, klv, hd), (0, cur, 0))
                    cvs.value = jax.lax.dynamic_update_slice(
                        cvs.value, vs_.reshape(bsz, klv, -1), (0, cur, 0))
                    idx.value = cur + q.shape[1]
                    k, v = ck.value, cv.value
                    dk_scales = (cks.value, cvs.value)
                else:
                    ck.value = jax.lax.dynamic_update_slice(
                        ck.value, k.reshape(bsz, klv, hd), (0, cur, 0))
                    cv.value = jax.lax.dynamic_update_slice(
                        cv.value, v.reshape(bsz, klv, hd), (0, cur, 0))
                    idx.value = cur + q.shape[1]
                    k, v = ck.value, cv.value
                cached_step = True

        qlen, klen = q.shape[1], k.shape[1]
        # Pallas blockwise path: eligible when callers passed the structured
        # mask form (causal flag + key-padding row — never a dense (q, k)
        # tensor), we're not in cached decode (qlen == 1 per-token launches
        # are a perf cliff; XLA's einsum path wins there), and attention
        # dropout is inactive (flash streams probabilities — there is no
        # materialized matrix to drop out of).  Dispatch among eligible
        # paths is by SHAPE at trace time (config.attention_impl="auto"):
        # einsum below the measured crossover, flash at/above it.
        eligible = (
            not decode
            and qlen > 1
            and mask is None
            and (deterministic or cfg.dropout_rate == 0)
        )
        impl = "flash" if cfg.use_flash_attention else getattr(
            cfg, "attention_impl", "auto"
        )
        if impl == "auto":
            from tpu_air.ops.flash_attention import auto_dispatch_ok

            use_flash = eligible and (
                max(qlen, klen) >= getattr(cfg, "flash_min_seq_len", 1024)
                and auto_dispatch_ok(qlen, klen)
            )
        else:
            use_flash = eligible and impl == "flash"
        if cached_step:
            # Single-token step over flat cache slabs.  Structured-mask
            # contract: mask here is batch-shared (decode causal row) or
            # None.
            # Measured dispatch (BENCH r5, W3 dials, flat storage): bf16
            # decodes FASTER through XLA's dense path reconstructed from
            # the flat slab (179.2 seq/s, 0.80 of roofline) than through
            # the block-diagonal formulation (161.2, 0.715) — given the
            # flat carry layout, XLA's own attention fusion wins.  int8
            # must NOT reconstruct (dequant materializes, pessimistic
            # bound 9.4 GB/step vs 3.3): the fold-based flat path wins
            # there (213.7 seq/s).  So "auto" = einsum for full-width
            # caches, flat folds for int8.
            impl_eff = dk_impl
            if dk_impl == "auto" and dk_scales[0] is None:
                impl_eff = "einsum"
            fast_ok = (
                qlen == 1
                and (deterministic or cfg.dropout_rate == 0)
                and (mask is None or mask.shape[0] == 1)
                and impl_eff != "einsum"
            )
            if fast_ok:
                if mask is not None and not (
                    mask.ndim == 4 and mask.shape[2] == 1
                ):
                    # the comb[0, :, 0, :] slice below assumes the decode
                    # mask layout [1, h|1, 1, klen]; any other layout would
                    # be silently mis-sliced (ADVICE r5) — fail loudly
                    raise ValueError(
                        "decode fast path expects a [1, h|1, 1, klen] "
                        f"mask; got shape {mask.shape}"
                    )
                bias_arg = None
                if position_bias is not None or mask is not None:
                    comb = jnp.zeros((1, 1, 1, klen), jnp.float32)
                    if position_bias is not None:
                        comb = comb + position_bias.astype(jnp.float32)
                    if mask is not None:
                        comb = comb + mask.astype(jnp.float32)
                    # batch-shared [1, h|1, 1, klen] -> [h, klen]
                    bias_arg = jnp.broadcast_to(
                        comb[0, :, 0, :], (cfg.num_heads, klen)
                    )
                if dk_impl == "pallas":
                    from tpu_air.ops.decode_attention import decode_attention

                    ctx = decode_attention(
                        q, k, v, bias=bias_arg, kv_mask=kv_mask,
                        k_scale=dk_scales[0], v_scale=dk_scales[1],
                    )
                else:
                    from tpu_air.ops.decode_attention import (
                        flat_decode_attention,
                    )

                    ctx = flat_decode_attention(
                        q, k, v, bias_arg, kv_mask,
                        dk_scales[0], dk_scales[1], cfg.num_heads, dtype,
                    )
            else:
                # legacy/comparison path: materialize the dequantized 4-D
                # slab and fall through to the dense einsum below
                bsz = k.shape[0]
                hpd = (cfg.num_heads, cfg.d_kv)
                ks_, vs_ = dk_scales
                if ks_ is not None:
                    if ks_.shape[1] == 1:          # cross: per-channel
                        k = (k.astype(jnp.float32) * ks_).reshape(
                            bsz, klen, *hpd).astype(dtype)
                        v = (v.astype(jnp.float32) * vs_).reshape(
                            bsz, klen, *hpd).astype(dtype)
                    else:                           # self: per-position
                        k = (k.reshape(bsz, klen, *hpd).astype(jnp.float32)
                             * ks_[..., None]).astype(dtype)
                        v = (v.reshape(bsz, klen, *hpd).astype(jnp.float32)
                             * vs_[..., None]).astype(dtype)
                else:
                    k = k.reshape(bsz, klen, *hpd)
                    v = v.reshape(bsz, klen, *hpd)
                ctx = None
        else:
            ctx = None
        if ctx is not None:
            pass
        elif use_flash:
            from tpu_air.ops import flash_attention

            # position_bias stays (1, H, q, k) — the kernel's BlockSpec
            # replays the head tile per batch element; no HBM broadcast.
            # Block sizes: None → the kernel's measured-on-TPU auto tiling
            # (512/1024 caps; 128-capped tiles ran the MXU at ~1/8 rate).
            ctx = flash_attention(
                q.transpose(0, 2, 1, 3),
                k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3),
                None if position_bias is None else position_bias.astype(jnp.float32),
                kv_mask=kv_mask,
                causal=causal,
                scale=1.0,  # T5: unscaled scores
            ).transpose(0, 2, 1, 3)
        else:
            if mask is None and (kv_mask is not None or causal):
                # densify the structured mask for the einsum path
                mask = jnp.zeros((1, 1, qlen, klen), jnp.float32)
                if kv_mask is not None:
                    mask = mask + (1.0 - kv_mask[:, None, None, :].astype(jnp.float32)) * NEG_INF
                if causal:
                    c = jnp.tril(jnp.ones((qlen, klen), jnp.float32))
                    mask = mask + ((1.0 - c) * NEG_INF)[None, None]
                mask = mask.astype(dtype)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            if position_bias is not None:
                scores = scores + position_bias
            if mask is not None:
                scores = scores + mask
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dtype)
            if not deterministic and cfg.dropout_rate > 0:
                probs = nn.Dropout(cfg.dropout_rate)(probs, deterministic=False)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), use_bias=False, dtype=dtype,
            kernel_init=nn.initializers.normal(stddev=(cfg.num_heads * cfg.d_kv) ** -0.5),
            name="o",
        )(ctx)


class FeedForward(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        cfg = self.config
        dtype = _dtype(cfg)
        init = nn.initializers.normal(stddev=cfg.d_model**-0.5)
        act = getattr(jax.nn, cfg.act_fn)
        if cfg.is_gated_act:
            wi0 = nn.Dense(cfg.d_ff, use_bias=False, dtype=dtype, kernel_init=init,
                           name="wi_0")(x)
            wi1 = nn.Dense(cfg.d_ff, use_bias=False, dtype=dtype, kernel_init=init,
                           name="wi_1")(x)
            h = act(wi0) * wi1
        else:
            h = act(nn.Dense(cfg.d_ff, use_bias=False, dtype=dtype, kernel_init=init,
                             name="wi")(x))
        if cfg.dropout_rate > 0:
            h = nn.Dropout(cfg.dropout_rate)(h, deterministic=deterministic)
        return nn.Dense(
            cfg.d_model, use_bias=False, dtype=dtype,
            kernel_init=nn.initializers.normal(stddev=cfg.d_ff**-0.5), name="wo",
        )(h)


class EncoderLayer(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x, kv_mask, position_bias, deterministic=True):
        cfg = self.config
        h = RMSNorm(cfg.layer_norm_epsilon, _dtype(cfg), name="ln_self")(x)
        x = x + Attention(cfg, name="self_attn")(
            h, h, None, position_bias, kv_mask=kv_mask, deterministic=deterministic
        )
        h = RMSNorm(cfg.layer_norm_epsilon, _dtype(cfg), name="ln_mlp")(x)
        x = x + FeedForward(cfg, name="mlp")(h, deterministic=deterministic)
        return x


class DecoderLayer(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(
        self, x, enc, position_bias, self_mask=None, self_kv_mask=None,
        self_causal=False, cross_kv_mask=None,
        decode=False, deterministic=True,
    ):
        cfg = self.config
        h = RMSNorm(cfg.layer_norm_epsilon, _dtype(cfg), name="ln_self")(x)
        x = x + Attention(cfg, name="self_attn")(
            h, h, self_mask, position_bias, kv_mask=self_kv_mask,
            causal=self_causal, decode=decode, deterministic=deterministic,
        )
        h = RMSNorm(cfg.layer_norm_epsilon, _dtype(cfg), name="ln_cross")(x)
        x = x + Attention(cfg, name="cross_attn")(
            h, enc, None, None, kv_mask=cross_kv_mask, cross_decode=decode,
            deterministic=deterministic,
        )
        h = RMSNorm(cfg.layer_norm_epsilon, _dtype(cfg), name="ln_mlp")(x)
        x = x + FeedForward(cfg, name="mlp")(h, deterministic=deterministic)
        return x


class Encoder(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, embeds, attention_mask, deterministic=True):
        cfg = self.config
        L = embeds.shape[1]
        positions = jnp.arange(L)
        bias = RelativePositionBias(cfg, bidirectional=True, name="rel_bias")(
            positions, positions
        )
        x = embeds
        for i in range(cfg.num_layers):
            x = EncoderLayer(cfg, name=f"layer_{i}")(
                x, attention_mask, bias, deterministic
            )
        return RMSNorm(cfg.layer_norm_epsilon, _dtype(cfg), name="final_ln")(x)


class Decoder(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(
        self, embeds, enc, enc_mask, dec_mask=None,
        decode=False, deterministic=True,
    ):
        cfg = self.config
        dtype = _dtype(cfg)
        qlen = embeds.shape[1]

        if decode:
            # Single-step (or cache-init) decoding over a pre-allocated cache
            # of klen = cache max_len.  Track the absolute query position.
            pos = self.variable(
                "cache", "decoder_pos", lambda: jnp.array(0, dtype=jnp.int32)
            )
            # klen equals the cache length, which equals qlen at init time and
            # is carried by the attention cache afterwards; the caller passes
            # the same max_len via embeds at init, so derive klen from the
            # layer-0 cache when present.
            is_init = not self.has_variable("cache", "decoder_max_len")
            if is_init:
                klen = qlen
            else:
                klen = int(self.get_variable("cache", "decoder_max_len").shape[0])
            self.variable(
                "cache", "decoder_max_len", jnp.zeros, (klen,), jnp.int8
            )
            query_positions = pos.value + jnp.arange(qlen)
            key_positions = jnp.arange(klen)
            bias = RelativePositionBias(cfg, bidirectional=False, name="rel_bias")(
                query_positions, key_positions
            )
            causal = (
                key_positions[None, :] <= query_positions[:, None]
            ).astype(jnp.float32)
            self_mask = ((1.0 - causal[None, None]) * NEG_INF).astype(dtype)
            x = embeds
            for i in range(cfg.num_decoder_layers):
                x = DecoderLayer(cfg, name=f"layer_{i}")(
                    x, enc, bias, self_mask=self_mask, cross_kv_mask=enc_mask,
                    decode=True, deterministic=deterministic,
                )
            if not is_init:
                # the cache-init pass (a real apply now, so cross K/V get
                # computed) is not a decoding step — position stays 0
                pos.value = pos.value + qlen
            return RMSNorm(cfg.layer_norm_epsilon, dtype, name="final_ln")(x)

        positions = jnp.arange(qlen)
        bias = RelativePositionBias(cfg, bidirectional=False, name="rel_bias")(
            positions, positions
        )
        x = embeds
        for i in range(cfg.num_decoder_layers):
            x = DecoderLayer(cfg, name=f"layer_{i}")(
                x, enc, bias, self_kv_mask=dec_mask, self_causal=True,
                cross_kv_mask=enc_mask,
                decode=False, deterministic=deterministic,
            )
        return RMSNorm(cfg.layer_norm_epsilon, dtype, name="final_ln")(x)


class T5ForConditionalGeneration(nn.Module):
    """Encoder-decoder LM head model (reference: predictor.py:68 loads the
    torch equivalent from a checkpoint)."""

    config: T5Config

    def setup(self):
        cfg = self.config
        self.shared = nn.Embed(
            cfg.vocab_size, cfg.d_model,
            embedding_init=nn.initializers.normal(stddev=1.0),
            dtype=_dtype(cfg), name="shared",
        )
        self.encoder = Encoder(cfg, name="encoder")
        self.decoder = Decoder(cfg, name="decoder")
        if not cfg.tie_word_embeddings:
            self.lm_head = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=_dtype(cfg),
                kernel_init=nn.initializers.normal(stddev=cfg.d_model**-0.5),
                name="lm_head",
            )

    def encode(self, input_ids, attention_mask, deterministic: bool = True):
        return self.encoder(self.shared(input_ids), attention_mask, deterministic)

    def _head(self, hidden):
        cfg = self.config
        if cfg.tie_word_embeddings:
            hidden = hidden * (cfg.d_model**-0.5)
            return hidden @ self.shared.embedding.T.astype(hidden.dtype)
        return self.lm_head(hidden)

    def init_decode_cache(self, decoder_input_ids, encoder_hidden, encoder_mask):
        """One real decoder pass (no LM head) whose purpose is the
        CROSS-ATTENTION K/V: computed from the encoder output once and
        stored in the cache, turning every subsequent decode step from
        compute-bound (re-projecting the whole encoder sequence) into
        bandwidth-bound (streaming the cached K/V).  Callers pass a qlen-1
        dummy — the self-attention slabs this pass creates are wrong-sized
        throwaways; ``generate.init_cache`` grafts only the ``cross_attn``
        entries onto an eval_shape-zeroed full-size tree."""
        self.decoder(
            self.shared(decoder_input_ids), encoder_hidden, encoder_mask,
            decode=True,
        )

    def decode(
        self, decoder_input_ids, encoder_hidden, encoder_mask,
        decoder_attention_mask=None, decode: bool = False,
        deterministic: bool = True,
    ):
        hidden = self.decoder(
            self.shared(decoder_input_ids), encoder_hidden, encoder_mask,
            dec_mask=decoder_attention_mask, decode=decode,
            deterministic=deterministic,
        )
        return self._head(hidden)

    def __call__(
        self, input_ids, attention_mask, decoder_input_ids,
        decoder_attention_mask=None, deterministic: bool = True,
    ):
        enc = self.encode(input_ids, attention_mask, deterministic)
        return self.decode(
            decoder_input_ids, enc, attention_mask,
            decoder_attention_mask=decoder_attention_mask,
            deterministic=deterministic,
        )


# -- training-loss helpers ---------------------------------------------------


def shift_right(labels: Array, decoder_start_token_id: int, pad_token_id: int) -> Array:
    """Teacher-forcing inputs: [start, y_0, ..., y_{n-2}]."""
    shifted = jnp.roll(labels, 1, axis=-1)
    shifted = shifted.at[:, 0].set(decoder_start_token_id)
    return jnp.where(shifted == -100, pad_token_id, shifted)


def cross_entropy_loss(
    logits: Array, labels: Array, pad_token_id: int
) -> tuple[Array, Array]:
    """Mean CE over non-pad label positions. Returns (loss, num_tokens)."""
    mask = (labels != pad_token_id) & (labels != -100)
    safe = jnp.where(mask, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    ntok = jnp.maximum(mask.sum(), 1)
    return (nll * mask).sum() / ntok, ntok
