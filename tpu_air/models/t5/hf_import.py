"""HF → Flax weight import for T5.

SURVEY.md §7 hard-part 4: bit-faithful import of HF torch weights into this
framework's param tree so `google/flan-t5-*` checkpoints load directly
(Model_finetuning…ipynb:cc-25 loads them via transformers).  Pure-numpy
conversion — torch is only needed to *read* the source state dict.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .config import T5Config


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def _attn_in(w, heads: int, d_kv: int) -> np.ndarray:
    # torch [heads*d_kv, d_model] → DenseGeneral kernel [d_model, heads, d_kv]
    w = np.asarray(w)
    return np.ascontiguousarray(w.T.reshape(w.shape[1], heads, d_kv))


def _attn_out(w, heads: int, d_kv: int) -> np.ndarray:
    # torch [d_model, heads*d_kv] → DenseGeneral kernel [heads, d_kv, d_model]
    w = np.asarray(w)
    return np.ascontiguousarray(w.T.reshape(heads, d_kv, w.shape[0]))


def convert_t5_state_dict(sd: Dict[str, Any], config: T5Config) -> Dict[str, Any]:
    """Map an HF torch T5 state_dict (numpy-convertible values) onto this
    framework's param tree."""
    h, dkv = config.num_heads, config.d_kv
    sd = {k: np.asarray(v) for k, v in sd.items()}
    params: Dict[str, Any] = {"shared": {"embedding": sd["shared.weight"]}}

    def attn(prefix: str) -> Dict[str, Any]:
        return {
            "q": {"kernel": _attn_in(sd[f"{prefix}.q.weight"], h, dkv)},
            "k": {"kernel": _attn_in(sd[f"{prefix}.k.weight"], h, dkv)},
            "v": {"kernel": _attn_in(sd[f"{prefix}.v.weight"], h, dkv)},
            "o": {"kernel": _attn_out(sd[f"{prefix}.o.weight"], h, dkv)},
        }

    def mlp(prefix: str) -> Dict[str, Any]:
        out = {"wo": {"kernel": _t(sd[f"{prefix}.wo.weight"])}}
        if config.is_gated_act:
            out["wi_0"] = {"kernel": _t(sd[f"{prefix}.wi_0.weight"])}
            out["wi_1"] = {"kernel": _t(sd[f"{prefix}.wi_1.weight"])}
        else:
            out["wi"] = {"kernel": _t(sd[f"{prefix}.wi.weight"])}
        return out

    enc: Dict[str, Any] = {
        "rel_bias": {
            "embedding": sd[
                "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ]
        },
        "final_ln": {"weight": sd["encoder.final_layer_norm.weight"]},
    }
    for i in range(config.num_layers):
        b = f"encoder.block.{i}"
        enc[f"layer_{i}"] = {
            "self_attn": attn(f"{b}.layer.0.SelfAttention"),
            "ln_self": {"weight": sd[f"{b}.layer.0.layer_norm.weight"]},
            "mlp": mlp(f"{b}.layer.1.DenseReluDense"),
            "ln_mlp": {"weight": sd[f"{b}.layer.1.layer_norm.weight"]},
        }
    params["encoder"] = enc

    dec: Dict[str, Any] = {
        "rel_bias": {
            "embedding": sd[
                "decoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ]
        },
        "final_ln": {"weight": sd["decoder.final_layer_norm.weight"]},
    }
    for i in range(config.num_decoder_layers):
        b = f"decoder.block.{i}"
        dec[f"layer_{i}"] = {
            "self_attn": attn(f"{b}.layer.0.SelfAttention"),
            "ln_self": {"weight": sd[f"{b}.layer.0.layer_norm.weight"]},
            "cross_attn": attn(f"{b}.layer.1.EncDecAttention"),
            "ln_cross": {"weight": sd[f"{b}.layer.1.layer_norm.weight"]},
            "mlp": mlp(f"{b}.layer.2.DenseReluDense"),
            "ln_mlp": {"weight": sd[f"{b}.layer.2.layer_norm.weight"]},
        }
    params["decoder"] = dec

    if not config.tie_word_embeddings:
        params["lm_head"] = {"kernel": _t(sd["lm_head.weight"])}
    return params


def config_from_hf(hf_config) -> T5Config:
    return T5Config(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.d_model,
        d_kv=hf_config.d_kv,
        d_ff=hf_config.d_ff,
        num_layers=hf_config.num_layers,
        num_decoder_layers=hf_config.num_decoder_layers,
        num_heads=hf_config.num_heads,
        relative_attention_num_buckets=hf_config.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(
            hf_config, "relative_attention_max_distance", 128
        ),
        dropout_rate=hf_config.dropout_rate,
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        feed_forward_proj=hf_config.feed_forward_proj.replace("gated-gelu_new", "gated-gelu"),
        tie_word_embeddings=hf_config.tie_word_embeddings,
        pad_token_id=hf_config.pad_token_id,
        eos_token_id=hf_config.eos_token_id,
        decoder_start_token_id=hf_config.decoder_start_token_id,
    )


def load_t5_from_hf(name_or_path: str, dtype: str = "float32"):
    """Load a (local) HF T5 checkpoint into (model, params).  Network
    availability is the caller's concern — in air-gapped environments point
    this at a downloaded directory."""
    from transformers import T5ForConditionalGeneration as TorchT5

    from .modeling import T5ForConditionalGeneration

    torch_model = TorchT5.from_pretrained(name_or_path)
    config = config_from_hf(torch_model.config)
    config.dtype = dtype
    sd = {k: v.detach().cpu().numpy() for k, v in torch_model.state_dict().items()}
    params = convert_t5_state_dict(sd, config)
    model = T5ForConditionalGeneration(config)
    import jax.numpy as jnp

    params = __import__("jax").tree_util.tree_map(lambda x: jnp.asarray(x), params)
    return model, params
