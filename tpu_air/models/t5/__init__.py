"""Flax T5 / FLAN-T5 model family."""

from .config import T5Config
from .generate import (
    generate,
    make_generate_fn,
    make_t5_decode_step_fn,
    make_t5_prefill_fn,
)
from .hf_import import config_from_hf, convert_t5_state_dict, load_t5_from_hf
from .modeling import (
    T5ForConditionalGeneration,
    cross_entropy_loss,
    shift_right,
)

__all__ = [
    "T5Config",
    "T5ForConditionalGeneration",
    "config_from_hf",
    "convert_t5_state_dict",
    "cross_entropy_loss",
    "generate",
    "load_t5_from_hf",
    "make_generate_fn",
    "make_t5_decode_step_fn",
    "make_t5_prefill_fn",
    "shift_right",
]
