"""T5 configuration.

Covers the FLAN-T5 family the reference fine-tunes and generates with
(`google/flan-t5-base`, Model_finetuning…ipynb:cc-25,35; sizes small→xl per
BASELINE.json configs).  FLAN-T5 is the T5 v1.1 architecture: gated-GELU MLP,
untied embedding/lm_head, RMSNorm, relative position bias, no attention
score scaling.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass
from typing import Optional


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 1024
    num_layers: int = 8
    num_decoder_layers: Optional[int] = None
    num_heads: int = 6
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    dropout_rate: float = 0.1
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "gated-gelu"  # v1.1 / FLAN; "relu" for t5 v1.0
    tie_word_embeddings: bool = False
    pad_token_id: int = 0
    eos_token_id: int = 1
    decoder_start_token_id: int = 0
    # dtype policy: bf16 activations on TPU (fp16-on-GPU analog of
    # Model_finetuning…ipynb:cc-64), fp32 params.
    dtype: str = "float32"
    # Attention dispatch.  ``attention_impl`` picks per-call at TRACE time:
    # * "auto"   — einsum below ``flash_min_seq_len``, Pallas flash at or
    #   above it (the measured v5e crossover: dense wins at 512, flash is
    #   3.5-5x at >=2048 — BASELINE.md kernel table); no user flag needed.
    # * "einsum" — always the XLA dense path.
    # * "flash"  — always the Pallas kernel where eligible.
    # Flash is only eligible off the cached-decode path with structured
    # masks and inactive attention dropout (see modeling.Attention).
    # ``use_flash_attention`` is the legacy force-flash switch (== "flash").
    attention_impl: str = "auto"
    flash_min_seq_len: int = 1024
    use_flash_attention: bool = False
    # Opt-in int8 cross-attention K/V cache for cached decode: the cross
    # K/V are the dominant HBM term of every decode step (B x enc_len x
    # n_heads x d_kv x 2 x layers, re-read per emitted token); storing them
    # int8 with per-(batch, head, channel) scales halves that traffic at
    # the cost of quantization error in the cross-attention scores.  Off by
    # default — the reference decodes fp16 (cc-64); numerics parity is
    # tested at tolerance in tests/test_t5.py.
    decode_cache_int8: bool = False
    # Cached-decode attention dispatch (ops/decode_attention.py).  Caches
    # are stored FLAT [b, L, h*d] (the 4-D layout cost 2.67x physical HBM
    # bytes to tile padding — the r5 decode bottleneck).  "auto" follows
    # the BENCH r5 measurement at the W3 dials: full-width caches decode
    # through XLA's dense path reconstructed from the flat slab (179.2
    # seq/s, 0.80 of the v5e HBM roofline — XLA's own fusion wins once
    # the carry layout is flat), int8 caches through the flat block-
    # diagonal formulation whose scale FOLDS never materialize a
    # dequantized slab (213.7 seq/s vs a 9.4 GB/step materialization
    # bound).  Explicit values pin one path: "flat" = block-diagonal
    # formulation; "einsum" = dense reconstruction; "pallas" = the fused
    # kernel (measured slower — kept as the measured alternative,
    # interpret mode off-TPU).
    decode_attention_impl: str = "auto"

    def __post_init__(self):
        if self.num_decoder_layers is None:
            self.num_decoder_layers = self.num_layers

    @property
    def is_gated_act(self) -> bool:
        return "gated" in self.feed_forward_proj

    @property
    def act_fn(self) -> str:
        proj = self.feed_forward_proj
        return proj.split("-")[-1] if "-" in proj else proj

    # -- presets -----------------------------------------------------------
    @classmethod
    def tiny(cls, vocab_size: int = 384) -> "T5Config":
        """Test-dial config (SURVEY.md §4.2 smallest-variant strategy)."""
        return cls(
            vocab_size=vocab_size, d_model=64, d_kv=16, d_ff=128,
            num_layers=2, num_heads=4, dropout_rate=0.0,
        )

    @classmethod
    def flan_t5_small(cls) -> "T5Config":
        return cls(d_model=512, d_kv=64, d_ff=1024, num_layers=8, num_heads=6)

    @classmethod
    def flan_t5_base(cls) -> "T5Config":
        return cls(d_model=768, d_kv=64, d_ff=2048, num_layers=12, num_heads=12)

    @classmethod
    def flan_t5_large(cls) -> "T5Config":
        return cls(d_model=1024, d_kv=64, d_ff=2816, num_layers=24, num_heads=16)

    @classmethod
    def flan_t5_xl(cls) -> "T5Config":
        return cls(d_model=2048, d_kv=64, d_ff=5120, num_layers=24, num_heads=32)

    @classmethod
    def from_name(cls, name: str) -> "T5Config":
        key = name.split("/")[-1].replace("flan-t5-", "").replace("t5-", "")
        presets = {
            "tiny": cls.tiny,
            "small": cls.flan_t5_small,
            "base": cls.flan_t5_base,
            "large": cls.flan_t5_large,
            "xl": cls.flan_t5_xl,
        }
        if key not in presets:
            raise ValueError(f"unknown T5 preset {name!r}")
        return presets[key]()

    # -- (de)serialization — checkpoints store the config ------------------
    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "T5Config":
        return cls(**{k: v for k, v in d.items() if k in {f.name for f in dataclasses.fields(cls)}})

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, s: str) -> "T5Config":
        return cls.from_dict(json.loads(s))
