"""Autoregressive generation under jit.

The reference calls torch ``model.generate(**inputs, max_new_tokens=128)``
(predictor.py:102; Model_finetuning…ipynb:cc-67).  TPU-native version: a
fixed-shape `lax.scan` decode loop over a pre-allocated KV cache — no Python
control flow, no recompiles across batches of the same shape (SURVEY.md §7
hard-part 2).  Cache tensors are built with `jax.eval_shape`, so cache
construction costs nothing.

Greedy decoding is the default (matching the reference's ``generate`` call,
which passes no sampling flags); temperature/top-k sampling is available.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .config import T5Config
from .modeling import T5ForConditionalGeneration


def init_cache(model, params, batch_size: int, max_decode_len: int,
               enc_hidden, enc_mask):
    """Build the decode cache.

    Self-attention slabs and bookkeeping come from ``eval_shape`` (free);
    the cross-attention K/V — an invariant of the encoder output — come
    from ONE real qlen-1 decoder pass (``init_decode_cache``) whose only
    meaningful compute is the per-layer K/V projections of ``enc_hidden``.
    The two trees are grafted: everything under a ``cross_attn`` module is
    taken from the real pass, the rest from the zeroed full-size tree."""

    def _init():
        return model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((batch_size, max_decode_len), jnp.int32),
            enc_hidden,
            enc_mask,
            decode=True,
            method=model.decode,
        )

    shapes = jax.eval_shape(_init)["cache"]
    cache = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )
    _, vars1 = model.apply(
        {"params": params},
        jnp.zeros((batch_size, 1), jnp.int32),
        enc_hidden,
        enc_mask,
        mutable=["cache"],
        method=model.init_decode_cache,
    )

    def graft(dst, src, under_cross=False):
        for k, v in src.items():
            if isinstance(v, dict):
                graft(dst[k], v, under_cross or k == "cross_attn")
            elif under_cross:
                dst[k] = v

    from flax.core import unfreeze

    cache = unfreeze(cache)
    graft(cache, unfreeze(vars1["cache"]))
    return cache


from tpu_air.models.sampling import sample_token as _sample_token  # noqa: E402


def make_generate_fn(
    model: T5ForConditionalGeneration,
    max_new_tokens: int = 128,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    early_stop: bool = True,
):
    """Build a jit-compiled ``(params, input_ids, attention_mask, rng) ->
    (sequences, steps_taken)`` function with a fixed decode budget.

    ``early_stop=True`` (the default, matching the reference's torch
    ``model.generate`` stopping criterion — predictor.py:102) runs the
    decode as a ``lax.while_loop`` that exits once EVERY sequence has
    emitted EOS; outputs are identical to the full-budget scan (finished
    rows emit pad either way), the remaining steps are just not executed.
    ``early_stop=False`` keeps the fixed-trip ``lax.scan`` — what the
    bench measures, so throughput numbers always reflect the full budget.
    """
    cfg: T5Config = model.config
    start_id = cfg.decoder_start_token_id
    eos_id = cfg.eos_token_id
    pad_id = cfg.pad_token_id

    @jax.jit
    def generate_fn(params, input_ids, attention_mask, rng):
        batch = input_ids.shape[0]
        enc = model.apply(
            {"params": params}, input_ids, attention_mask, method=model.encode
        )
        cache = init_cache(model, params, batch, max_new_tokens + 1, enc,
                           attention_mask)
        tok0 = jnp.full((batch,), start_id, dtype=jnp.int32)
        # an all-pad input row is vacuous (bucket padding, empty inputs):
        # born finished, it emits pure pad and never blocks early-stop
        finished0 = jnp.sum(attention_mask, axis=-1) == 0

        def decode_one(tok, cache, finished, rng):
            logits, vars_out = model.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                enc,
                attention_mask,
                decode=True,
                mutable=["cache"],
                method=model.decode,
            )
            rng, sub = jax.random.split(rng)
            nxt = _sample_token(
                logits[:, -1, :], sub, do_sample, temperature, top_k
            )
            nxt = jnp.where(finished, pad_id, nxt)
            finished = finished | (nxt == eos_id)
            return nxt, vars_out["cache"], finished, rng

        if early_stop:
            toks0 = jnp.full((batch, max_new_tokens), pad_id, jnp.int32)

            def cond(carry):
                step, _, _, finished, _, _ = carry
                return (step < max_new_tokens) & ~jnp.all(finished)

            def body(carry):
                step, tok, cache, finished, rng, toks = carry
                nxt, cache, finished, rng = decode_one(tok, cache, finished, rng)
                toks = jax.lax.dynamic_update_slice(
                    toks, nxt[:, None], (0, step)
                )
                return (step + 1, nxt, cache, finished, rng, toks)

            step, _, _, _, _, toks = jax.lax.while_loop(
                cond, body, (jnp.asarray(0), tok0, cache, finished0, rng, toks0)
            )
            return toks, step

        def step(carry, _):
            tok, cache, finished, rng = carry
            nxt, cache, finished, rng = decode_one(tok, cache, finished, rng)
            return (nxt, cache, finished, rng), nxt

        (_, _, _, _), toks = jax.lax.scan(
            step, (tok0, cache, finished0, rng), None, length=max_new_tokens
        )
        return jnp.transpose(toks), jnp.asarray(max_new_tokens)

    return generate_fn


# ---------------------------------------------------------------------------
# Continuous-batching entry points (tpu_air.engine)
#
# make_generate_fn keeps the encode+cache-build prefill and the per-token
# decode private inside one jitted program.  These expose the two phases as
# standalone compiled units so an online engine can admit/retire between
# steps.  Encoder-decoder caveat: the decode cache carries the CROSS-
# attention K/V of the whole batch's encoder output, so these entry points
# are batch-synchronized (one scalar cache index — every row at the same
# decode position); per-slot cross-attn slabs are the remaining work before
# the slot engine (engine/engine.py) can drive the T5 family.
# ---------------------------------------------------------------------------


def make_t5_prefill_fn(model: T5ForConditionalGeneration,
                       max_decode_len: int):
    """Build a jitted ``fn(params, input_ids, attention_mask) ->
    (first_tok, cache, enc_hidden)``: encode the prompts, build the decode
    cache (self-attn slabs zeroed, cross-attn K/V computed from the encoder
    output — the prefill-into-segment), and run the first decode step from
    ``decoder_start_token_id``, returning the first greedy token."""
    cfg: T5Config = model.config

    @jax.jit
    def prefill(params, input_ids, attention_mask):
        batch = input_ids.shape[0]
        enc = model.apply(
            {"params": params}, input_ids, attention_mask, method=model.encode
        )
        cache = init_cache(model, params, batch, max_decode_len, enc,
                           attention_mask)
        tok0 = jnp.full((batch, 1), cfg.decoder_start_token_id, jnp.int32)
        logits, vars_ = model.apply(
            {"params": params, "cache": cache}, tok0, enc, attention_mask,
            decode=True, mutable=["cache"], method=model.decode,
        )
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return tok, vars_["cache"], enc

    return prefill


def make_t5_decode_step_fn(model: T5ForConditionalGeneration):
    """Build a jitted single-token decode step ``fn(params, cache, tok,
    enc_hidden, enc_mask) -> (cache', next_tok)`` with the cache donated —
    the per-step unit an online loop re-invokes, greedy (the engine parity
    anchor)."""
    from functools import partial

    @partial(jax.jit, donate_argnums=(1,))
    def step(params, cache, tok, enc_hidden, enc_mask):
        logits, vars_ = model.apply(
            {"params": params, "cache": cache}, tok[:, None], enc_hidden,
            enc_mask, decode=True, mutable=["cache"], method=model.decode,
        )
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        return vars_["cache"], nxt

    return step


_GEN_CACHE: Dict[Tuple, Any] = {}
_GEN_CACHE_MAX = 16


def generate(
    model: T5ForConditionalGeneration,
    params,
    input_ids,
    attention_mask=None,
    max_new_tokens: int = 128,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int = 0,
    rng: Optional[jax.Array] = None,
    early_stop: bool = True,
):
    """Convenience wrapper caching compiled generate fns per config."""
    input_ids = jnp.asarray(input_ids, dtype=jnp.int32)
    if attention_mask is None:
        attention_mask = (input_ids != model.config.pad_token_id).astype(jnp.int32)
    else:
        attention_mask = jnp.asarray(attention_mask, dtype=jnp.int32)
    # key by config content, not id(model): model objects are rebuilt per
    # Checkpoint.get_model() call and ids can be reused after GC
    cfg_key = tuple(sorted(model.config.to_dict().items()))
    key = (cfg_key, max_new_tokens, do_sample, temperature, top_k, early_stop)
    if key not in _GEN_CACHE:
        if len(_GEN_CACHE) >= _GEN_CACHE_MAX:
            _GEN_CACHE.pop(next(iter(_GEN_CACHE)))
        _GEN_CACHE[key] = make_generate_fn(
            model, max_new_tokens, do_sample, temperature, top_k, early_stop
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # batch-size BUCKETING (SURVEY.md §7 hard-part 2): pad the batch up to
    # the next power of two with all-pad rows (born finished, emit pad, cost
    # ~0 under early_stop) so a stream of blocks with a ragged tail reuses
    # one compiled program instead of retracing per batch size.  GREEDY
    # outputs are bit-identical to the unpadded batch; SAMPLED outputs are
    # distributionally equivalent but not bitwise reproducible across
    # bucket sizes (the per-position sampling noise is keyed by the padded
    # batch shape).
    n = input_ids.shape[0]
    bucket = 1 << max(0, int(n - 1).bit_length())
    if bucket != n:
        pad_id = model.config.pad_token_id
        L = input_ids.shape[1]
        input_ids = jnp.concatenate(
            [input_ids, jnp.full((bucket - n, L), pad_id, jnp.int32)]
        )
        attention_mask = jnp.concatenate(
            [attention_mask, jnp.zeros((bucket - n, L), jnp.int32)]
        )
    seqs, _steps = _GEN_CACHE[key](params, input_ids, attention_mask, rng)
    return seqs[:n]
