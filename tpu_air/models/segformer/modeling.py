"""SegFormer in Flax, written TPU-first (NHWC, static shapes, fused via XLA).

From-scratch implementation of the hierarchical Mix-Transformer encoder and
the all-MLP decode head (SegFormer, Xie et al. 2021).  Capability target: the
reference's `SegformerForSemanticSegmentation` fine-tune of `nvidia/mit-b0`
(Scaling_model_training.ipynb:cc-15-16,52) and batch inference with
`segformer-b0-finetuned-ade-512-512` (Scaling_batch_inference.ipynb:cc-19-24).

Design notes (TPU):
- NHWC layout everywhere — XLA:TPU's native conv layout; the MXU sees the
  channel dim contiguous.
- Attention over the flattened (H*W) sequence with spatial-reduction convs;
  softmax in f32, matmuls in the config dtype (bf16 on TPU).
- BatchNorm in the decode head carries a `batch_stats` collection; training
  steps pass `mutable=["batch_stats"]`.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .config import SegformerConfig

Array = Any


def _dtype(config: SegformerConfig):
    return jnp.dtype(config.dtype)


def _resize_bilinear(x: Array, h: int, w: int) -> Array:
    """Bilinear resize on NHWC, half-pixel centers (== torch align_corners=False)."""
    return jax.image.resize(x, (x.shape[0], h, w, x.shape[3]), method="bilinear")


class DropPath(nn.Module):
    """Per-sample stochastic depth (the SegFormer block regularizer)."""

    rate: float

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        if deterministic or self.rate == 0.0:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("dropout")
        shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0)


class OverlapPatchEmbed(nn.Module):
    """Overlapping patch embedding: strided conv + LayerNorm."""

    config: SegformerConfig
    patch_size: int
    stride: int
    hidden_size: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        p = self.patch_size // 2
        x = nn.Conv(
            self.hidden_size,
            kernel_size=(self.patch_size, self.patch_size),
            strides=(self.stride, self.stride),
            padding=[(p, p), (p, p)],
            dtype=_dtype(self.config),
            name="proj",
        )(x)
        x = nn.LayerNorm(epsilon=self.config.layer_norm_eps, dtype=_dtype(self.config),
                         name="layer_norm")(x)
        return x


class EfficientSelfAttention(nn.Module):
    """MHA over the flattened spatial sequence with sequence-reduction convs.

    The sr conv shrinks K/V spatially by `sr_ratio`, so attention cost is
    O(N * N/sr^2) — this is what makes stage-1 (N = (H/4)(W/4)) tractable.
    """

    config: SegformerConfig
    hidden_size: int
    num_heads: int
    sr_ratio: int

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        cfg, dt = self.config, _dtype(self.config)
        b, h, w, c = x.shape
        head_dim = self.hidden_size // self.num_heads

        q = nn.Dense(self.hidden_size, dtype=dt, name="query")(x.reshape(b, h * w, c))

        kv_src = x
        if self.sr_ratio > 1:
            kv_src = nn.Conv(
                self.hidden_size,
                kernel_size=(self.sr_ratio, self.sr_ratio),
                strides=(self.sr_ratio, self.sr_ratio),
                padding="VALID",
                dtype=dt,
                name="sr",
            )(x)
            kv_src = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dt,
                                  name="sr_norm")(kv_src)
        n_kv = kv_src.shape[1] * kv_src.shape[2]
        kv_src = kv_src.reshape(b, n_kv, self.hidden_size)
        k = nn.Dense(self.hidden_size, dtype=dt, name="key")(kv_src)
        v = nn.Dense(self.hidden_size, dtype=dt, name="value")(kv_src)

        q = q.reshape(b, h * w, self.num_heads, head_dim)
        k = k.reshape(b, n_kv, self.num_heads, head_dim)
        v = v.reshape(b, n_kv, self.num_heads, head_dim)

        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(head_dim)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        probs = nn.Dropout(cfg.attention_probs_dropout_prob)(
            probs, deterministic=deterministic
        )
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, h * w, self.hidden_size)
        out = nn.Dense(self.hidden_size, dtype=dt, name="out")(out)
        out = nn.Dropout(cfg.hidden_dropout_prob)(out, deterministic=deterministic)
        return out.reshape(b, h, w, self.hidden_size)


class MixFFN(nn.Module):
    """Mix-FFN: dense → 3x3 depthwise conv (positional signal) → GELU → dense."""

    config: SegformerConfig
    hidden_size: int
    mlp_ratio: int

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        cfg, dt = self.config, _dtype(self.config)
        inner = self.hidden_size * self.mlp_ratio
        x = nn.Dense(inner, dtype=dt, name="dense1")(x)
        x = nn.Conv(
            inner,
            kernel_size=(3, 3),
            padding=[(1, 1), (1, 1)],
            feature_group_count=inner,
            dtype=dt,
            name="dwconv",
        )(x)
        x = jax.nn.gelu(x, approximate=False)
        x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic=deterministic)
        x = nn.Dense(self.hidden_size, dtype=dt, name="dense2")(x)
        x = nn.Dropout(cfg.hidden_dropout_prob)(x, deterministic=deterministic)
        return x


class Block(nn.Module):
    config: SegformerConfig
    hidden_size: int
    num_heads: int
    sr_ratio: int
    mlp_ratio: int
    drop_path: float

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        cfg, dt = self.config, _dtype(self.config)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dt, name="layer_norm_1")(x)
        h = EfficientSelfAttention(
            cfg, self.hidden_size, self.num_heads, self.sr_ratio, name="attention"
        )(h, deterministic)
        x = x + DropPath(self.drop_path, name="drop_path_attn")(h, deterministic)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=dt, name="layer_norm_2")(x)
        h = MixFFN(cfg, self.hidden_size, self.mlp_ratio, name="mlp")(h, deterministic)
        x = x + DropPath(self.drop_path, name="drop_path_mlp")(h, deterministic)
        return x


class SegformerEncoder(nn.Module):
    """4-stage hierarchical encoder; returns all stage feature maps (NHWC)."""

    config: SegformerConfig

    @nn.compact
    def __call__(self, pixel_values: Array, deterministic: bool = True) -> List[Array]:
        cfg = self.config
        # linearly-increasing stochastic-depth schedule over total depth
        total = sum(cfg.depths)
        dp_rates = [cfg.drop_path_rate * i / max(total - 1, 1) for i in range(total)]

        x = pixel_values
        features: List[Array] = []
        cursor = 0
        for s in range(cfg.num_encoder_blocks):
            x = OverlapPatchEmbed(
                cfg,
                cfg.patch_sizes[s],
                cfg.strides[s],
                cfg.hidden_sizes[s],
                name=f"patch_embed_{s}",
            )(x)
            for d in range(cfg.depths[s]):
                x = Block(
                    cfg,
                    cfg.hidden_sizes[s],
                    cfg.num_attention_heads[s],
                    cfg.sr_ratios[s],
                    cfg.mlp_ratios[s],
                    dp_rates[cursor],
                    name=f"block_{s}_{d}",
                )(x, deterministic)
                cursor += 1
            x = nn.LayerNorm(
                epsilon=cfg.layer_norm_eps,
                dtype=_dtype(cfg),
                name=f"stage_norm_{s}",
            )(x)
            features.append(x)
        return features


class SegformerDecodeHead(nn.Module):
    """All-MLP decode head: per-stage linear → upsample to 1/4 res → fuse."""

    config: SegformerConfig

    @nn.compact
    def __call__(self, features: List[Array], deterministic: bool = True) -> Array:
        cfg, dt = self.config, _dtype(self.config)
        h0, w0 = features[0].shape[1], features[0].shape[2]
        projected = []
        for i, f in enumerate(features):
            p = nn.Dense(cfg.decoder_hidden_size, dtype=dt, name=f"linear_c_{i}")(f)
            if i > 0:
                p = _resize_bilinear(p, h0, w0)
            projected.append(p)
        # fuse deepest-first (matches the published head's concat order)
        x = jnp.concatenate(projected[::-1], axis=-1)
        x = nn.Conv(
            cfg.decoder_hidden_size,
            kernel_size=(1, 1),
            use_bias=False,
            dtype=dt,
            name="linear_fuse",
        )(x)
        x = nn.BatchNorm(
            use_running_average=deterministic,
            momentum=0.9,
            epsilon=1e-5,
            dtype=dt,
            name="batch_norm",
        )(x)
        x = nn.relu(x)
        x = nn.Dropout(cfg.classifier_dropout_prob)(x, deterministic=deterministic)
        logits = nn.Conv(cfg.num_labels, kernel_size=(1, 1), dtype=dt,
                         name="classifier")(x)
        return logits  # (B, H/4, W/4, num_labels)


class SegformerForSemanticSegmentation(nn.Module):
    """Encoder + decode head.  Input NHWC; logits at 1/4 input resolution."""

    config: SegformerConfig

    def setup(self):
        self.encoder = SegformerEncoder(self.config)
        self.decode_head = SegformerDecodeHead(self.config)

    def __call__(self, pixel_values: Array, deterministic: bool = True) -> Array:
        features = self.encoder(pixel_values, deterministic)
        return self.decode_head(features, deterministic)

    def features(self, pixel_values: Array, deterministic: bool = True) -> List[Array]:
        return self.encoder(pixel_values, deterministic)


class SegformerForImageClassification(nn.Module):
    """MiT backbone + mean-pool + linear head (the `nvidia/mit-b0` form)."""

    config: SegformerConfig
    num_classes: int = 1000

    @nn.compact
    def __call__(self, pixel_values: Array, deterministic: bool = True) -> Array:
        feats = SegformerEncoder(self.config, name="encoder")(pixel_values, deterministic)
        x = feats[-1]
        x = x.reshape(x.shape[0], -1, x.shape[-1]).mean(axis=1)
        return nn.Dense(self.num_classes, dtype=_dtype(self.config), name="classifier")(x)


def segmentation_loss(
    logits: Array,
    labels: Array,
    ignore_index: int = 255,
) -> Array:
    """Cross-entropy vs full-resolution integer label maps.

    Upsamples the 1/4-resolution logits to the label size (the published
    model's training objective) and masks `ignore_index` pixels.
    logits: (B, h, w, L) NHWC; labels: (B, H, W) int.
    """
    h, w = labels.shape[1], labels.shape[2]
    logits = _resize_bilinear(logits.astype(jnp.float32), h, w)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)
