"""SegFormer model family (Flax, NHWC, TPU-first).

Covers the reference's semantic-segmentation workloads W6/W7
(Scaling_model_training.ipynb, Scaling_batch_inference.ipynb).
"""

from .config import SegformerConfig
from .hf_import import (
    config_from_hf,
    convert_segformer_state_dict,
    load_segformer_from_hf,
)
from .image_processor import (
    SegformerFeatureExtractor,
    SegformerImageProcessor,
)
from .modeling import (
    SegformerForImageClassification,
    SegformerForSemanticSegmentation,
    segmentation_loss,
)

__all__ = [
    "SegformerConfig",
    "SegformerForImageClassification",
    "SegformerForSemanticSegmentation",
    "SegformerImageProcessor",
    "SegformerFeatureExtractor",
    "segmentation_loss",
    "config_from_hf",
    "convert_segformer_state_dict",
    "load_segformer_from_hf",
]
