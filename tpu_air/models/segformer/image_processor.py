"""SegFormer image (pre)processor — host-side, numpy/PIL only.

Capability target: the reference's `SegformerImageProcessor` /
`SegformerFeatureExtractor` usage — `do_reduce_labels=True` preprocessing for
ADE20K fine-tuning (Scaling_model_training.ipynb:cc-38,42) and
`post_process_semantic_segmentation` at inference
(Scaling_batch_inference.ipynb:cc-42).

Host-side by design (SURVEY.md §7 stance: preprocessing stays on CPU/Arrow;
device work enters at step boundaries), NHWC output for the TPU model.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


def _to_numpy_image(img) -> np.ndarray:
    """Accept PIL / numpy HWC / numpy CHW; return uint8-or-float HWC RGB."""
    if hasattr(img, "convert"):  # PIL
        img = np.asarray(img.convert("RGB"))
    img = np.asarray(img)
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    if img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[-1] not in (1, 3):
        img = np.transpose(img, (1, 2, 0))  # CHW → HWC
    if img.shape[-1] == 1:
        img = np.repeat(img, 3, axis=-1)
    return img


def _resize(img: np.ndarray, h: int, w: int, nearest: bool) -> np.ndarray:
    from PIL import Image

    mode = Image.NEAREST if nearest else Image.BILINEAR
    if img.ndim == 2:
        return np.asarray(Image.fromarray(img).resize((w, h), mode))
    # PIL wants uint8/float32 2D or RGB
    if img.dtype != np.uint8:
        chans = [
            np.asarray(Image.fromarray(img[..., c].astype(np.float32), mode="F").resize((w, h), mode))
            for c in range(img.shape[-1])
        ]
        return np.stack(chans, axis=-1)
    return np.asarray(Image.fromarray(img).resize((w, h), mode))


def _resize_int32(lbl: np.ndarray, h: int, w: int) -> np.ndarray:
    """Nearest-neighbor resize for label maps with class ids > 255
    (PIL mode 'I' keeps int32 exact under NEAREST)."""
    from PIL import Image

    return np.asarray(Image.fromarray(lbl, mode="I").resize((w, h), Image.NEAREST))


class SegformerImageProcessor:
    """Resize → rescale → normalize images; resize(nearest) → reduce labels."""

    def __init__(
        self,
        do_resize: bool = True,
        size: Union[int, Dict[str, int], Tuple[int, int]] = 512,
        do_rescale: bool = True,
        rescale_factor: float = 1.0 / 255.0,
        do_normalize: bool = True,
        image_mean: Sequence[float] = IMAGENET_MEAN,
        image_std: Sequence[float] = IMAGENET_STD,
        do_reduce_labels: bool = False,
        data_format: str = "channels_last",
    ):
        if isinstance(size, int):
            size = (size, size)
        elif isinstance(size, dict):
            size = (size["height"], size["width"])
        self.size = tuple(size)
        self.do_resize = do_resize
        self.do_rescale = do_rescale
        self.rescale_factor = rescale_factor
        self.do_normalize = do_normalize
        self.image_mean = np.asarray(image_mean, np.float32)
        self.image_std = np.asarray(image_std, np.float32)
        self.do_reduce_labels = do_reduce_labels
        self.data_format = data_format

    # -- single-image paths -------------------------------------------------
    def _process_image(self, img) -> np.ndarray:
        img = _to_numpy_image(img)
        if self.do_resize:
            img = _resize(img, self.size[0], self.size[1], nearest=False)
        img = img.astype(np.float32)
        if self.do_rescale:
            img = img * self.rescale_factor
        if self.do_normalize:
            img = (img - self.image_mean) / self.image_std
        if self.data_format == "channels_first":
            img = np.transpose(img, (2, 0, 1))
        return img

    def _process_label(self, lbl) -> np.ndarray:
        if hasattr(lbl, "convert"):
            lbl = np.asarray(lbl.convert("L") if lbl.mode not in ("I", "L") else lbl)
        lbl = np.asarray(lbl)
        if lbl.ndim == 3:
            lbl = lbl[..., 0]
        if self.do_reduce_labels:
            # ADE20K convention: 0 = "background/unlabeled" → ignore(255);
            # classes shift down by one.
            lbl = lbl.astype(np.int32)
            lbl = np.where(lbl == 0, 255, lbl - 1)
        if self.do_resize:
            # uint8 is enough for ADE20K (150 classes + ignore=255) but
            # truncates ids > 255 — keep int32 through the resize then
            lbl = np.asarray(lbl)
            if lbl.max(initial=0) < 256:
                lbl = _resize(lbl.astype(np.uint8), self.size[0], self.size[1], nearest=True)
            else:
                lbl = _resize_int32(lbl.astype(np.int32), self.size[0], self.size[1])
        return lbl.astype(np.int32)

    # -- batch entry point --------------------------------------------------
    def __call__(
        self,
        images,
        segmentation_maps=None,
        return_tensors: str = "np",
        **_: Any,
    ) -> Dict[str, np.ndarray]:
        if not isinstance(images, (list, tuple)):
            images = [images]
        out = {"pixel_values": np.stack([self._process_image(i) for i in images])}
        if segmentation_maps is not None:
            if not isinstance(segmentation_maps, (list, tuple)):
                segmentation_maps = [segmentation_maps]
            out["labels"] = np.stack([self._process_label(m) for m in segmentation_maps])
        return out

    preprocess = __call__

    # -- postprocessing -----------------------------------------------------
    def post_process_semantic_segmentation(
        self,
        logits: np.ndarray,
        target_sizes: Optional[List[Tuple[int, int]]] = None,
    ) -> List[np.ndarray]:
        """(B, h, w, L) NHWC logits → per-image (H, W) int class maps.

        Mirrors the reference's
        `feature_extractor.post_process_semantic_segmentation(outputs, sizes)`
        (Scaling_batch_inference.ipynb:cc-42): bilinear-upsample logits to each
        target size, then argmax.  Host-side: PIL bilinear (half-pixel
        centers, same convention as the model's internal resize).
        """
        logits = np.asarray(logits, np.float32)
        results = []
        for i in range(logits.shape[0]):
            lg = logits[i]
            if target_sizes is not None:
                h, w = target_sizes[i]
                lg = _resize(lg, h, w, nearest=False)
            results.append(np.argmax(lg, axis=-1).astype(np.int32))
        return results


def collate_pixel_batch(values) -> np.ndarray:
    """Stack per-row pixel arrays into one NHWC float32 batch, accepting CHW
    rows (torch-layout data) — the single home of the layout heuristic shared
    by the trainer collate and the segmentation predictor."""
    px = np.stack([np.asarray(v, dtype=np.float32) for v in values])
    if px.ndim == 4 and px.shape[1] in (1, 3) and px.shape[-1] not in (1, 3):
        px = px.transpose(0, 2, 3, 1)
    return px


# The reference imports both names (Scaling_batch_inference.ipynb:cc-24).
SegformerFeatureExtractor = SegformerImageProcessor
