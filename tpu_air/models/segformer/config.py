"""SegFormer (Mix Transformer, MiT) configuration.

The reference fine-tunes `nvidia/mit-b0` for semantic segmentation on ADE20K
(Scaling_model_training.ipynb:cc-16) and runs batch inference with
`nvidia/segformer-b0-finetuned-ade-512-512`
(Scaling_batch_inference.ipynb:cc-20-21).  This config covers the full MiT
family (b0-b5); defaults are MiT-b0.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import List


@dataclass
class SegformerConfig:
    num_channels: int = 3
    num_encoder_blocks: int = 4
    depths: List[int] = field(default_factory=lambda: [2, 2, 2, 2])
    sr_ratios: List[int] = field(default_factory=lambda: [8, 4, 2, 1])
    hidden_sizes: List[int] = field(default_factory=lambda: [32, 64, 160, 256])
    patch_sizes: List[int] = field(default_factory=lambda: [7, 3, 3, 3])
    strides: List[int] = field(default_factory=lambda: [4, 2, 2, 2])
    num_attention_heads: List[int] = field(default_factory=lambda: [1, 2, 5, 8])
    mlp_ratios: List[int] = field(default_factory=lambda: [4, 4, 4, 4])
    hidden_dropout_prob: float = 0.0
    attention_probs_dropout_prob: float = 0.0
    classifier_dropout_prob: float = 0.1
    drop_path_rate: float = 0.1
    layer_norm_eps: float = 1e-6
    decoder_hidden_size: int = 256
    num_labels: int = 150
    semantic_loss_ignore_index: int = 255
    dtype: str = "float32"

    @classmethod
    def from_dict(cls, d: dict) -> "SegformerConfig":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in names})

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["model_type"] = "segformer"  # checkpoint-loader dispatch key
        return json.dumps(d)

    @classmethod
    def from_json(cls, s: str) -> "SegformerConfig":
        return cls.from_dict(json.loads(s))

    @classmethod
    def mit_b0(cls, **kw) -> "SegformerConfig":
        return cls(**kw)

    @classmethod
    def mit_b1(cls, **kw) -> "SegformerConfig":
        return cls(hidden_sizes=[64, 128, 320, 512], decoder_hidden_size=256, **kw)

    @classmethod
    def mit_b2(cls, **kw) -> "SegformerConfig":
        return cls(
            hidden_sizes=[64, 128, 320, 512],
            depths=[3, 4, 6, 3],
            decoder_hidden_size=768,
            **kw,
        )

    @classmethod
    def mit_b3(cls, **kw) -> "SegformerConfig":
        return cls(
            hidden_sizes=[64, 128, 320, 512],
            depths=[3, 4, 18, 3],
            decoder_hidden_size=768,
            **kw,
        )

    @classmethod
    def mit_b4(cls, **kw) -> "SegformerConfig":
        return cls(
            hidden_sizes=[64, 128, 320, 512],
            depths=[3, 8, 27, 3],
            decoder_hidden_size=768,
            **kw,
        )

    @classmethod
    def mit_b5(cls, **kw) -> "SegformerConfig":
        return cls(
            hidden_sizes=[64, 128, 320, 512],
            depths=[3, 6, 40, 3],
            decoder_hidden_size=768,
            **kw,
        )

    @classmethod
    def tiny(cls, **kw) -> "SegformerConfig":
        """Test-sized config (SURVEY.md §4.2 small-dials strategy)."""
        return cls(
            depths=[1, 1, 1, 1],
            hidden_sizes=[8, 16, 24, 32],
            num_attention_heads=[1, 1, 2, 2],
            decoder_hidden_size=32,
            num_labels=8,
            drop_path_rate=0.0,
            **kw,
        )
