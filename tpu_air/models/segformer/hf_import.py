"""HF → Flax weight import for SegFormer.

SURVEY.md §7 hard-part 4: conv-layout-faithful import of HF torch weights
(`nvidia/mit-b0`, `nvidia/segformer-b0-finetuned-ade-512-512` — the two
checkpoints the reference loads at Scaling_model_training.ipynb:cc-16 and
Scaling_batch_inference.ipynb:cc-20-21) into this framework's NHWC param
tree.  Pure-numpy conversion; torch only reads the source state dict.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from .config import SegformerConfig


def _conv(w) -> np.ndarray:
    # torch conv (O, I, kh, kw) → flax (kh, kw, I, O); also correct for
    # depthwise convs ((C,1,3,3) → (3,3,1,C)).
    return np.ascontiguousarray(np.asarray(w).transpose(2, 3, 1, 0))


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def _ln(sd, prefix: str) -> Dict[str, np.ndarray]:
    return {"scale": sd[f"{prefix}.weight"], "bias": sd[f"{prefix}.bias"]}


def _dense(sd, prefix: str) -> Dict[str, np.ndarray]:
    return {"kernel": _t(sd[f"{prefix}.weight"]), "bias": sd[f"{prefix}.bias"]}


def convert_segformer_state_dict(
    sd: Dict[str, Any], config: SegformerConfig
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Map an HF torch Segformer state_dict onto (params, batch_stats).

    Handles both the segmentation form (`segformer.encoder…` + `decode_head…`)
    and the bare-backbone classification form (decode-head keys absent →
    returned trees omit `decode_head`, callers init it fresh, mirroring HF's
    "newly initialized" head warning when fine-tuning from `nvidia/mit-b0`).
    """
    sd = {k: np.asarray(v) for k, v in sd.items()}
    enc: Dict[str, Any] = {}
    for s in range(config.num_encoder_blocks):
        pe = f"segformer.encoder.patch_embeddings.{s}"
        enc[f"patch_embed_{s}"] = {
            "proj": {"kernel": _conv(sd[f"{pe}.proj.weight"]), "bias": sd[f"{pe}.proj.bias"]},
            "layer_norm": _ln(sd, f"{pe}.layer_norm"),
        }
        for d in range(config.depths[s]):
            b = f"segformer.encoder.block.{s}.{d}"
            attn: Dict[str, Any] = {
                "query": _dense(sd, f"{b}.attention.self.query"),
                "key": _dense(sd, f"{b}.attention.self.key"),
                "value": _dense(sd, f"{b}.attention.self.value"),
                "out": _dense(sd, f"{b}.attention.output.dense"),
            }
            if config.sr_ratios[s] > 1:
                attn["sr"] = {
                    "kernel": _conv(sd[f"{b}.attention.self.sr.weight"]),
                    "bias": sd[f"{b}.attention.self.sr.bias"],
                }
                attn["sr_norm"] = _ln(sd, f"{b}.attention.self.layer_norm")
            enc[f"block_{s}_{d}"] = {
                "layer_norm_1": _ln(sd, f"{b}.layer_norm_1"),
                "attention": attn,
                "layer_norm_2": _ln(sd, f"{b}.layer_norm_2"),
                "mlp": {
                    "dense1": _dense(sd, f"{b}.mlp.dense1"),
                    "dwconv": {
                        "kernel": _conv(sd[f"{b}.mlp.dwconv.dwconv.weight"]),
                        "bias": sd[f"{b}.mlp.dwconv.dwconv.bias"],
                    },
                    "dense2": _dense(sd, f"{b}.mlp.dense2"),
                },
            }
        enc[f"stage_norm_{s}"] = _ln(sd, f"segformer.encoder.layer_norm.{s}")

    params: Dict[str, Any] = {"encoder": enc}
    batch_stats: Dict[str, Any] = {}

    if "decode_head.linear_fuse.weight" in sd:
        head: Dict[str, Any] = {}
        for i in range(config.num_encoder_blocks):
            head[f"linear_c_{i}"] = _dense(sd, f"decode_head.linear_c.{i}.proj")
        head["linear_fuse"] = {"kernel": _conv(sd["decode_head.linear_fuse.weight"])}
        head["batch_norm"] = {
            "scale": sd["decode_head.batch_norm.weight"],
            "bias": sd["decode_head.batch_norm.bias"],
        }
        head["classifier"] = {
            "kernel": _conv(sd["decode_head.classifier.weight"]),
            "bias": sd["decode_head.classifier.bias"],
        }
        params["decode_head"] = head
        batch_stats["decode_head"] = {
            "batch_norm": {
                "mean": sd["decode_head.batch_norm.running_mean"],
                "var": sd["decode_head.batch_norm.running_var"],
            }
        }
    return params, batch_stats


def config_from_hf(hf_config) -> SegformerConfig:
    return SegformerConfig(
        num_channels=hf_config.num_channels,
        num_encoder_blocks=hf_config.num_encoder_blocks,
        depths=list(hf_config.depths),
        sr_ratios=list(hf_config.sr_ratios),
        hidden_sizes=list(hf_config.hidden_sizes),
        patch_sizes=list(hf_config.patch_sizes),
        strides=list(hf_config.strides),
        num_attention_heads=list(hf_config.num_attention_heads),
        mlp_ratios=list(hf_config.mlp_ratios),
        hidden_dropout_prob=hf_config.hidden_dropout_prob,
        attention_probs_dropout_prob=hf_config.attention_probs_dropout_prob,
        classifier_dropout_prob=hf_config.classifier_dropout_prob,
        drop_path_rate=hf_config.drop_path_rate,
        layer_norm_eps=hf_config.layer_norm_eps,
        decoder_hidden_size=hf_config.decoder_hidden_size,
        num_labels=getattr(hf_config, "num_labels", 150),
    )


def load_segformer_from_hf(
    name_or_path: str,
    dtype: str = "float32",
    num_labels: Optional[int] = None,
    seed: int = 0,
):
    """Load a (local) HF Segformer checkpoint into (model, variables).

    `variables` is a full flax variable dict {"params": …, "batch_stats": …};
    a missing decode head (bare `nvidia/mit-b0` backbone) is freshly
    initialized, matching the reference's fine-tune-from-backbone flow
    (Scaling_model_training.ipynb:cc-16).
    """
    import jax
    import jax.numpy as jnp
    from transformers import AutoConfig, AutoModel

    from .modeling import SegformerForSemanticSegmentation

    hf_config = AutoConfig.from_pretrained(name_or_path)
    config = config_from_hf(hf_config)
    if num_labels is not None:
        config.num_labels = num_labels
    config.dtype = dtype

    try:
        from transformers import SegformerForSemanticSegmentation as TorchSeg

        torch_model = TorchSeg.from_pretrained(name_or_path)
    except Exception:  # noqa: BLE001 — head-ful load fails on bare backbones; retry as AutoModel
        torch_model = AutoModel.from_pretrained(name_or_path)
    sd = {k: v.detach().cpu().numpy() for k, v in torch_model.state_dict().items()}
    # Bare-backbone checkpoints (AutoModel → SegformerModel) lack the
    # "segformer." prefix the converter keys on — normalize.
    if not any(k.startswith("segformer.") for k in sd):
        sd = {f"segformer.{k}": v for k, v in sd.items()}
    params, batch_stats = convert_segformer_state_dict(sd, config)

    model = SegformerForSemanticSegmentation(config)
    if "decode_head" not in params:
        init = model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1, 64, 64, config.num_channels))
        )
        params["decode_head"] = init["params"]["decode_head"]
        batch_stats = jax.tree_util.tree_map(lambda x: x, init.get("batch_stats", {}))

    to_jnp = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa: E731
    return model, {"params": to_jnp(params), "batch_stats": to_jnp(batch_stats)}
