"""Pure-Python sentencepiece unigram tokenizer (T5/FLAN-T5 vocabulary).

The reference tokenizes with the sentencepiece C++ ``T5Tokenizer``
(NLP_workloads/Anyscale_job/utils.py:23-28; requirements.txt:146).  This
environment has neither the sentencepiece wheel nor network access, so the
framework ships a dependency-free implementation that loads the REAL
FLAN-T5 vocabulary from either on-disk asset format:

* ``spiece.model``   — the sentencepiece ``ModelProto`` (a protobuf; parsed
                       here with a minimal wire-format reader, no protoc),
* ``tokenizer.json`` — the HF fast-tokenizer serialization of the same
                       Unigram model.

Encoding is standard unigram-LM Viterbi segmentation: normalize (NFKC,
whitespace collapse, ``▁`` escaping with a dummy prefix — T5's ``nmt_nfkc``
normalizer approximated), then pick the piece segmentation with the highest
total log-probability.  Unknown characters get the sentencepiece unk penalty
(min piece score − 10).

Parity is tested against the Rust ``tokenizers`` Unigram model when that
library is importable (tests/test_tokenizer_spm.py).
"""

from __future__ import annotations

import json
import os
import re
import struct
import unicodedata
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

SPIECE_UNDERLINE = "▁"  # ▁

# sentencepiece_model.proto piece types
_NORMAL, _UNKNOWN, _CONTROL, _USER_DEFINED, _UNUSED, _BYTE = 1, 2, 3, 4, 5, 6

_UNK_PENALTY = 10.0


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format reader (just enough for ModelProto)
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result, shift = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a protobuf message body."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 1:  # 64-bit
            val = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:  # 32-bit
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, val


def parse_model_proto(data: bytes) -> List[Tuple[str, float, int]]:
    """Parse a sentencepiece ``ModelProto`` → [(piece, score, type), ...].

    ModelProto field 1 = repeated SentencePiece{piece:1 string,
    score:2 float, type:3 enum}.  Everything else (trainer/normalizer
    specs) is skipped — specials are identified by piece type.
    """
    pieces: List[Tuple[str, float, int]] = []
    for field, wt, val in _iter_fields(data):
        if field == 1 and wt == 2:
            piece, score, ptype = "", 0.0, _NORMAL
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 2:
                    piece = v2.decode("utf-8")
                elif f2 == 2 and w2 == 5:
                    score = struct.unpack("<f", v2)[0]
                elif f2 == 3 and w2 == 0:
                    ptype = v2
            pieces.append((piece, score, ptype))
    if not pieces:
        raise ValueError("no pieces found — not a sentencepiece ModelProto?")
    return pieces


def serialize_model_proto(pieces: List[Tuple[str, float, int]]) -> bytes:
    """Inverse of :func:`parse_model_proto` (used by save_pretrained and to
    build test fixtures without the sentencepiece wheel)."""
    out = bytearray()
    for piece, score, ptype in pieces:
        body = bytearray()
        pb = piece.encode("utf-8")
        body += b"\x0a" + _varint(len(pb)) + pb           # field 1, wt 2
        body += b"\x15" + struct.pack("<f", score)        # field 2, wt 5
        body += b"\x18" + _varint(ptype)                  # field 3, wt 0
        out += b"\x0a" + _varint(len(body)) + bytes(body)  # ModelProto.pieces
    return bytes(out)


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return bytes(out)


# ---------------------------------------------------------------------------
# Unigram Viterbi encoder
# ---------------------------------------------------------------------------

class SentencePieceUnigram:
    """Unigram-LM tokenizer over a piece vocabulary with Viterbi decoding."""

    def __init__(
        self,
        pieces: List[Tuple[str, float, int]],
        *,
        add_dummy_prefix: bool = True,
        remove_extra_whitespaces: bool = True,
    ):
        self.pieces = pieces
        self.add_dummy_prefix = add_dummy_prefix
        self.remove_extra_whitespaces = remove_extra_whitespaces
        self.piece_to_id: Dict[str, int] = {}
        self.id_to_piece: List[str] = []
        self.scores: List[float] = []
        self.types: List[int] = []
        self.unk_id = 0
        for i, (piece, score, ptype) in enumerate(pieces):
            # first occurrence wins, like sentencepiece
            self.piece_to_id.setdefault(piece, i)
            self.id_to_piece.append(piece)
            self.scores.append(score)
            self.types.append(ptype)
            if ptype == _UNKNOWN:
                self.unk_id = i
        scorable = [s for s, t in zip(self.scores, self.types) if t == _NORMAL]
        min_score = min(scorable) if scorable else 0.0
        self._unk_score = min_score - _UNK_PENALTY
        self._max_piece_len = max((len(p) for p, _, t in pieces if t != _UNKNOWN), default=1)
        # prefix-keyed lookup: for Viterbi we need all pieces matching at a
        # position; a dict keyed by piece string with a windowed scan is
        # O(len * max_piece_len) per sentence — fine for host-side tokenize
        self._vocab_set = {
            p for p, _, t in pieces if t in (_NORMAL, _USER_DEFINED, _CONTROL, _BYTE)
        }

    # -- normalization (nmt_nfkc approximation) ----------------------------
    def normalize(self, text: str) -> str:
        text = unicodedata.normalize("NFKC", text)
        if self.remove_extra_whitespaces:
            text = " ".join(text.split())
        if not text:
            return ""
        if self.add_dummy_prefix:
            text = " " + text
        return text.replace(" ", SPIECE_UNDERLINE)

    def encode_pieces(self, text: str) -> List[str]:
        s = self.normalize(text)
        if not s:
            return []
        n = len(s)
        # Viterbi over character positions
        best = [-1e18] * (n + 1)
        back: List[Tuple[int, Optional[str]]] = [(-1, None)] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] <= -1e18:
                continue
            # unknown single char fallback
            cand = best[i] + self._unk_score
            if cand > best[i + 1]:
                best[i + 1] = cand
                back[i + 1] = (i, None)
            for ln in range(1, min(self._max_piece_len, n - i) + 1):
                sub = s[i:i + ln]
                if sub in self._vocab_set:
                    idx = self.piece_to_id[sub]
                    cand = best[i] + self.scores[idx]
                    if cand > best[i + ln]:
                        best[i + ln] = cand
                        back[i + ln] = (i, sub)
        # trace back
        out: List[str] = []
        pos = n
        while pos > 0:
            prev, piece = back[pos]
            out.append(piece if piece is not None else s[prev:pos])
            pos = prev
        out.reverse()
        # merge adjacent unknowns like sentencepiece's unk aggregation? spm
        # emits one unk per unknown character span element; keep per-char
        return out

    def piece_id(self, piece: str) -> int:
        return self.piece_to_id.get(piece, self.unk_id)

    def encode_ids(self, text: str) -> List[int]:
        ids: List[int] = []
        for p in self.encode_pieces(text):
            i = self.piece_to_id.get(p, self.unk_id)
            # sentencepiece fuses runs of unknown characters into ONE <unk>
            if i == self.unk_id and ids and ids[-1] == self.unk_id:
                continue
            ids.append(i)
        return ids

    def decode_pieces(self, pieces: List[str]) -> str:
        text = "".join(pieces).replace(SPIECE_UNDERLINE, " ")
        return text.lstrip(" ") if self.add_dummy_prefix else text


# ---------------------------------------------------------------------------
# T5 tokenizer surface over the unigram core
# ---------------------------------------------------------------------------

class T5SentencePieceTokenizer:
    """HF-``T5Tokenizer``-compatible surface over :class:`SentencePieceUnigram`.

    Load from a directory (or file) holding ``spiece.model`` or
    ``tokenizer.json``.  T5 convention: pad=0, eos=1 (``</s>``), unk=2,
    plus ``extra_ids`` sentinel tokens appended at the END of the id space
    in REVERSE order (``<extra_id_0>`` = vocab_size-1), exactly like HF.
    """

    def __init__(
        self,
        sp: SentencePieceUnigram,
        model_max_length: int = 512,
        extra_ids: int = 100,
    ):
        self.sp = sp
        self.model_max_length = model_max_length
        self.extra_ids = extra_ids
        self._base = len(sp.id_to_piece)
        self.vocab_size = self._base + extra_ids
        self.pad_token = "<pad>"
        self.eos_token = "</s>"
        self.unk_token = "<unk>"
        self.pad_token_id = sp.piece_to_id.get("<pad>", 0)
        self.eos_token_id = sp.piece_to_id.get("</s>", 1)
        self.unk_token_id = sp.unk_id
        # <extra_id_0> is the LAST id, <extra_id_99> the first extra slot
        self._extra_to_id = {
            f"<extra_id_{i}>": self.vocab_size - 1 - i for i in range(extra_ids)
        }
        self._id_to_extra = {v: k for k, v in self._extra_to_id.items()}

    # -- loading -----------------------------------------------------------
    @classmethod
    def from_pretrained(
        cls, path: str, model_max_length: int = 512, extra_ids: int = 100
    ) -> "T5SentencePieceTokenizer":
        spm_path, json_path = None, None
        if os.path.isdir(path):
            for name in ("spiece.model", "sentencepiece.model"):
                p = os.path.join(path, name)
                if os.path.exists(p):
                    spm_path = p
                    break
            p = os.path.join(path, "tokenizer.json")
            if os.path.exists(p):
                json_path = p
            cfg_path = os.path.join(path, "tokenizer_config.json")
            if os.path.exists(cfg_path):
                try:
                    with open(cfg_path) as f:
                        cfg = json.load(f)
                    model_max_length = cfg.get("model_max_length", model_max_length)
                    # honor the saved sentinel count — otherwise a
                    # save/load round-trip would shift every <extra_id_*>
                    extra_ids = cfg.get("extra_ids", extra_ids)
                except Exception:  # noqa: BLE001 — malformed sidecar config: keep defaults
                    pass
        elif path.endswith(".model"):
            spm_path = path
        elif path.endswith(".json"):
            json_path = path
        if spm_path:
            with open(spm_path, "rb") as f:
                pieces = parse_model_proto(f.read())
            return cls(SentencePieceUnigram(pieces), model_max_length, extra_ids)
        if json_path:
            return cls.from_tokenizer_json(json_path, model_max_length)
        raise FileNotFoundError(
            f"no spiece.model or tokenizer.json under {path!r}"
        )

    @classmethod
    def from_tokenizer_json(
        cls, path: str, model_max_length: int = 512
    ) -> "T5SentencePieceTokenizer":
        with open(path) as f:
            tj = json.load(f)
        model = tj.get("model", {})
        if model.get("type") != "Unigram":
            raise ValueError(f"tokenizer.json model type {model.get('type')!r} != Unigram")
        vocab = model["vocab"]  # [[piece, score], ...]
        unk_id = model.get("unk_id", 2)
        pieces: List[Tuple[str, float, int]] = []
        n_extra = 0
        for i, (piece, score) in enumerate(vocab):
            if i == unk_id:
                ptype = _UNKNOWN
            elif piece in ("<pad>", "</s>", "<s>"):
                ptype = _CONTROL
            elif piece.startswith("<extra_id_") and piece.endswith(">"):
                ptype = _USER_DEFINED
                n_extra += 1
            else:
                ptype = _NORMAL
            pieces.append((piece, score, ptype))
        if n_extra:
            # HF fast files already include the sentinels in-vocab; keep
            # their ids and disable the synthetic extra-id block
            pieces_main = pieces
            tok = cls(SentencePieceUnigram(pieces_main), model_max_length, extra_ids=0)
            tok._extra_to_id = {
                p: i for i, (p, _, t) in enumerate(pieces) if t == _USER_DEFINED
            }
            tok._id_to_extra = {v: k for k, v in tok._extra_to_id.items()}
            return tok
        return cls(SentencePieceUnigram(pieces), model_max_length)

    # -- encode ------------------------------------------------------------
    _SENTINEL_RE = re.compile(r"(<extra_id_\d+>)")

    def encode(self, text: str, add_eos: bool = True) -> List[int]:
        ids: List[int] = []
        # split out sentinel tokens verbatim (T5 infilling convention);
        # one regex pass — no per-sentinel substring scans
        for part in self._SENTINEL_RE.split(text):
            if not part:
                continue
            sid = self._extra_to_id.get(part)
            if sid is not None:
                ids.append(sid)
            else:
                ids.extend(self.sp.encode_ids(part))
        if add_eos:
            ids.append(self.eos_token_id)
        return ids

    def __call__(
        self,
        text: Union[str, List[str]],
        max_length: Optional[int] = None,
        padding: Union[bool, str] = False,
        truncation: bool = False,
        return_tensors: Optional[str] = None,
        add_special_tokens: bool = True,
    ) -> Dict[str, Union[List, np.ndarray]]:
        texts = [text] if isinstance(text, str) else list(text)
        seqs = [self.encode(t, add_eos=add_special_tokens) for t in texts]
        limit = max_length or self.model_max_length
        if truncation:
            seqs = [s[:limit] for s in seqs]
        if padding == "max_length":
            width = limit
        elif padding in (True, "longest"):
            width = max((len(s) for s in seqs), default=0)
        else:
            width = None
        if width is not None:
            attn = [[1] * len(s) + [0] * max(0, width - len(s)) for s in seqs]
            seqs = [s + [self.pad_token_id] * max(0, width - len(s)) for s in seqs]
        else:
            attn = [[1] * len(s) for s in seqs]
        out = {"input_ids": seqs, "attention_mask": attn}
        if return_tensors in ("np", "jax"):
            if len({len(s) for s in seqs}) > 1:
                raise ValueError(
                    "ragged sequences cannot become tensors — pass "
                    "truncation=True (some inputs exceed max_length)"
                )
            out = {k: np.asarray(v, dtype=np.int32) for k, v in out.items()}
        return out

    # -- decode ------------------------------------------------------------
    def convert_ids_to_tokens(self, ids) -> List[str]:
        toks = []
        for i in ids:
            i = int(i)
            if i in self._id_to_extra:
                toks.append(self._id_to_extra[i])
            elif 0 <= i < self._base:
                toks.append(self.sp.id_to_piece[i])
            else:
                toks.append(self.unk_token)
        return toks

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        pieces = []
        # one host pull for the whole sequence: a device array decodes with
        # a single transfer instead of one sync per token (airlint JX004)
        for i in np.asarray(ids, dtype=np.int64).tolist():
            if skip_special_tokens and (
                i in (self.pad_token_id, self.eos_token_id)
                or (i < self._base and self.sp.types[i] == _CONTROL)
            ):
                continue
            if i in self._id_to_extra:
                pieces.append(self._id_to_extra[i])
            elif 0 <= i < self._base:
                pieces.append(self.sp.id_to_piece[i])
        return self.sp.decode_pieces(pieces)

    def batch_decode(self, batch, skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(row, skip_special_tokens) for row in np.asarray(batch)]

    # -- persistence --------------------------------------------------------
    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "spiece.model"), "wb") as f:
            f.write(serialize_model_proto(self.sp.pieces))
        with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
            json.dump(
                {
                    "tokenizer_class": "T5SentencePieceTokenizer",
                    "model_max_length": self.model_max_length,
                    "extra_ids": self.extra_ids,
                },
                f,
            )


# ---------------------------------------------------------------------------
# unigram training (EM) — produce REAL .model assets without the spm wheel
# ---------------------------------------------------------------------------

def train_unigram(
    texts: List[str],
    vocab_size: int = 2048,
    max_piece_len: int = 8,
    seed_factor: int = 8,
    em_iters: int = 2,
    shrink_factor: float = 0.75,
) -> List[Tuple[str, float, int]]:
    """Train a unigram-LM piece vocabulary (the sentencepiece algorithm,
    simplified): seed with frequent substrings of ▁-escaped words, run EM
    (forward-backward expected counts over each word's segmentation
    lattice), and iteratively prune low-count pieces until ``vocab_size``
    NORMAL pieces remain — single characters are never pruned (full
    character coverage, like spm's required_chars).  Returns
    ``(piece, log-prob score, type)`` rows ready for
    :func:`serialize_model_proto`.
    """
    import math
    from collections import Counter

    # ▁-escaped word counts (the training view of the corpus)
    words: Counter = Counter()
    for text in texts:
        text = unicodedata.normalize("NFKC", text)
        for w in text.split():
            words[SPIECE_UNDERLINE + w] += 1

    chars: Counter = Counter()
    for w, f in words.items():
        for ch in w:
            chars[ch] += f
    required = set(chars)

    # seed: frequent substrings, scored by count * len (spm's seed heuristic)
    subs: Counter = Counter()
    for w, f in words.items():
        L = len(w)
        for i in range(L):
            for ln in range(2, min(max_piece_len, L - i) + 1):
                subs[w[i : i + ln]] += f
    seed_n = max(seed_factor * vocab_size, vocab_size + len(required))
    seeded = [
        s for s, c in sorted(
            subs.items(), key=lambda kv: (-kv[1] * len(kv[0]), kv[0])
        )[:seed_n]
    ]
    vocab = {p: float(subs[p] * len(p)) for p in seeded}
    for ch in required:
        vocab[ch] = float(max(chars[ch], 1))

    def em_round(vocab):
        total = sum(vocab.values())
        logp = {p: math.log(c / total) for p, c in vocab.items()}
        maxlen = max(len(p) for p in logp)
        counts: Counter = Counter()
        for w, f in words.items():
            n = len(w)
            # forward
            alpha = [-1e30] * (n + 1)
            alpha[0] = 0.0
            arcs = [[] for _ in range(n + 1)]  # arcs[end] = [(start, piece, lp)]
            for i in range(n):
                if alpha[i] <= -1e29:
                    continue
                for ln in range(1, min(maxlen, n - i) + 1):
                    sub = w[i : i + ln]
                    lp = logp.get(sub)
                    if lp is None:
                        continue
                    arcs[i + ln].append((i, sub, lp))
                    cand = alpha[i] + lp
                    a = alpha[i + ln]
                    m = cand if cand > a else a
                    alpha[i + ln] = m + math.log1p(math.exp(-abs(cand - a))) \
                        if a > -1e29 else cand
            if alpha[n] <= -1e29:
                continue  # unreachable (cannot happen: chars are in vocab)
            # backward
            beta = [-1e30] * (n + 1)
            beta[n] = 0.0
            for end in range(n, 0, -1):
                if beta[end] <= -1e29:
                    continue
                for i, sub, lp in arcs[end]:
                    cand = beta[end] + lp
                    b = beta[i]
                    m = cand if cand > b else b
                    beta[i] = m + math.log1p(math.exp(-abs(cand - b))) \
                        if b > -1e29 else cand
            z = alpha[n]
            for end in range(1, n + 1):
                for i, sub, lp in arcs[end]:
                    post = alpha[i] + lp + beta[end] - z
                    if post > -30.0:
                        counts[sub] += f * math.exp(post)
        return counts

    while True:
        for _ in range(em_iters):
            counts = em_round(vocab)
            vocab = {
                p: max(counts.get(p, 0.0), 1e-6 if p in required else 0.0)
                for p in vocab
            }
            vocab = {p: c for p, c in vocab.items() if c > 0.0}
        n_prunable = len(vocab)
        if n_prunable <= vocab_size:
            break
        keep = max(vocab_size, int(n_prunable * shrink_factor))
        ranked = sorted(vocab.items(), key=lambda kv: (-kv[1], kv[0]))
        kept = {p: c for p, c in ranked[:keep]}
        for ch in required:  # coverage is non-negotiable
            kept.setdefault(ch, vocab.get(ch, 1e-6))
        if len(kept) == len(vocab):
            break  # nothing prunable left beyond required chars
        vocab = kept

    total = sum(vocab.values())
    import math as _m

    scored = sorted(
        ((p, _m.log(c / total)) for p, c in vocab.items()),
        key=lambda kv: (-kv[1], kv[0]),
    )
    return [(p, s, _NORMAL) for p, s in scored]


def train_t5_tokenizer(
    texts: List[str], vocab_size: int = 2048, model_max_length: int = 512,
    extra_ids: int = 100, **train_kwargs,
) -> "T5SentencePieceTokenizer":
    """Train and wrap with the T5 id layout (pad=0, eos=1, unk=2)."""
    normal = train_unigram(texts, vocab_size=vocab_size, **train_kwargs)
    pieces = [
        ("<pad>", 0.0, _CONTROL),
        ("</s>", 0.0, _CONTROL),
        ("<unk>", 0.0, _UNKNOWN),
    ] + normal
    return T5SentencePieceTokenizer(
        SentencePieceUnigram(pieces), model_max_length=model_max_length,
        extra_ids=extra_ids,
    )
