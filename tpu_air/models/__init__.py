"""tpu_air.models — Flax model families (L6 compute layer)."""

from . import segformer, t5
from .tokenizer import ByteTokenizer, auto_tokenizer

__all__ = ["ByteTokenizer", "auto_tokenizer", "segformer", "t5"]
