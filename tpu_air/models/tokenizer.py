"""Self-contained tokenizers.

The reference tokenizes with sentencepiece-backed ``T5Tokenizer``
(Model_finetuning…ipynb:cc-26; requirements.txt:146).  This environment has no
sentencepiece, so the framework ships a dependency-free byte-level tokenizer
with the T5 special-token convention (pad=0, eos=1) and an HF-compatible
calling surface (``__call__`` with padding/truncation/max_length,
``batch_decode``, ``save_pretrained``/``from_pretrained``) so the workload
layer is drop-in.  When HF fast tokenizers are importable, ``auto_tokenizer``
prefers them for real FLAN-T5 checkpoints.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Union

import numpy as np


class ByteTokenizer:
    """Byte-level tokenizer: one id per byte + specials. Lossless on any
    UTF-8 text, no training required — ideal for offline tests and a sound
    default for synthetic corpora."""

    PAD, EOS, UNK = 0, 1, 2
    OFFSET = 3

    def __init__(self, model_max_length: int = 512):
        self.model_max_length = model_max_length
        self.pad_token_id = self.PAD
        self.eos_token_id = self.EOS
        self.unk_token_id = self.UNK
        self.pad_token = "<pad>"
        self.eos_token = "</s>"
        self.vocab_size = 256 + self.OFFSET

    # -- encode ------------------------------------------------------------
    def encode(self, text: str, add_eos: bool = True) -> List[int]:
        ids = [b + self.OFFSET for b in text.encode("utf-8")]
        if add_eos:
            ids.append(self.EOS)
        return ids

    def __call__(
        self,
        text: Union[str, List[str]],
        max_length: Optional[int] = None,
        padding: Union[bool, str] = False,
        truncation: bool = False,
        return_tensors: Optional[str] = None,
        add_special_tokens: bool = True,
    ) -> Dict[str, Union[List, np.ndarray]]:
        texts = [text] if isinstance(text, str) else list(text)
        seqs = [self.encode(t, add_eos=add_special_tokens) for t in texts]
        limit = max_length or self.model_max_length
        if truncation:
            seqs = [s[:limit] for s in seqs]
        if padding == "max_length":
            width = limit
        elif padding in (True, "longest"):
            width = max((len(s) for s in seqs), default=0)
        else:
            width = None
        if width is not None:
            # like HF: padding never truncates — over-length sequences stay
            # full length unless truncation=True was passed
            attn = [[1] * len(s) + [0] * max(0, width - len(s)) for s in seqs]
            seqs = [s + [self.PAD] * max(0, width - len(s)) for s in seqs]
        else:
            attn = [[1] * len(s) for s in seqs]
        out = {"input_ids": seqs, "attention_mask": attn}
        if return_tensors in ("np", "jax"):
            if len({len(s) for s in seqs}) > 1:
                raise ValueError(
                    "ragged sequences cannot become tensors — pass "
                    "truncation=True (some inputs exceed max_length)"
                )
            out = {k: np.asarray(v, dtype=np.int32) for k, v in out.items()}
        return out

    # -- decode ------------------------------------------------------------
    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        data = bytearray()
        for i in np.asarray(ids).tolist():
            if self.OFFSET <= i < self.OFFSET + 256:
                data.append(i - self.OFFSET)
            elif i < self.OFFSET and not skip_special_tokens:
                data.extend(f"<{i}>".encode())
            # ids beyond the byte range (model vocab padded past 256+OFFSET,
            # reachable from an untrained head) decode to nothing, like HF's
            # handling of out-of-vocab pieces
        return data.decode("utf-8", errors="replace")

    def batch_decode(self, batch, skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(ids, skip_special_tokens) for ids in np.asarray(batch)]

    # -- persistence (checkpoint bundling, SURVEY.md §5 checkpoint notes) --
    def save_pretrained(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "tokenizer_config.json"), "w") as f:
            json.dump(
                {
                    "tokenizer_class": "ByteTokenizer",
                    "model_max_length": self.model_max_length,
                },
                f,
            )

    @classmethod
    def from_pretrained(cls, path: str) -> "ByteTokenizer":
        cfg_path = os.path.join(path, "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            return cls(model_max_length=cfg.get("model_max_length", 512))
        return cls()


def auto_tokenizer(name_or_path: str, strict: bool = False):
    """Best-effort tokenizer resolution (predictor.py:64 defaults to
    AutoTokenizer): HF fast tokenizer when its assets resolve locally, else
    the framework's pure-Python sentencepiece unigram loader for on-disk
    ``spiece.model``/``tokenizer.json`` (real FLAN-T5 vocab, offline), else
    ByteTokenizer.

    ``strict=True`` disables the ByteTokenizer fallback: a missing real
    vocab raises with both loaders' errors instead of silently degrading
    (a degraded tokenizer makes every downstream result quietly wrong)."""
    errors = []
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(name_or_path)
    except Exception as e:  # noqa: BLE001 — any backend failure falls through to the next loader
        errors.append(f"transformers.AutoTokenizer: {type(e).__name__}: {e}")
    try:
        from .sentencepiece_unigram import T5SentencePieceTokenizer

        return T5SentencePieceTokenizer.from_pretrained(name_or_path)
    except Exception as e:  # noqa: BLE001 — fall through to the strict/degraded decision below
        errors.append(f"T5SentencePieceTokenizer: {type(e).__name__}: {e}")
    if strict:
        raise RuntimeError(
            f"auto_tokenizer({name_or_path!r}, strict=True): no real vocab "
            "loadable:\n  " + "\n  ".join(errors)
        )
    if os.path.isdir(name_or_path):
        return ByteTokenizer.from_pretrained(name_or_path)
    return ByteTokenizer()
