"""Long-context causal LM — Flax decoder-only transformer, TPU-first.

First-class sequence parallelism: with ``config.attention="ring"`` and
``config.sequence_axis`` naming a mesh axis, the model runs INSIDE shard_map
with activations sequence-sharded — each device holds L/P tokens, RoPE uses
global positions (shard offset from ``lax.axis_index``), and attention is
ring attention (ops/ring_attention.py): K/V shards rotate over ICI while the
blockwise-softmax state folds in each incoming block.  Context length then
scales linearly with the ``sequence`` mesh axis — the long-context design
the reference never had (its T5 path truncates at 512:
NLP_workloads/Anyscale_job/utils.py:23-28).

Everything is static-shape and scan/ppermute-based, so one compiled program
serves every step.  Architecture: pre-RMSNorm, RoPE attention, SwiGLU MLP,
tied embeddings (LLaMA-style — chosen for MXU-friendly dims, not copied
from any reference code).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .config import LMConfig

Array = jax.Array
NEG_INF = -1e30


class RMSNorm(nn.Module):
    eps: float = 1e-6
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (w * x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)).astype(self.dtype)


def rope(x: Array, positions: Array, theta: float) -> Array:
    """Rotary embedding.  x: (B, H, L, D), positions: (B, L) global token
    positions (sequence-sharded models pass shard-offset positions)."""
    d = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions[:, None, :, None].astype(jnp.float32) * inv_freq  # (B,1,L,D/2)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    return jnp.stack([y1, y2], axis=-1).reshape(x.shape).astype(x.dtype)


def _dense_causal_attention(q, k, v, scale, q_offset=0):
    """(B,H,L,D) einsum attention with causal mask; baseline path."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s * scale
    lq, lk = q.shape[2], k.shape[2]
    qi = q_offset + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
    kj = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
    s = jnp.where(qi >= kj, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


class CausalSelfAttention(nn.Module):
    config: LMConfig

    @nn.compact
    def __call__(self, x: Array, positions: Array, decode: bool = False) -> Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        b, l, _ = x.shape
        h, d = cfg.n_heads, cfg.head_dim

        def proj(name, out):
            return nn.Dense(out, use_bias=False, dtype=dtype,
                            kernel_init=nn.initializers.normal(0.02), name=name)

        q = proj("q", h * d)(x).reshape(b, l, h, d).transpose(0, 2, 1, 3)
        k = proj("k", h * d)(x).reshape(b, l, h, d).transpose(0, 2, 1, 3)
        v = proj("v", h * d)(x).reshape(b, l, h, d).transpose(0, 2, 1, 3)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        scale = 1.0 / (d ** 0.5)

        if decode:
            # KV-cache path (autoregressive generate, SURVEY.md §7
            # hard-part 2): keys/values land at the running cache index via
            # dynamic_update_slice; the SAME call handles both the
            # multi-token prefill and 1-token decode steps.  Cached k is
            # already RoPE'd (positions are global — the caller derives them
            # from the cache index).  Slabs are stored FLAT [b, L, h*d]:
            # the r5 T5 profile measured the [.., L, d=64] layout at 2x
            # physical HBM bytes from (8, 128) tile padding; h*d is
            # unpadded, and the 1-token step attends via the flat block-
            # diagonal formulation (ops/decode_attention.py) that streams
            # the slab once in storage layout.
            max_len = cfg.max_seq_len
            ck = self.variable(
                "cache", "cached_key",
                lambda: jnp.zeros((b, max_len, h * d), dtype))
            cv = self.variable(
                "cache", "cached_value",
                lambda: jnp.zeros((b, max_len, h * d), dtype))
            idx = self.variable(
                "cache", "cache_index", lambda: jnp.array(0, jnp.int32))
            i = idx.value
            kflat = k.transpose(0, 2, 1, 3).reshape(b, l, h * d)
            vflat = v.transpose(0, 2, 1, 3).reshape(b, l, h * d)
            if self.has_variable("cache", "block_table"):
                # PAGED engine cache (engine/kvpool/): cached_key/value are
                # page POOLS [P, page_len, h*d] shared by every slot, and
                # block_table [S, pages_per_slot] maps each slot's logical
                # positions onto physical pages — position p of slot s lives
                # at (table[s, p // C], p % C).  Prefix-shared pages appear
                # in several rows at once; the null page (id 0) absorbs
                # writes/reads of masked rows and unreached entries.
                from tpu_air.ops.decode_attention import (
                    flat_decode_attention, gather_pages)

                bt = self.variable(
                    "cache", "block_table",
                    lambda: jnp.zeros((b, 1), jnp.int32))
                table = bt.value
                npg = table.shape[1]
                C = ck.value.shape[1]
                lg = npg * C
                if l == 1:
                    # paged decode step: scatter each slot's new K/V to its
                    # current (page, offset), then attend over the gathered
                    # flat slab — same r5 formulation, pool-resident pages.
                    rows = jnp.arange(b)
                    page = table[rows, i // C]
                    off = i % C
                    ck.value = ck.value.at[page, off].set(
                        kflat[:, 0].astype(dtype))
                    cv.value = cv.value.at[page, off].set(
                        vflat[:, 0].astype(dtype))
                    idx.value = i + 1
                    kvm = jnp.arange(lg)[None, :] <= i[:, None]
                    o4 = flat_decode_attention(
                        q.transpose(0, 2, 1, 3) * scale,
                        gather_pages(ck.value, table),
                        gather_pages(cv.value, table),
                        None, kvm, None, None, h, dtype)
                    return proj("o", cfg.d_model)(o4.reshape(b, 1, h * d))
                # chunked prefill: ONE slot (b == 1) processes one page-
                # aligned chunk of its prompt at positions p0 .. p0+l-1.
                # The whole chunk writes its page in one dynamic_update_
                # slice; attention runs dense over the gathered pages with
                # the query offset at p0 (earlier chunks / prefix-shared
                # pages supply 0 .. p0-1).  One compiled program serves
                # EVERY prompt length — no per-bucket prefill compiles.
                if b != 1 or l != C:
                    raise ValueError(
                        f"paged chunk prefill wants b=1, l=page_len ({C}); "
                        f"got b={b}, l={l}"
                    )
                p0 = i[0]
                page = table[0, p0 // C]
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, kflat.astype(dtype), (page, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, vflat.astype(dtype), (page, 0, 0))
                idx.value = i + l
                kg = gather_pages(ck.value, table[:1])
                vg = gather_pages(cv.value, table[:1])
                k4 = kg.reshape(1, lg, h, d).transpose(0, 2, 1, 3)
                v4 = vg.reshape(1, lg, h, d).transpose(0, 2, 1, 3)
                o = _dense_causal_attention(q, k4, v4, scale, q_offset=p0)
                o = o.transpose(0, 2, 1, 3).reshape(b, l, h * d)
                return proj("o", cfg.d_model)(o)
            if i.ndim == 1:
                # PER-ROW cache index [b] (the continuous-batching engine,
                # engine/engine.py): every slot sits at its own position, so
                # the new token's K/V scatter to (row, i[row]) and the
                # validity mask is per-row.  Positions beyond i[row] may
                # hold STALE bytes from a retired occupant — masked here,
                # progressively overwritten by subsequent steps.
                if l != 1:
                    raise ValueError(
                        "per-row cache_index supports single-token decode "
                        f"steps only; got l={l}"
                    )
                from tpu_air.ops.decode_attention import flat_decode_attention

                rows = jnp.arange(b)
                ck.value = ck.value.at[rows, i].set(
                    kflat[:, 0].astype(dtype))
                cv.value = cv.value.at[rows, i].set(
                    vflat[:, 0].astype(dtype))
                idx.value = i + 1
                kvm = jnp.arange(max_len)[None, :] <= i[:, None]
                o4 = flat_decode_attention(
                    q.transpose(0, 2, 1, 3) * scale, ck.value, cv.value,
                    None, kvm, None, None, h, dtype)
                return proj("o", cfg.d_model)(o4.reshape(b, 1, h * d))
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, kflat.astype(dtype), (0, i, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, vflat.astype(dtype), (0, i, 0))
            idx.value = i + l
            if l == 1:
                from tpu_air.ops.decode_attention import flat_decode_attention

                # future cache slots are zeros; the kv_mask hides them
                kvm = jnp.broadcast_to(
                    (jnp.arange(max_len) <= i)[None], (b, max_len))
                o4 = flat_decode_attention(
                    q.transpose(0, 2, 1, 3) * scale, ck.value, cv.value,
                    None, kvm, None, None, h, dtype)
                return proj("o", cfg.d_model)(o4.reshape(b, 1, h * d))
            # prefill (and any multi-token window): dense attention over
            # the cache with the query offset at the index — a one-time
            # 4-D view per generate call.  Future slots are zeros but
            # kj > qi masks them out.
            ck4 = ck.value.reshape(b, max_len, h, d).transpose(0, 2, 1, 3)
            cv4 = cv.value.reshape(b, max_len, h, d).transpose(0, 2, 1, 3)
            o = _dense_causal_attention(q, ck4, cv4, scale, q_offset=i)
            o = o.transpose(0, 2, 1, 3).reshape(b, l, h * d)
            return proj("o", cfg.d_model)(o)

        impl = cfg.attention
        if impl == "auto":
            # trace-time shape dispatch: the einsum path wins short
            # sequences, the Pallas kernel wins at/above the measured
            # crossover (no user flag — VERDICT r3 weak #2); off-TPU and
            # tile-degenerate shapes stay dense (interpret-mode flash and
            # 1-wide tiles are both perf cliffs)
            from tpu_air.ops.flash_attention import auto_dispatch_ok

            impl = (
                "flash"
                if l >= getattr(cfg, "flash_min_seq_len", 1024)
                and auto_dispatch_ok(l, l)
                else "dense"
            )
        if impl == "ring":
            if cfg.sequence_axis is None:
                raise ValueError('attention="ring" requires sequence_axis')
            from tpu_air.ops.ring_attention import ring_attention

            # fold heads into batch: ring expects (B·H, L_local, D)
            o = ring_attention(
                q.reshape(b * h, l, d), k.reshape(b * h, l, d),
                v.reshape(b * h, l, d), axis_name=cfg.sequence_axis,
                scale=scale, causal=True,
                block_q=cfg.block_q, block_k=cfg.block_k,
            ).reshape(b, h, l, d)
        elif impl == "flash":
            from tpu_air.ops.flash_attention import flash_attention

            o = flash_attention(
                q.reshape(b * h, l, d), k.reshape(b * h, l, d),
                v.reshape(b * h, l, d), scale=scale, causal=True,
                block_q=cfg.block_q, block_k=cfg.block_k,
            ).reshape(b, h, l, d)
        else:
            o = _dense_causal_attention(q, k, v, scale)

        o = o.transpose(0, 2, 1, 3).reshape(b, l, h * d)
        return proj("o", cfg.d_model)(o)


class SwiGLU(nn.Module):
    config: LMConfig

    @nn.compact
    def __call__(self, x: Array) -> Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        dense = lambda name, out: nn.Dense(  # noqa: E731
            out, use_bias=False, dtype=dtype,
            kernel_init=nn.initializers.normal(0.02), name=name)
        gate = nn.silu(dense("gate", cfg.d_ff)(x))
        up = dense("up", cfg.d_ff)(x)
        return dense("down", cfg.d_model)(gate * up)


class Block(nn.Module):
    config: LMConfig

    @nn.compact
    def __call__(self, x: Array, positions: Array, deterministic: bool = True,
                 decode: bool = False) -> Array:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        drop = nn.Dropout(cfg.dropout_rate, deterministic=deterministic)
        x = x + drop(CausalSelfAttention(cfg, name="attn")(
            RMSNorm(cfg.rmsnorm_eps, dtype, name="attn_norm")(x), positions,
            decode=decode,
        ))
        x = x + drop(SwiGLU(cfg, name="mlp")(
            RMSNorm(cfg.rmsnorm_eps, dtype, name="mlp_norm")(x)
        ))
        return x


class CausalLM(nn.Module):
    """``apply(params, input_ids, positions=None) -> logits``.

    ``positions``: (B, L) global positions; defaults to 0..L-1.  Sequence-
    parallel callers pass ``shard_offset + arange(L_local)`` so RoPE and the
    ring causal mask see global coordinates.
    """

    config: LMConfig

    @nn.compact
    def __call__(self, input_ids: Array, positions: Optional[Array] = None,
                 deterministic: bool = True, return_hidden: bool = False,
                 decode: bool = False) -> Array:
        cfg = self.config
        b, l = input_ids.shape
        if l > cfg.max_seq_len:
            raise ValueError(
                f"sequence length {l} exceeds max_seq_len {cfg.max_seq_len}"
            )
        dtype = jnp.dtype(cfg.dtype)
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(l, dtype=jnp.int32), (b, l))
        embed = self.param(
            "embedding", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.d_model),
            jnp.float32,
        )
        x = embed[input_ids].astype(dtype)
        for i in range(cfg.n_layers):
            x = Block(cfg, name=f"layer_{i}")(x, positions, deterministic,
                                              decode=decode)
        x = RMSNorm(cfg.rmsnorm_eps, dtype, name="final_norm")(x)
        if return_hidden:
            # pre-head hidden states: pair with head_weight() +
            # lm_chunked_loss_with_targets so the (B, L, V) logits are never
            # materialized (the other long-context memory cliff besides
            # attention; at L=8k, V=50k that tensor alone is GBs)
            return x
        if cfg.tie_embeddings:
            logits = x.astype(jnp.float32) @ embed.T
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                              name="lm_head")(x.astype(jnp.float32))
        return logits


def head_weight(params, config: LMConfig) -> Array:
    """The (d_model, vocab) head matrix out of a CausalLM param tree."""
    if config.tie_embeddings:
        return params["embedding"].T
    return params["lm_head"]["kernel"]


def lm_loss(logits: Array, input_ids: Array, pad_token_id: int):
    """Next-token cross entropy over non-pad targets; returns (sum, count)
    so sequence-parallel callers can psum both before dividing."""
    return lm_loss_with_targets(logits[:, :-1], input_ids[:, 1:], pad_token_id)


def lm_chunked_loss_with_targets(hidden: Array, head_w: Array, targets: Array,
                                 pad_token_id: int, chunk_size: int = 512):
    """CE without materializing the (B, L, V) logits.

    Scans over sequence chunks; each chunk's logits exist only inside the
    (rematerialized) chunk body, so peak memory is O(B·chunk·V) in both the
    forward and the backward instead of O(B·L·V) — the lm-head analog of
    blockwise attention, and the second memory cliff of long-context
    training.  Returns (sum, count) like :func:`lm_loss_with_targets`."""
    b, l, d = hidden.shape
    chunk_size = min(chunk_size, l)
    if l % chunk_size:
        # pad to a chunk multiple — padded targets are pad_token_id, so they
        # are masked out and contribute (0, 0).  Never fall back to the
        # dense (B, L, V) head: odd lengths show up exactly in the
        # long-context regime this function exists for.
        padded = (l + chunk_size - 1) // chunk_size * chunk_size
        hidden = jnp.pad(hidden, ((0, 0), (0, padded - l), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, padded - l)),
                          constant_values=pad_token_id)
        l = padded
    n = l // chunk_size
    hs = hidden.reshape(b, n, chunk_size, d).transpose(1, 0, 2, 3)
    ts = targets.reshape(b, n, chunk_size).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk(carry, xt):
        h, t = xt
        logits = h.astype(jnp.float32) @ head_w.astype(jnp.float32)
        s, c = lm_loss_with_targets(logits, t, pad_token_id)
        return (carry[0] + s, carry[1] + c), None

    (s, c), _ = jax.lax.scan(chunk, (jnp.float32(0), jnp.float32(0)), (hs, ts))
    return s, c


def lm_loss_with_targets(logits: Array, targets: Array, pad_token_id: int):
    """CE against precomputed targets — the sequence-parallel form: the
    next-token shift crosses shard boundaries, so callers shift GLOBALLY
    before sharding (use parallel.sequence_parallel.shift_targets, which
    pads-and-masks the final position — a plain roll would wrap token 0 into
    it and score it unmasked) so every local position keeps its true
    target."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = (targets != pad_token_id).astype(jnp.float32)
    return jnp.sum(nll * mask), jnp.sum(mask)
