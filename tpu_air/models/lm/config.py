"""Config for the long-context causal LM family."""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Optional


@dataclass
class LMConfig:
    """Decoder-only transformer (RoPE + SwiGLU, pre-RMSNorm) — the
    framework's long-context flagship.  ``attention`` picks the kernel:

    * ``"dense"`` — XLA einsum softmax (baseline, any backend);
    * ``"flash"`` — the Pallas blockwise kernel (ops/flash_attention.py);
    * ``"ring"``  — ring attention over the ``sequence_axis`` mesh axis
      (ops/ring_attention.py): each device holds L/P of the sequence and
      K/V shards rotate over ICI, so context length scales with the mesh.
    """

    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    head_dim: Optional[int] = None  # default d_model // n_heads
    d_ff: Optional[int] = None      # default 4 * d_model (SwiGLU uses 2/3)
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    rmsnorm_eps: float = 1e-6
    dropout_rate: float = 0.0
    dtype: str = "float32"
    tie_embeddings: bool = True
    # "auto" picks per-trace by sequence length: dense below
    # flash_min_seq_len, the Pallas flash kernel at/above it (measured v5e
    # crossover — BASELINE.md kernel table).  "ring" stays explicit: it
    # needs a sequence mesh axis.
    attention: str = "auto"           # auto | dense | flash | ring
    flash_min_seq_len: int = 1024
    sequence_axis: Optional[str] = None  # mesh axis for ring attention
    # None -> kernel's measured-on-TPU auto tiling (512/1024 caps)
    block_q: Optional[int] = None
    block_k: Optional[int] = None
    pad_token_id: int = 0
    eos_token_id: Optional[int] = None  # None: generation never early-stops

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.d_model // self.n_heads
        if self.d_ff is None:
            self.d_ff = int(8 * self.d_model / 3 + 255) // 256 * 256

    def to_dict(self) -> dict:
        return asdict(self)

    def to_json(self) -> str:
        """Checkpoint serialization (train/checkpoint.py model_config.json);
        ``model_type`` tags the config class for reconstruction."""
        import json

        return json.dumps({**self.to_dict(), "model_type": "causal_lm"})

    @classmethod
    def from_dict(cls, d: dict) -> "LMConfig":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})

    @classmethod
    def tiny(cls, vocab_size: int = 384) -> "LMConfig":
        return cls(vocab_size=vocab_size, d_model=64, n_layers=2, n_heads=4,
                   head_dim=16, d_ff=128, max_seq_len=512)
