from .config import LMConfig
from .modeling import (
    CausalLM,
    head_weight,
    lm_chunked_loss_with_targets,
    lm_loss,
    lm_loss_with_targets,
)

__all__ = [
    "LMConfig",
    "CausalLM",
    "head_weight",
    "lm_chunked_loss_with_targets",
    "lm_loss",
    "lm_loss_with_targets",
]
