from .config import LMConfig
from .modeling import CausalLM, lm_loss, lm_loss_with_targets

__all__ = ["LMConfig", "CausalLM", "lm_loss", "lm_loss_with_targets"]
