from .config import LMConfig
from .generate import (
    generate,
    init_slot_cache,
    make_lm_decode_step_fn,
    make_lm_generate_fn,
    make_lm_prefill_fn,
)
from .modeling import (
    CausalLM,
    head_weight,
    lm_chunked_loss_with_targets,
    lm_loss,
    lm_loss_with_targets,
)

__all__ = [
    "LMConfig",
    "generate",
    "init_slot_cache",
    "make_lm_decode_step_fn",
    "make_lm_generate_fn",
    "make_lm_prefill_fn",
    "CausalLM",
    "head_weight",
    "lm_chunked_loss_with_targets",
    "lm_loss",
    "lm_loss_with_targets",
]
