from .config import LMConfig
from .generate import generate, make_lm_generate_fn
from .modeling import (
    CausalLM,
    head_weight,
    lm_chunked_loss_with_targets,
    lm_loss,
    lm_loss_with_targets,
)

__all__ = [
    "LMConfig",
    "generate",
    "make_lm_generate_fn",
    "CausalLM",
    "head_weight",
    "lm_chunked_loss_with_targets",
    "lm_loss",
    "lm_loss_with_targets",
]
