"""Autoregressive generation for the causal LM family, under jit.

Same contract as the T5 generate (models/t5/generate.py): a fixed-shape
``lax.scan`` decode loop over a pre-allocated KV cache — prefill processes
the whole prompt in one cached call, then one cached call per new token.
Greedy by default; temperature/top-k via the shared sampler
(models/sampling.py).  TPU-minded details:

* the cache is RIGHT-SIZED to ``L_prompt + max_new_tokens`` (a decode-time
  config override — cache length is static per compiled shape), not to the
  model's ``max_seq_len``, so per-token attention cost is O(L_prompt + t);
* prefill computes only the LAST position's logits via ``return_hidden`` +
  ``head_weight`` — the (B, L, V) prompt logits tensor (the long-context
  memory cliff lm_chunked_loss_with_targets exists for) never materializes;
* the scan emits the token it computes (no discarded final forward).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_air.models.sampling import sample_token

from .config import LMConfig
from .modeling import CausalLM, head_weight


def init_cache(model: CausalLM, batch_size: int):
    """Zero cache with the right structure, via eval_shape (free).  Cache
    length comes from ``model.config.max_seq_len`` — generate passes a
    decode model whose config is right-sized to prompt + budget."""

    def _init():
        return model.init(
            jax.random.PRNGKey(0),
            jnp.zeros((batch_size, 1), jnp.int32),
            decode=True,
        )

    shapes = jax.eval_shape(_init)["cache"]
    return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def make_lm_generate_fn(model: CausalLM, max_new_tokens: int,
                        do_sample: bool = False, temperature: float = 1.0,
                        top_k: int = 0, eos_token_id: Optional[int] = None,
                        early_stop: bool = True):
    """Build a jitted ``fn(params, input_ids, rng, live_mask=None) ->
    (B, max_new_tokens)``.

    ``input_ids``: (B, L_prompt) un-padded prompts (fixed shape per compile).
    After ``eos_token_id`` is emitted a row keeps emitting pad.

    ``live_mask``: optional (B,) bool — True marks a REAL row, False marks
    bucket filler the batching wrapper appended (born finished: emits pure
    pad, never holds early-stop open).  Filler is declared by the caller —
    the host side knows which rows it appended — never inferred from
    content, so an all-pad USER prompt generates normally (ADVICE r5).
    ``None`` means every row is real.

    ``early_stop=True`` (requires ``eos_token_id``; the t5/generate.py
    pattern) runs the decode as a ``lax.while_loop`` that exits once EVERY
    row has emitted EOS — outputs identical to the full-budget scan, the
    remaining steps just don't execute.  With ``eos_token_id=None`` there
    is no stopping criterion and the fixed-trip scan runs regardless."""
    cfg = model.config
    pad = cfg.pad_token_id

    def pick(logits, rng):
        return sample_token(logits, rng, do_sample, temperature, top_k)

    @jax.jit
    def generate(params, input_ids, rng, live_mask=None):
        b, lp = input_ids.shape
        total = lp + max_new_tokens
        if total > cfg.max_seq_len:
            raise ValueError(
                f"prompt {lp} + max_new_tokens {max_new_tokens} exceeds "
                f"max_seq_len {cfg.max_seq_len}"
            )
        # decode model with a right-sized cache (lp/max_new are static at
        # trace time; params are unaffected by max_seq_len)
        dmodel = CausalLM(LMConfig.from_dict(
            {**cfg.to_dict(), "max_seq_len": total}
        ))
        cache = init_cache(dmodel, b)
        positions = jnp.broadcast_to(jnp.arange(lp, dtype=jnp.int32), (b, lp))
        # prefill: hidden states only — head applied to the LAST position,
        # never to the (B, L, V) prompt logits
        hidden, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, input_ids, positions,
            decode=True, return_hidden=True, mutable=["cache"],
        )
        head_w = head_weight(params, cfg).astype(jnp.float32)
        rng, sub = jax.random.split(rng)
        tok = pick(hidden[:, -1].astype(jnp.float32) @ head_w, sub)
        if eos_token_id is not None:
            # filler rows (declared by the caller's live_mask) are born
            # finished: they emit pure pad and never hold the while_loop
            # open for the full budget
            filler = (jnp.zeros((b,), bool) if live_mask is None
                      else ~live_mask)
            tok = jnp.where(filler, pad, tok)
            done = filler | (tok == eos_token_id)
        else:
            done = None

        def decode_one(cache, tok, pos, rng, done):
            hidden, vars_ = dmodel.apply(
                {"params": params, "cache": cache}, tok[:, None],
                jnp.full((b, 1), pos, jnp.int32), decode=True,
                return_hidden=True, mutable=["cache"],
            )
            rng, sub = jax.random.split(rng)
            nxt = pick(hidden[:, -1].astype(jnp.float32) @ head_w, sub)
            if done is not None:
                nxt = jnp.where(done, pad, nxt)
                done = done | (nxt == eos_token_id)
            return vars_["cache"], nxt, pos + 1, rng, done

        if early_stop and done is not None:
            toks0 = jnp.full((b, max_new_tokens), pad, jnp.int32)
            toks0 = toks0.at[:, 0].set(tok)

            def cond(carry):
                step, _, _, _, _, done, _ = carry
                return (step < max_new_tokens) & ~jnp.all(done)

            def body(carry):
                step, cache, tok, pos, rng, done, toks = carry
                cache, nxt, pos, rng, done = decode_one(
                    cache, tok, pos, rng, done
                )
                toks = jax.lax.dynamic_update_slice(
                    toks, nxt[:, None], (0, step)
                )
                return (step + 1, cache, nxt, pos, rng, done, toks)

            (_, _, _, _, _, _, toks) = jax.lax.while_loop(
                cond, body,
                (jnp.asarray(1), vars_["cache"], tok, jnp.int32(lp), rng,
                 done, toks0),
            )
            return toks

        def step(carry, _):
            cache, tok, pos, rng, done = carry
            cache, nxt, pos, rng, done = decode_one(cache, tok, pos, rng, done)
            return (cache, nxt, pos, rng, done), nxt

        # the prefill already produced token 0; the scan computes (and
        # emits) the remaining max_new_tokens - 1 — no discarded forward
        (_, _, _, _, _), toks = jax.lax.scan(
            step, (vars_["cache"], tok, jnp.int32(lp), rng, done), None,
            length=max_new_tokens - 1,
        )
        return jnp.concatenate([tok[:, None], toks.T], axis=1)

    return generate


# ---------------------------------------------------------------------------
# Continuous-batching entry points (tpu_air.engine)
#
# make_lm_generate_fn keeps prefill and the per-token step private inside one
# jitted program — right for offline batches, useless for an engine that must
# admit/retire requests BETWEEN steps.  These expose the same two phases as
# standalone compiled units over the engine's slot-pool cache layout:
# per-layer flat slabs [S, L_slot, h*d] plus a PER-ROW cache index (each slot
# sits at its own position — modeling.py scatters the new token's K/V to
# (row, index[row]) and masks per row).
# ---------------------------------------------------------------------------


def _map_cache_leaf(cache, leaf, fn):
    """Rebuild a flax cache dict with ``fn`` applied to every ``leaf``-named
    entry (everything else passes through untouched)."""
    out = {}
    for k, v in cache.items():
        if isinstance(v, dict):
            out[k] = _map_cache_leaf(v, leaf, fn)
        elif k == leaf:
            out[k] = fn(v)
        else:
            out[k] = v
    return out


def _map_cache_index(cache, fn):
    return _map_cache_leaf(cache, "cache_index", fn)


def init_slot_cache(model: CausalLM, num_slots: int, slot_len: int):
    """Zero KV slab pool for ``num_slots`` sequence slots of ``slot_len``
    positions each, with PER-SLOT cache indices ([S] int32 vector instead of
    the offline scalar).  This is the persistent cache the engine's decode
    step carries (and donates) across its whole lifetime."""
    dmodel = CausalLM(LMConfig.from_dict(
        {**model.config.to_dict(), "max_seq_len": slot_len}
    ))
    cache = init_cache(dmodel, num_slots)
    return _map_cache_index(
        cache, lambda _: jnp.zeros((num_slots,), jnp.int32)
    )


def make_lm_prefill_fn(model: CausalLM, prompt_len: int):
    """Build a jitted ``fn(params, input_ids, last_index) -> (tok, cache)``:
    one whole-prompt cached pass producing the first greedy token plus the
    prompt's KV segment (per-layer ``[B, prompt_len, h*d]`` slabs) ready for
    ``dynamic_update_slice`` insertion into a free engine slot.

    ``input_ids``: (B, prompt_len) prompts right-padded to the length bucket;
    ``last_index``: (B,) index of each row's LAST REAL token (the head is
    applied there, not at the padded end — right-padding can't leak into
    earlier positions under the causal mask, so bucketed prefill is
    token-identical to an exact-length prefill)."""
    cfg = model.config

    @jax.jit
    def prefill(params, input_ids, last_index):
        b, lp = input_ids.shape
        dmodel = CausalLM(LMConfig.from_dict(
            {**cfg.to_dict(), "max_seq_len": lp}
        ))
        cache = init_cache(dmodel, b)
        positions = jnp.broadcast_to(jnp.arange(lp, dtype=jnp.int32), (b, lp))
        hidden, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, input_ids, positions,
            decode=True, return_hidden=True, mutable=["cache"],
        )
        head_w = head_weight(params, cfg).astype(jnp.float32)
        h_last = jnp.take_along_axis(
            hidden, last_index[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
        tok = jnp.argmax(
            h_last.astype(jnp.float32) @ head_w, axis=-1
        ).astype(jnp.int32)
        return tok, vars_["cache"]

    return prefill


def make_lm_decode_step_fn(model: CausalLM, slot_len: int):
    """Build THE persistent engine step: a jitted ``fn(params, cache, tok,
    pos) -> (cache', next_tok)`` over the fixed slot pool, cache donated so
    the slabs update in place across the engine's lifetime.

    ``tok``/``pos``: (S,) current token and cache position per slot.  Every
    slot steps every call (fixed shape — the continuous-batching discipline);
    free slots ride along at pos 0 and their outputs are discarded host-side.
    Greedy by construction: the engine's correctness anchor is token-equality
    with offline greedy ``generate``."""
    cfg = model.config
    dcfg = {**cfg.to_dict(), "max_seq_len": slot_len}

    from functools import partial

    @partial(jax.jit, donate_argnums=(1,))
    def step(params, cache, tok, pos):
        dmodel = CausalLM(LMConfig.from_dict(dcfg))
        pos = pos.astype(jnp.int32)
        cache = _map_cache_index(cache, lambda _: pos)
        hidden, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, tok[:, None], pos[:, None],
            decode=True, return_hidden=True, mutable=["cache"],
        )
        head_w = head_weight(params, cfg).astype(jnp.float32)
        nxt = jnp.argmax(
            hidden[:, -1].astype(jnp.float32) @ head_w, axis=-1
        ).astype(jnp.int32)
        return vars_["cache"], nxt

    return step


# ---------------------------------------------------------------------------
# Paged engine entry points (tpu_air.engine.kvpool)
#
# Same phases as the slab entry points above, over the paged cache layout:
# per-layer page POOLS [num_pages, page_len, h*d] shared by all slots plus a
# block_table leaf [S, pages_per_slot] mapping each slot's logical positions
# onto physical pages.  The table and per-slot indices are HOST state
# (engine/kvpool/pool.py) pushed into the cache dict at every call via leaf
# mappers, so the donated device cache never round-trips.  Prefill is
# page-sized CHUNKS — one compiled program for every prompt length — instead
# of the slab path's per-bucket compiles.
# ---------------------------------------------------------------------------


def init_paged_cache(model: CausalLM, num_slots: int, num_pages: int,
                     page_len: int, pages_per_slot: int):
    """Zero paged KV cache: every attention layer gets page pools
    ``[num_pages, page_len, h*d]`` (page 0 = the pinned null page), a
    per-slot index vector ``[S]`` and a block table ``[S, pages_per_slot]``
    of page ids (0 = unreached/null).  This is the persistent donated cache
    of a paged engine."""
    base = init_slot_cache(model, num_slots, page_len)

    def rebuild(d):
        out = {}
        for k, v in d.items():
            if not isinstance(v, dict):
                out[k] = v
            elif "cached_key" in v:
                hd = v["cached_key"].shape[-1]
                dt = v["cached_key"].dtype
                out[k] = {
                    "cached_key": jnp.zeros((num_pages, page_len, hd), dt),
                    "cached_value": jnp.zeros((num_pages, page_len, hd), dt),
                    "cache_index": jnp.zeros((num_slots,), jnp.int32),
                    "block_table": jnp.zeros(
                        (num_slots, pages_per_slot), jnp.int32),
                }
            else:
                out[k] = rebuild(v)
        return out

    return rebuild(base)


def make_paged_decode_body(model: CausalLM, slot_len: int,
                           adapters: bool = False):
    """The UNJITTED paged decode step body: ``fn(params, cache, tok, pos,
    block_table) -> (cache', next_tok)``.  Both the single-chip factory
    below and the sharded factory (engine/dist/sharded.py, which adds
    pjit in/out shardings over a ``(data, model)`` mesh) wrap this same
    body — parity between the two engines is parity of jit options, not
    of two step implementations.

    With ``adapters=True`` the signature grows three trailing args —
    ``bank_a [A+1, d, r]``, ``bank_b [A+1, r, V]``, ``adapter_ids [S]``
    — and each slot's head logits get a per-slot LoRA delta
    ``(h @ bank_a[id]) @ bank_b[id]`` gathered exactly the way the block
    table gathers pages: one dynamic-gather per step, no per-tenant
    retrace.  Bank row 0 is the zero adapter, so slots with id 0 compute
    an exact-zero delta and stay bit-identical to the base model."""
    cfg = model.config
    dcfg = {**cfg.to_dict(), "max_seq_len": slot_len}

    def step(params, cache, tok, pos, block_table,
             bank_a=None, bank_b=None, adapter_ids=None):
        dmodel = CausalLM(LMConfig.from_dict(dcfg))
        pos = pos.astype(jnp.int32)
        cache = _map_cache_index(cache, lambda _: pos)
        cache = _map_cache_leaf(
            cache, "block_table",
            lambda _: block_table.astype(jnp.int32))
        hidden, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, tok[:, None], pos[:, None],
            decode=True, return_hidden=True, mutable=["cache"],
        )
        head_w = head_weight(params, cfg).astype(jnp.float32)
        h = hidden[:, -1].astype(jnp.float32)
        logits = h @ head_w
        if adapters:
            a = bank_a[adapter_ids]                      # [S, d, r]
            b = bank_b[adapter_ids]                      # [S, r, V]
            logits = logits + jnp.einsum(
                "sr,srv->sv", jnp.einsum("sd,sdr->sr", h, a), b)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return vars_["cache"], nxt

    if not adapters:
        def base_step(params, cache, tok, pos, block_table):
            return step(params, cache, tok, pos, block_table)
        return base_step
    return step


def make_lm_paged_decode_step_fn(model: CausalLM, slot_len: int,
                                 adapters: bool = False):
    """The persistent paged engine step: jitted ``fn(params, cache, tok,
    pos, block_table) -> (cache', next_tok)``, cache donated.  Identical
    contract to :func:`make_lm_decode_step_fn` plus the block table
    ``[S, pages_per_slot]`` int32 (the host pool's authoritative table —
    rows of non-decoding slots pointed at the null page so their ride-along
    scatter can't touch a live or prefix-shared page).  ``adapters=True``
    appends the LoRA bank args (see :func:`make_paged_decode_body`); the
    banks are NOT donated — they persist across steps like params."""
    return jax.jit(make_paged_decode_body(model, slot_len, adapters),
                   donate_argnums=(1,))


def make_prefill_chunk_body(model: CausalLM, page_len: int, slot_len: int,
                            adapters: bool = False):
    """The UNJITTED chunked-prefill body: ``fn(params, cache, ids, p0,
    last_local, table_row) -> (cache', tok)`` — shared by the single-chip
    jit wrapper below and the sharded pjit wrapper (engine/dist/sharded.py,
    where ids/p0/last_local/table_row replicate: a chunk is b=1 work, only
    its page writes land in a data shard).

    With ``adapters=True`` three trailing args appear — ``bank_a``,
    ``bank_b`` and a SCALAR ``adapter_id`` (a chunk is one slot's work) —
    and the final chunk's first greedy token gets the same LoRA head
    delta as the decode body, so a tenant's stream is adapter-consistent
    from token 0."""
    cfg = model.config
    dcfg = {**cfg.to_dict(), "max_seq_len": slot_len}

    def prefill_chunk(params, cache, ids, p0, last_local, table_row,
                      bank_a=None, bank_b=None, adapter_id=None):
        dmodel = CausalLM(LMConfig.from_dict(dcfg))
        p0 = p0.astype(jnp.int32)
        # leaf shapes must stay [S]/[S, npg] across chunk and decode calls
        # (shape-stable donation); only row 0 is consulted at b=1
        cache = _map_cache_index(
            cache, lambda v: jnp.full(v.shape, p0, jnp.int32))
        cache = _map_cache_leaf(
            cache, "block_table",
            lambda v: jnp.broadcast_to(
                table_row.astype(jnp.int32)[None], v.shape))
        positions = (p0 + jnp.arange(page_len, dtype=jnp.int32))[None]
        hidden, vars_ = dmodel.apply(
            {"params": params, "cache": cache}, ids, positions,
            decode=True, return_hidden=True, mutable=["cache"],
        )
        head_w = head_weight(params, cfg).astype(jnp.float32)
        h_last = hidden[0, last_local.astype(jnp.int32)].astype(jnp.float32)
        logits = h_last @ head_w
        if adapters:
            logits = logits + (h_last @ bank_a[adapter_id]) @ bank_b[adapter_id]
        tok = jnp.argmax(logits).astype(jnp.int32)
        return vars_["cache"], tok

    if not adapters:
        def base_chunk(params, cache, ids, p0, last_local, table_row):
            return prefill_chunk(params, cache, ids, p0, last_local,
                                 table_row)
        return base_chunk
    return prefill_chunk


def make_lm_prefill_chunk_fn(model: CausalLM, page_len: int, slot_len: int,
                             adapters: bool = False):
    """Build THE chunked-prefill unit: a jitted ``fn(params, cache, ids,
    p0, last_local, table_row) -> (cache', tok)``, cache donated.

    One call processes ONE page-sized chunk of ONE slot's prompt:

    * ``ids`` ``[1, page_len]`` — the chunk's tokens, right-padded on the
      final (partial) chunk.  Pad positions write don't-care K/V into the
      page tail; the per-slot validity mask hides them until decode
      appends overwrite them — the slab engine's stale-bytes discipline.
    * ``p0`` — the chunk's first global position (page-aligned).
    * ``last_local`` — index of the prompt's last real token WITHIN this
      chunk, valid only on the final chunk; the returned greedy first
      token is read there (intermediate chunks' tok is discarded).
    * ``table_row`` ``[pages_per_slot]`` — the slot's block-table row (the
      pool may substitute the null page for a fully-prefix-covered
      prompt's re-run tail chunk: PagedKVPool.chunk_row).

    Fixed shapes -> ONE compiled program covers every prompt length; the
    engine interleaves these calls between decode steps so long prompts
    stream in without stalling in-flight decodes."""
    return jax.jit(make_prefill_chunk_body(model, page_len, slot_len,
                                           adapters),
                   donate_argnums=(1,))


def page_copy_body(cache, dst, src):
    """The UNJITTED copy-on-write body: copy page ``src`` onto page ``dst``
    in every layer's K and V pools; index and table leaves pass through.
    Wrapped by :func:`make_page_copy_fn` (single chip) and the sharded
    factory (engine/dist/sharded.py)."""
    dst = dst.astype(jnp.int32) if hasattr(dst, "astype") else dst
    src = src.astype(jnp.int32) if hasattr(src, "astype") else src

    def walk(d):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = walk(v)
            elif k in ("cached_key", "cached_value"):
                page = jax.lax.dynamic_slice(
                    v, (src, 0, 0), (1,) + v.shape[1:])
                out[k] = jax.lax.dynamic_update_slice(
                    v, page, (dst, 0, 0))
            else:
                out[k] = v
        return out

    return walk(cache)


def make_page_copy_fn():
    """Build the copy-on-write primitive: a jitted ``fn(cache, dst, src) ->
    cache'`` (cache donated) copying page ``src`` onto page ``dst`` in every
    layer's K and V pools.  Run once when a slot's first decode append would
    land in a prefix-shared tail page (PagedKVPool.resolve_cow)."""
    return jax.jit(page_copy_body, donate_argnums=(0,))


_GEN_CACHE: Dict[Tuple, Any] = {}
_GEN_CACHE_MAX = 16


def generate(model: CausalLM, params, input_ids, max_new_tokens: int = 64,
             do_sample: bool = False, temperature: float = 1.0, top_k: int = 0,
             eos_token_id: Optional[int] = None, rng=None,
             early_stop: bool = True):
    """Convenience wrapper caching compiled generate fns per config (the
    t5/generate.py pattern — repeated same-shape calls never retrace)."""
    cfg_key = tuple(sorted(model.config.to_dict().items()))
    key = (cfg_key, max_new_tokens, do_sample, temperature, top_k,
           eos_token_id, early_stop)
    if key not in _GEN_CACHE:
        if len(_GEN_CACHE) >= _GEN_CACHE_MAX:
            _GEN_CACHE.pop(next(iter(_GEN_CACHE)))
        _GEN_CACHE[key] = make_lm_generate_fn(
            model, max_new_tokens, do_sample, temperature, top_k, eos_token_id,
            early_stop,
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    ids = jnp.asarray(input_ids, jnp.int32)
    # batch-size bucketing (t5/generate.py pattern): a ragged tail batch
    # reuses the compiled program; the filler rows' outputs are discarded.
    # Same semantics caveat as the T5 path: GREEDY outputs are bit-identical
    # to the unpadded batch; SAMPLED outputs are distributionally equivalent
    # but not bitwise reproducible across bucket sizes (sampling noise is
    # keyed by the padded batch shape).  With ``eos_token_id`` set, filler
    # rows are born finished and cost ~0 under early_stop; with no EOS the
    # fixed-trip scan runs filler rows for the full decode budget — the
    # bucketing win is then compile-cache reuse only.
    n = ids.shape[0]
    bucket = 1 << max(0, int(n - 1).bit_length())
    live_mask = None
    if bucket != n:
        ids = jnp.concatenate(
            [ids, jnp.full((bucket - n, ids.shape[1]),
                           model.config.pad_token_id, jnp.int32)]
        )
        # declare the appended rows as filler EXPLICITLY (this wrapper knows
        # which rows it added) instead of inferring filler from all-pad
        # content — a legitimate all-pad user prompt stays live (ADVICE r5)
        live_mask = jnp.concatenate(
            [jnp.ones((n,), bool), jnp.zeros((bucket - n,), bool)]
        )
    return _GEN_CACHE[key](params, ids, rng, live_mask)[:n]
