"""Shared token sampler for the autoregressive generate loops (T5 + LM)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_token(logits, rng, do_sample: bool, temperature: float, top_k: int):
    """Greedy argmax, or temperature/top-k categorical sampling.

    ``top_k`` uses ``lax.top_k`` (partial selection), not a full vocab sort —
    this runs once per decoded token."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e9, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
