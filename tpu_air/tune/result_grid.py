"""ResultGrid — the return value of Tuner.fit().

Parity surface: ``.errors``, ``.get_best_result()``,
``best_result.checkpoint/.metrics`` (Introduction_to_Ray_AI_Runtime.ipynb:
cc-49,52), per-trial failure isolation (§5: "a failed trial must not kill the
sweep — ResultGrid.errors semantics").
"""

from __future__ import annotations

from typing import List, Optional

import pandas as pd

from tpu_air.train.result import Result


class ResultGrid:
    def __init__(self, results: List[Result], metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[Exception]:
        return [r.error for r in self._results if r.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    @property
    def num_terminated(self) -> int:
        return len(self._results) - self.num_errors

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode or "min"
        if metric is None:
            raise ValueError("no metric configured; pass metric= explicitly")
        candidates = [
            r for r in self._results
            if r.error is None and r.metrics.get(metric) is not None
        ]
        if not candidates:
            raise RuntimeError(
                f"no completed trial reported metric {metric!r} "
                f"({self.num_errors} errored)"
            )
        sign = -1.0 if mode == "max" else 1.0
        return min(candidates, key=lambda r: sign * float(r.metrics[metric]))

    def get_dataframe(self) -> pd.DataFrame:
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            for k, v in (r.config or {}).items():
                if isinstance(v, (int, float, str, bool)) or v is None:
                    row[f"config/{k}"] = v
            row["error"] = repr(r.error) if r.error else None
            row["path"] = r.path
            rows.append(row)
        return pd.DataFrame(rows)

    def __repr__(self):
        return (f"ResultGrid({len(self._results)} trials, "
                f"{self.num_errors} errored)")
