"""Trial schedulers — ASHA (async successive halving) and FIFO.

Parity surface: ``ray.tune.schedulers.async_hyperband.ASHAScheduler(max_t=…)``
(Model_finetuning…ipynb:cc-51,57).  The reference uses it to early-stop
underperforming HPO trials on ``eval_loss`` per epoch (§3.2: "per-epoch metric
report → scheduler decision (continue/stop)").

Decision protocol: the Tuner calls ``on_result(trial_id, metrics)`` for every
streamed report and gets back CONTINUE or STOP.  ASHA is *asynchronous*: rung
decisions use whatever results have arrived so far — no barrier across trials
(the property that lets TPU sub-mesh leases recycle immediately).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def set_metric(self, metric: str, mode: str) -> None:
        """Inherit metric/mode from TuneConfig when not set explicitly."""
        if getattr(self, "metric", None) is None:
            self.metric = metric
        if getattr(self, "mode", None) is None:
            self.mode = mode

    def on_result(self, trial_id: str, metrics: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial_id: str) -> None:
        pass

    def reset(self) -> None:
        """Clear per-sweep state.  Called at the start of every ``fit()`` so a
        scheduler instance may be reused across sweeps; stateful built-ins
        override this."""


class FIFOScheduler(TrialScheduler):
    """No early stopping — every trial runs to completion."""


class ASHAScheduler(TrialScheduler):
    """Async Successive Halving.

    Rungs at ``grace_period * reduction_factor**k`` (in units of
    ``time_attr``, default ``training_iteration`` = epochs here) up to
    ``max_t``.  When a trial reaches a rung, its metric joins the rung's
    record; the trial continues only if it is in the top ``1/reduction_factor``
    fraction of results seen at that rung so far.  Reaching ``max_t`` stops
    the trial (budget exhausted).
    """

    def __init__(
        self,
        time_attr: str = "training_iteration",
        metric: Optional[str] = None,
        mode: Optional[str] = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
        brackets: int = 1,  # accepted for parity; single bracket implemented
    ):
        if max_t < grace_period:
            raise ValueError("max_t must be >= grace_period")
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones ascending: g, g*rf, g*rf^2, ... < max_t
        self.milestones: List[int] = []
        t = grace_period
        while t < max_t:
            self.milestones.append(int(t))
            t *= reduction_factor
        # per-rung records keyed by trial: a trial joins each rung at most
        # once, at its first report with t >= milestone (reports may skip
        # milestone values when the loop's time_attr strides)
        self._rungs: Dict[int, Dict[str, float]] = {m: {} for m in self.milestones}
        self._stopped: set = set()

    def _key(self, metrics: Dict[str, Any]) -> Optional[float]:
        v = metrics.get(self.metric)
        if v is None:
            return None
        v = float(v)
        return -v if self.mode == "max" else v  # normalize: lower is better

    def on_result(self, trial_id: str, metrics: Dict[str, Any]) -> str:
        if trial_id in self._stopped:
            return STOP
        t = int(metrics.get(self.time_attr, 0))
        if t >= self.max_t:
            self._stopped.add(trial_id)
            return STOP
        val = self._key(metrics)
        if val is None:
            return CONTINUE
        decision = CONTINUE
        for m in self.milestones:
            if t >= m and trial_id not in self._rungs[m]:
                rung = self._rungs[m]
                rung[trial_id] = val
                vals = sorted(rung.values())
                k = max(1, int(len(vals) / self.rf))
                cutoff = vals[k - 1]
                if val > cutoff:
                    decision = STOP
                    break  # pruned here; don't join higher rungs
        if decision == STOP:
            self._stopped.add(trial_id)
        return decision

    def on_trial_complete(self, trial_id: str) -> None:
        self._stopped.discard(trial_id)

    def reset(self) -> None:
        self._rungs = {m: {} for m in self.milestones}
        self._stopped = set()
