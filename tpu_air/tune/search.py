"""Search-space primitives (SURVEY.md §1-L4).

Parity surface: ``tune.choice`` (Model_finetuning…ipynb:cc-57),
``tune.uniform``/``tune.randint`` (Introduction_to_Ray_AI_Runtime.ipynb:cc-45),
plus the standard companions (loguniform/quniform/grid_search) so user sweeps
don't hit a wall one symbol past the reference.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        if not categories:
            raise ValueError("choice() requires a non-empty sequence")
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(0, len(self.categories)))]

    def __repr__(self):
        return f"choice({self.categories})"


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False, q: float = 0):
        if upper <= lower:
            raise ValueError("upper must be > lower")
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = min(self.upper, max(self.lower, round(v / self.q) * self.q))
        return float(v)

    def __repr__(self):
        kind = "loguniform" if self.log else "uniform"
        return f"{kind}({self.lower}, {self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int):
        if upper <= lower:
            raise ValueError("upper must be > lower")
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return int(rng.integers(self.lower, self.upper))

    def __repr__(self):
        return f"randint({self.lower}, {self.upper})"


class GridSearch:
    """Marker for exhaustive grid axes (expanded, not sampled)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def __repr__(self):
        return f"grid_search({self.values})"


class SampleFrom:
    """Marker for a user-supplied sampler fn (``tune.sample_from``); plain
    callables in a config are passed through untouched."""

    def __init__(self, fn):
        self.fn = fn

    def __repr__(self):
        return f"sample_from({self.fn!r})"


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


def _grid_axes(space: Dict[str, Any], prefix: Tuple = ()) -> List[Tuple[Tuple, List[Any]]]:
    axes = []
    for k, v in space.items():
        if isinstance(v, GridSearch):
            axes.append((prefix + (k,), v.values))
        elif isinstance(v, dict):
            axes.extend(_grid_axes(v, prefix + (k,)))
    return axes


def _set_path(d: Dict[str, Any], path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d[k]
    d[path[-1]] = value


def sample_space(space: Dict[str, Any], rng: np.random.Generator) -> Dict[str, Any]:
    """One concrete config: Domains sampled, dicts recursed, literals kept.
    GridSearch leaves must be resolved by the caller (expand_grid)."""
    out: Dict[str, Any] = {}
    for k, v in space.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = sample_space(v, rng)
        elif isinstance(v, GridSearch):
            raise ValueError("grid_search must be expanded before sampling")
        elif isinstance(v, SampleFrom):
            out[k] = v.fn(out)  # sees previously-resolved keys (spec dict)
        else:
            out[k] = v  # literals — including callables — pass through
    return out


def expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand grid_search axes into the cross-product of sub-spaces (each
    still containing Domains for sample_space)."""
    import copy
    import itertools

    axes = _grid_axes(space)
    if not axes:
        return [space]
    out = []
    for combo in itertools.product(*(vals for _, vals in axes)):
        s = copy.deepcopy(space)
        for (path, _), val in zip(axes, combo):
            _set_path(s, path, val)
        out.append(s)
    return out
