"""tpu_air.tune — trial-parallel hyperparameter optimization (L4).

Reference surface (SURVEY.md §1-L4): ``Tuner``, ``TuneConfig``, search-space
primitives (``choice``/``uniform``/``randint``/…), ``ASHAScheduler``,
``ResultGrid``.
"""

from .result_grid import ResultGrid
from .schedulers import ASHAScheduler, FIFOScheduler, TrialScheduler
from .search import (
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from .tuner import TuneConfig, Tuner

# reference import spellings: ray.tune.tuner.TuneConfig and
# ray.tune.schedulers.async_hyperband.ASHAScheduler both resolve here
from . import schedulers  # noqa: F401

__all__ = [
    "ASHAScheduler",
    "FIFOScheduler",
    "ResultGrid",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "sample_from",
    "uniform",
]
