"""Tuner — trial-parallel HPO over a Trainer (L4; SURVEY.md §3.2).

Parity surface: ``Tuner(trainer, param_space, tune_config, run_config)``
(Model_finetuning…ipynb:cc-57), ``TuneConfig(metric, mode, num_samples,
scheduler)`` (both import spellings), ``tuner.fit() -> ResultGrid``.

TPU-native resource model (§2C trial parallelism): every trial is a trial
actor requesting the trainer's ``ScalingConfig`` worth of **chips**; the core
scheduler queues actors until a chip lease frees, so concurrent trials occupy
disjoint sub-meshes of the slice and excess trials wait — the reference's
"1 worker per trial so trials parallelize" dial (cc-53-54) maps to
``num_chips_per_worker`` sizing the per-trial lease.

Driver loop: trials stream per-epoch reports through the object store
(`{trial}-report-{i}` keys written by the trial actor's decision callback);
the scheduler (e.g. ASHA) judges each report and the driver plants a
`{trial}-stop` marker that the trial's next ``session.report`` observes —
asynchronous early-stopping with no barrier across trials.
"""

from __future__ import annotations

import copy
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

import tpu_air
from tpu_air.train.checkpoint import Checkpoint
from tpu_air.train.config import RunConfig
from tpu_air.train.result import Result
from tpu_air.train.trainer import BaseTrainer, JaxTrainer, _TrialRunner, _default_storage

from .result_grid import ResultGrid
from .schedulers import CONTINUE, FIFOScheduler, TrialScheduler
from .search import expand_grid, sample_space


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"
    num_samples: int = 1
    scheduler: Optional[TrialScheduler] = None
    max_concurrent_trials: Optional[int] = None
    time_budget_s: Optional[float] = None
    seed: Optional[int] = None
    reuse_actors: bool = False  # accepted for parity; actors are per-trial


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


class Tuner:
    def __init__(
        self,
        trainable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        if isinstance(trainable, BaseTrainer):
            self._trainer = trainable
        elif callable(trainable):
            # function trainable: config -> session.report(...) calls
            self._trainer = JaxTrainer(trainable)
        else:
            raise TypeError("trainable must be a Trainer or a callable")
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or self._trainer.run_config

    # -- config sampling ----------------------------------------------------
    def _sample_trial_configs(self) -> List[Dict[str, Any]]:
        """grid_search axes are exhaustive; num_samples multiplies the grid
        (Ray semantics: every grid point runs num_samples times)."""
        tc = self.tune_config
        rng = np.random.default_rng(tc.seed)
        subspaces = expand_grid(self.param_space)
        return [
            sample_space(space, rng)
            for _ in range(tc.num_samples)
            for space in subspaces
        ]

    def _trial_config(self, sampled: Dict[str, Any]) -> Dict[str, Any]:
        """Merge a sampled point over the trainer's base config.  The
        reference nests tuned keys under ``trainer_init_config``
        (Model_finetuning…ipynb:cc-57) or ``train_loop_config`` — both
        flatten into the top-level trial config the training fn reads."""
        base = dict(self._trainer._train_loop_config())
        sampled = copy.deepcopy(sampled)
        for alias in ("trainer_init_config", "train_loop_config"):
            if isinstance(sampled.get(alias), dict):
                base = _deep_merge(base, sampled.pop(alias))
        return _deep_merge(base, sampled)

    # -- fit ----------------------------------------------------------------
    def fit(self) -> ResultGrid:
        tpu_air.init()
        from tpu_air.core.runtime import get_runtime

        rt = get_runtime()
        store = rt.store
        tc = self.tune_config
        # schedulers accumulate rung/stop state per sweep; a second fit() (or
        # a scheduler shared across Tuners) must not judge trials against a
        # previous sweep's records
        scheduler = tc.scheduler or FIFOScheduler()
        scheduler.reset()
        if tc.metric:
            scheduler.set_metric(tc.metric, tc.mode)

        name = self.run_config.name or f"Tuner_{int(time.time())}_{os.urandom(3).hex()}"
        exp_dir = os.path.join(
            self.run_config.storage_path or _default_storage(), name
        )
        os.makedirs(exp_dir, exist_ok=True)

        datasets = self._trainer._preprocess()
        sc = self._trainer.scaling_config
        cc = self.run_config.checkpoint_config
        training_fn = self._trainer._training_fn()

        sampled = self._sample_trial_configs()
        n = len(sampled)
        # cap concurrency so trial actors don't exhaust host RAM even when
        # chips are plentiful; the chip lease queue enforces the mesh limit
        max_conc = tc.max_concurrent_trials or n

        max_failures = self.run_config.failure_config.max_failures

        trials: List[Dict[str, Any]] = []
        for i, s in enumerate(sampled):
            tid = f"{name}_trial_{i:05d}"
            cfg = self._trial_config(s)
            cfg["_preprocessor"] = self._trainer.preprocessor
            cfg["_scaling_config"] = sc  # trial mesh topology (dp x tp)
            if self._trainer.resume_from_checkpoint is not None:
                resume = self._trainer.resume_from_checkpoint
                cfg["resume_from_checkpoint"] = (
                    resume.to_directory() if isinstance(resume, Checkpoint) else resume
                )
            trials.append({
                "id": tid, "config": cfg, "sampled": s,
                "dir": os.path.join(exp_dir, tid),
                "runner": None, "future": None, "next_report": 1,
                "attempt": 0, "start": None,
            })

        launched = 0
        running: List[Dict[str, Any]] = []
        results: List[Optional[Result]] = [None] * n
        t0 = time.time()

        def budget_left() -> bool:
            return not (tc.time_budget_s and time.time() - t0 > tc.time_budget_s)

        def launch(tr):
            os.makedirs(tr["dir"], exist_ok=True)
            runner = _TrialRunner.options(
                num_chips=sc.total_chips or None, num_cpus=0
            ).remote()
            tr["runner"] = runner
            tr["start"] = time.time()
            tr["future"] = runner.run.remote(
                training_fn, tr["config"], tr["dir"], datasets, cc,
                sc.num_workers, tr["id"],
            )
            running.append(tr)

        def drain_reports(tr):
            """Feed streamed reports to the scheduler; ack each decision (the
            trial blocks on the ack so prunes land before the next round) and
            plant the async stop marker as a backstop."""
            while True:
                seq = tr["next_report"]
                key = f"{tr['id']}-report-{seq}"
                if not store.contains(key):
                    return
                rec = store.get(key)
                store.delete(key)
                tr["next_report"] += 1
                go = scheduler.on_result(tr["id"], rec) == CONTINUE
                if not go and not store.contains(f"{tr['id']}-stop"):
                    store.put(True, f"{tr['id']}-stop")
                store.put(go, f"{tr['id']}-ack-{seq}")

        def finalize(tr, out, err):
            idx = trials.index(tr)
            scheduler.on_trial_complete(tr["id"])
            results[idx] = self._trainer._assemble(
                out, tr["dir"], tr["config"],
                RuntimeError(err) if err else None,
            )
            tpu_air.kill(tr["runner"])
            store.delete(f"{tr['id']}-stop")
            # drop any reports that streamed after the last drain, and any
            # acks the (now dead) trial never consumed
            while store.contains(f"{tr['id']}-report-{tr['next_report']}"):
                store.delete(f"{tr['id']}-report-{tr['next_report']}")
                tr["next_report"] += 1
            for i in range(1, tr["next_report"]):
                store.delete(f"{tr['id']}-ack-{i}")

        def complete(tr):
            """Trial future resolved: finalize, or retry per FailureConfig
            (same resume-from-latest semantics as trainer._run_attempts)."""
            running.remove(tr)
            try:
                out = tpu_air.get(tr["future"])
                err = out.get("error")
                if out.get("stopped"):
                    err = None  # scheduler prune is a clean exit
            except tpu_air.RemoteError as e:
                out = {"history": [], "checkpoints": [],
                       "best_checkpoint": None, "latest_checkpoint": None}
                err = str(e)
            drain_reports(tr)
            if err is not None and tr["attempt"] < max_failures and budget_left():
                tr["attempt"] += 1
                tpu_air.kill(tr["runner"])
                # reset per-attempt stream state: drop leftover reports and
                # any stale stop marker, and restart the report cursor so the
                # retried attempt's report-1.. stream is drained from its start
                store.delete(f"{tr['id']}-stop")
                while store.contains(f"{tr['id']}-report-{tr['next_report']}"):
                    store.delete(f"{tr['id']}-report-{tr['next_report']}")
                    tr["next_report"] += 1
                for i in range(1, tr["next_report"]):
                    store.delete(f"{tr['id']}-ack-{i}")
                tr["next_report"] = 1
                latest = out.get("latest_checkpoint")
                if latest:
                    tr["config"]["resume_from_checkpoint"] = latest[0]
                launch(tr)
                return
            finalize(tr, out, err)

        while launched < n and len(running) < max_conc and budget_left():
            launch(trials[launched])
            launched += 1

        while running:
            futures = [tr["future"] for tr in running]
            # short slot: trials block on per-report acks, so drain latency
            # is training latency
            ready, _ = tpu_air.wait(futures, num_returns=1, timeout=0.05)
            for tr in list(running):
                drain_reports(tr)
                if tr["future"] in ready:
                    complete(tr)
            if not budget_left():
                # budget exhausted: stop running trials at their next report,
                # launch nothing further (unlaunched trials are dropped)
                for tr in running:
                    if not store.contains(f"{tr['id']}-stop"):
                        store.put(True, f"{tr['id']}-stop")
            while launched < n and len(running) < max_conc and budget_left():
                launch(trials[launched])
                launched += 1

        return ResultGrid([r for r in results if r is not None],
                          metric=tc.metric, mode=tc.mode)
