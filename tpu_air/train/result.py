"""Result of a training run (Introduction…ipynb:cc-36: ``.checkpoint``,
``.best_checkpoints``, ``.metrics``, ``.error``)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import pandas as pd

from .checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    error: Optional[Exception] = None
    path: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    best_checkpoints: List[Tuple[Checkpoint, Dict[str, Any]]] = field(default_factory=list)
    config: Dict[str, Any] = field(default_factory=dict)

    @property
    def metrics_dataframe(self) -> pd.DataFrame:
        return pd.DataFrame(self.metrics_history)

    def __repr__(self) -> str:
        keys = {k: v for k, v in self.metrics.items() if not k.startswith("_")}
        return f"Result(metrics={keys}, error={self.error!r}, checkpoint={self.checkpoint})"
