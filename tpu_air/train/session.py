"""Training session context — the worker↔driver reporting channel.

SURVEY.md §5 metrics notes: "a ``report(metrics, checkpoint)`` primitive from
workers → driver, pluggable sinks."  The training loop calls
``session.report`` per epoch; the session records history, applies
score-based checkpoint retention (CheckpointConfig, cc-40), forwards metrics
to sinks (tensorboard/prometheus when available), and raises ``StopTrial``
when a Tune scheduler has pruned the trial (ASHA, cc-51).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_air.faults import plan as _faults
from tpu_air.observability import tracing as _tracing

from .checkpoint import Checkpoint
from .config import CheckpointConfig


class StopTrial(Exception):
    """Raised inside the training loop when the scheduler stops this trial."""


class Session:
    def __init__(
        self,
        run_dir: str,
        checkpoint_config: Optional[CheckpointConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        config: Optional[Dict[str, Any]] = None,
        world_size: int = 1,
        decision_cb: Optional[Callable[[Dict[str, Any]], bool]] = None,
        sinks: Optional[List] = None,
    ):
        self.run_dir = run_dir
        self.checkpoint_config = checkpoint_config or CheckpointConfig()
        self.datasets = datasets or {}
        self.config = config or {}
        self.world_size = world_size
        self.decision_cb = decision_cb
        self.sinks = sinks if sinks is not None else _default_sinks(run_dir)
        self.history: List[Dict[str, Any]] = []
        self.checkpoints: List[Tuple[str, Dict[str, Any]]] = []  # (dir, metrics)
        self._iter = 0
        # airtrace: ambient context at session construction (the trainer's
        # task span on the worker) so every train.iteration span lands on
        # the same trial timeline; report-to-report window stamps
        self._trace_ctx = _tracing.current_propagation()
        self._last_report_ns = _tracing.now_ns() if _tracing.enabled() else 0
        os.makedirs(run_dir, exist_ok=True)

    # -- dataset access (train_loop_per_worker surface) --------------------
    def get_dataset_shard(self, name: str):
        return self.datasets.get(name)

    # -- reporting ---------------------------------------------------------
    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        self._iter += 1
        if _faults.enabled():
            # deterministic chaos (docs/RESILIENCE.md): a "kill" here takes
            # the whole trial actor down BEFORE this report's checkpoint is
            # retained — exactly the crash FailureConfig recovery must
            # survive by resuming from the previous retained checkpoint
            spec = _faults.perturb("train.report", key=str(self._iter))
            if spec is not None and spec.action == "kill":
                os._exit(1)
        rec = dict(metrics)
        rec.setdefault("training_iteration", self._iter)
        rec.setdefault("_timestamp", time.time())
        self.history.append(rec)
        if _tracing.enabled():
            self._emit_iteration_span()
        with open(os.path.join(self.run_dir, "progress.jsonl"), "a") as f:
            f.write(json.dumps(rec, default=float) + "\n")
        for sink in self.sinks:
            try:
                sink.log(rec, self._iter)
            except Exception:  # noqa: BLE001 — a broken sink must not kill the training loop
                pass
        if checkpoint is not None:
            self._retain(checkpoint, rec)
            if self.checkpoint_config.publish_weights_to:
                self._publish_weights(checkpoint, rec)
        # pass the internal monotone counter separately: user metrics may
        # override training_iteration, but report streaming must stay
        # contiguous (the Tune driver drains report-1, report-2, …)
        if self.decision_cb is not None and not self.decision_cb(rec, self._iter):
            raise StopTrial(f"trial stopped by scheduler at iteration {self._iter}")

    def _emit_iteration_span(self) -> None:
        """One ``train.iteration`` span per report, covering the window
        since the previous report (what ``step_timer`` summarizes) so the
        trial's cadence is visible on the same timeline as everything else."""
        now = _tracing.now_ns()
        if self._trace_ctx is None:
            # no ambient context at construction (tracing enabled later, or
            # a bare local session): root one trace for the whole session
            self._trace_ctx = {"trace_id": _tracing.new_trace_id()}
        _tracing.record_span(
            "train.iteration",
            trace_id=self._trace_ctx.get("trace_id"),
            parent_id=self._trace_ctx.get("span_id"),
            start_ns=self._last_report_ns or now,
            end_ns=now,
            attrs={"iteration": self._iter, "run_dir": self.run_dir},
        )
        self._last_report_ns = now

    # -- weight publishing (live-serving handoff) ----------------------------
    def _publish_weights(self, checkpoint: Checkpoint,
                         metrics: Dict[str, Any]) -> None:
        """Publish the retained checkpoint's params to the configured
        WeightStore (CheckpointConfig.publish_weights_to).  The publish is
        torn-proof (manifest written last) and checksummed; a failure —
        including an injected ``weights.publish`` fault — must not kill the
        training loop: serving simply keeps the previous version."""
        from tpu_air.serve.weights import WeightStore

        try:
            params = checkpoint.get_params()
        except Exception:  # noqa: BLE001 — dict/dir checkpoint without params
            params = None
        if params is None:
            return
        cfg = self.checkpoint_config
        try:
            store = WeightStore(cfg.publish_weights_to)
            store.publish(params, metadata={
                "iteration": self._iter,
                "run_dir": self.run_dir,
                "metrics": {k: v for k, v in metrics.items()
                            if isinstance(v, (int, float, str))},
            })
            store.gc(keep=cfg.num_to_keep or 2)
        except Exception:  # noqa: BLE001 — torn publish / store error: the
            pass           # trial continues; the store still ends in a sealed
            # state (no manifest for the torn version) so serving never sees it

    # -- retention (CheckpointConfig semantics, cc-40) ----------------------
    def _retain(self, checkpoint: Checkpoint, metrics: Dict[str, Any]):
        import tempfile

        ckpt_dir = os.path.join(self.run_dir, f"checkpoint_{self._iter:06d}")
        src = checkpoint.path
        checkpoint.to_directory(ckpt_dir)
        # from_model() stages into a tempdir; once copied under run_dir the
        # staging copy would leak one param tree per epoch — remove it and
        # repoint the handle at the retained copy.
        if (
            src
            and os.path.abspath(src) != os.path.abspath(ckpt_dir)
            and os.path.abspath(src).startswith(tempfile.gettempdir() + os.sep)
        ):
            shutil.rmtree(src, ignore_errors=True)
            checkpoint._path = ckpt_dir
        self.checkpoints.append((ckpt_dir, metrics))
        cfg = self.checkpoint_config
        if cfg.num_to_keep is None or len(self.checkpoints) <= cfg.num_to_keep:
            return
        attr = cfg.checkpoint_score_attribute
        if attr:
            sign = 1 if cfg.checkpoint_score_order == "min" else -1
            ranked = sorted(
                self.checkpoints,
                key=lambda cm: sign * float(cm[1].get(attr, float("inf") * sign)),
            )
        else:
            ranked = list(self.checkpoints)  # keep most recent
            ranked.reverse()
        keep = ranked[: cfg.num_to_keep]
        for path, _ in self.checkpoints:
            if all(path != k[0] for k in keep):
                shutil.rmtree(path, ignore_errors=True)
        self.checkpoints = [cm for cm in self.checkpoints if any(cm[0] == k[0] for k in keep)]

    # -- results ------------------------------------------------------------
    def best_checkpoint(self) -> Optional[Tuple[str, Dict[str, Any]]]:
        if not self.checkpoints:
            return None
        cfg = self.checkpoint_config
        attr = cfg.checkpoint_score_attribute
        if not attr:
            return self.checkpoints[-1]
        sign = 1 if cfg.checkpoint_score_order == "min" else -1
        return min(
            self.checkpoints,
            key=lambda cm: sign * float(cm[1].get(attr, float("inf") * sign)),
        )

    def latest_checkpoint(self) -> Optional[Tuple[str, Dict[str, Any]]]:
        return self.checkpoints[-1] if self.checkpoints else None


def _default_sinks(run_dir: str) -> List:
    """Tensorboard logging is opt-in (TPU_AIR_TENSORBOARD=1): the reference
    pins tensorboardX but never configures it (SURVEY.md §5 "Sinks pinned but
    not configured"), and the writer's protobuf import chain costs ~2.5s per
    worker process — too heavy to pay silently in every trial."""
    if os.environ.get("TPU_AIR_TENSORBOARD", "0") != "1":
        return []
    try:
        from tpu_air.utils.metrics import TensorboardSink

        return [TensorboardSink(run_dir)]
    except Exception:  # noqa: BLE001 — tensorboard missing or broken: run without the sink
        return []


# -- module-level session (what user train loops import) ---------------------

_active: Optional[Session] = None


def _set_active(s: Optional[Session]):
    global _active
    _active = s


def get_session() -> Session:
    if _active is None:
        raise RuntimeError("no active training session (call inside a trainer loop)")
    return _active


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_dataset_shard(name: str):
    return get_session().get_dataset_shard(name)


def get_config() -> Dict[str, Any]:
    return get_session().config


def get_world_size() -> int:
    return get_session().world_size
