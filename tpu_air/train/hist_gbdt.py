"""Histogram-based gradient-boosted trees with allreduce-merged statistics.

The reference's ``XGBoostTrainer`` (Introduction_to_Ray_AI_Runtime.ipynb:cc-32)
trains xgboost with ``tree_method="approx"``: every rank holds its row shard,
per-node gradient/hessian HISTOGRAMS are allreduced through rabit, and all
ranks grow the SAME tree on the merged (global) statistics.  This module is
that algorithm over tpu_air's host-side collectives facade (SURVEY.md §2D):

* quantile bin edges are built from rank-local candidate quantiles merged by
  weighted pooling (the quantile-sketch-merge analog — like xgboost's approx
  sketch, the edges depend slightly on the sharding, but are identical on
  every rank);
* per boosting round a tree grows depth-wise: each depth's
  (node, feature, bin) gradient/hessian/count histograms are summed over
  local rows, allreduced, and the identical merged histograms drive the
  identical split choice on every rank — so after every round **all ranks
  hold bit-identical trees** (rabit semantics; asserted by
  tests/test_train.py), unlike bagging where each rank's model differs;
* split gain and leaf values use the standard second-order formulas
  (gain = GL^2/(HL+lambda) + GR^2/(HR+lambda) - G^2/(H+lambda),
  leaf = -eta * G/(H+lambda)).

Objectives: ``binary:logistic`` (grad = p - y, hess = p(1-p)) and
``reg:squarederror`` (grad = pred - y, hess = 1).  Single-process training is
the world_size=1 special case of the same code path, so metrics no longer
shift in kind between ``num_workers=1`` and ``num_workers=N``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _sigmoid(x):
    out = np.empty_like(x)
    pos = x >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-x[pos]))
    ex = np.exp(x[~pos])
    out[~pos] = ex / (1.0 + ex)
    return out


class _NoComm:
    """world_size=1: allreduce is the identity."""

    rank = 0
    world = 1

    def allreduce_sum(self, arr: np.ndarray, tag: str) -> np.ndarray:
        return arr

    def allgather(self, obj: Any, tag: str) -> List[Any]:
        return [obj]


class CollectivesComm:
    """Adapter over tpu_air.parallel.collectives for the worker actors."""

    def __init__(self, rank: int, world: int, namespace: str,
                 timeout: float = 3600.0):
        self.rank = rank
        self.world = world
        self.namespace = namespace
        self.timeout = timeout
        self._seq = 0
        self._names: List[str] = []

    def _name(self, tag: str) -> str:
        self._seq += 1
        name = f"{self.namespace}-{tag}-{self._seq}"
        self._names.append(name)
        return name

    def drain_store_keys(self) -> List[str]:
        """Store keys of completed collectives (safe to delete once every
        rank has returned from the calls — the facade has no auto-cleanup)."""
        keys = [f"ar-{n}-{r}" for n in self._names for r in range(self.world)]
        self._names.clear()
        return keys

    def allreduce_sum(self, arr: np.ndarray, tag: str) -> np.ndarray:
        from tpu_air.parallel.collectives import allreduce

        # reduce_fn sees the rank-ordered list on every rank -> the summed
        # array is bit-identical everywhere (the determinism the tree
        # growth relies on)
        return allreduce(
            np.asarray(arr), name=self._name(tag), rank=self.rank,
            world_size=self.world,
            reduce_fn=lambda vals: np.sum(np.stack(vals, axis=0), axis=0),
            timeout=self.timeout,
        )

    def allgather(self, obj: Any, tag: str) -> List[Any]:
        from tpu_air.parallel.collectives import allreduce

        return allreduce(
            obj, name=self._name(tag), rank=self.rank,
            world_size=self.world, reduce_fn=list, timeout=self.timeout,
        )


class HistGBDT:
    """The merged-histogram booster.  Scoring API matches what
    ``GBDTPredictor`` expects (``predict`` / ``predict_proba``)."""

    def __init__(
        self,
        objective: str = "binary:logistic",
        eta: float = 0.3,
        max_depth: int = 6,
        min_child_weight: float = 1.0,
        reg_lambda: float = 1.0,
        max_bins: int = 256,
    ):
        self.objective = objective
        self.is_classif = "logistic" in objective or "binary" in objective
        self.eta = float(eta)
        self.max_depth = int(max_depth)
        self.min_child_weight = float(min_child_weight)
        self.reg_lambda = float(reg_lambda)
        self.max_bins = int(max_bins)
        self.trees: List[Dict[str, np.ndarray]] = []
        self._edges: Optional[List[np.ndarray]] = None  # per-feature cut values
        # training state (rank-local; dropped on checkpointing via __getstate__
        # staying intact — state is plain numpy, picklable, but only trees and
        # edges are needed to score)
        self._Xb = None
        self._g = None
        self._margin = None
        self._y = None
        self._comm = _NoComm()

    # -- setup ---------------------------------------------------------------
    def setup(self, X: np.ndarray, y: np.ndarray, comm=None) -> None:
        """Bind the rank-local shard and build the (merged) bin edges."""
        self._comm = comm or _NoComm()
        X = np.asarray(X, dtype=np.float64)
        self._y = np.asarray(y, dtype=np.float64)
        self._edges = self._build_edges(X)
        self._Xb = self._digitize(X)
        self._margin = np.zeros(len(X), dtype=np.float64)

    def _build_edges(self, X: np.ndarray) -> List[np.ndarray]:
        grid = np.linspace(0.0, 1.0, self.max_bins + 1)[1:-1]
        local = [
            (np.quantile(X[:, j], grid) if len(X) else np.zeros(0))
            for j in range(X.shape[1])
        ]
        gathered = self._comm.allgather(
            {"cands": local, "n": len(X)}, "bin-edges"
        )
        edges: List[np.ndarray] = []
        for j in range(X.shape[1]):
            vals, wts = [], []
            for part in gathered:
                c = np.asarray(part["cands"][j], dtype=np.float64)
                if len(c) == 0:
                    continue
                vals.append(c)
                wts.append(np.full(len(c), part["n"] / len(c)))
            if not vals:
                edges.append(np.zeros(0))
                continue
            v = np.concatenate(vals)
            w = np.concatenate(wts)
            order = np.argsort(v, kind="stable")
            v, w = v[order], w[order]
            cum = np.cumsum(w)
            targets = np.linspace(0, cum[-1], self.max_bins + 1)[1:-1]
            picked = v[np.searchsorted(cum, targets, side="left").clip(0, len(v) - 1)]
            edges.append(np.unique(picked))
        return edges

    def _digitize(self, X: np.ndarray) -> np.ndarray:
        Xb = np.empty(X.shape, dtype=np.int32)
        for j, e in enumerate(self._edges):
            # bin b: value <= edges[b] for b < len(e); last bin is the rest.
            Xb[:, j] = np.searchsorted(e, X[:, j], side="left")
        return Xb

    # -- boosting ------------------------------------------------------------
    def _grad_hess(self):
        if self.is_classif:
            p = _sigmoid(self._margin)
            return p - self._y, p * (1.0 - p)
        return self._margin - self._y, np.ones_like(self._margin)

    def fit_one_round(self) -> None:
        """Grow ONE tree on merged histograms and update local margins."""
        g, h = self._grad_hess()
        Xb = self._Xb
        n, F = Xb.shape
        B = self.max_bins
        # tree arrays (preallocated worst case: full binary tree)
        max_nodes = 2 ** (self.max_depth + 1)
        feat = np.full(max_nodes, -1, dtype=np.int32)
        cutb = np.zeros(max_nodes, dtype=np.int32)
        cutv = np.zeros(max_nodes, dtype=np.float64)
        left = np.full(max_nodes, -1, dtype=np.int32)
        right = np.full(max_nodes, -1, dtype=np.int32)
        leaf = np.zeros(max_nodes, dtype=np.float64)
        node_g = np.zeros(max_nodes)
        node_h = np.zeros(max_nodes)
        n_nodes = 1

        pos = np.zeros(n, dtype=np.int32)  # row -> node id
        active = [0]
        first_level = True
        for depth in range(self.max_depth):
            if not active:
                break
            slot = {nid: s for s, nid in enumerate(active)}
            S = len(active)
            # (S, F, B) histograms of grad / hess / count over LOCAL rows
            row_slot = np.full(n, -1, dtype=np.int64)
            for nid, s in slot.items():
                row_slot[pos == nid] = s
            live = row_slot >= 0
            hist = np.zeros((3, S, F, B), dtype=np.float64)
            if live.any():
                rs = row_slot[live]
                gl = g[live]
                hl = h[live]
                for j in range(F):
                    key = rs * B + Xb[live, j]
                    hist[0, :, j, :] += np.bincount(
                        key, weights=gl, minlength=S * B
                    ).reshape(S, B)
                    hist[1, :, j, :] += np.bincount(
                        key, weights=hl, minlength=S * B
                    ).reshape(S, B)
                    hist[2, :, j, :] += np.bincount(
                        key, minlength=S * B
                    ).reshape(S, B)
            # THE rabit analog: merged histograms are identical on all ranks,
            # so the split decisions below are identical on all ranks.
            hist = self._comm.allreduce_sum(hist, f"hist-d{depth}")

            next_active = []
            for nid, s in slot.items():
                G = hist[0, s, 0, :].sum()
                H = hist[1, s, 0, :].sum()
                if first_level:
                    node_g[nid], node_h[nid] = G, H
                best = self._best_split(hist[:, s], G, H)
                if best is None:
                    continue  # stays a leaf
                j, b, GL, HL = best
                l_id, r_id = n_nodes, n_nodes + 1
                n_nodes += 2
                feat[nid], cutb[nid] = j, b
                cutv[nid] = (
                    self._edges[j][b] if b < len(self._edges[j]) else np.inf
                )
                left[nid], right[nid] = l_id, r_id
                node_g[l_id], node_h[l_id] = GL, HL
                node_g[r_id], node_h[r_id] = G - GL, H - HL
                in_node = pos == nid
                go_left = in_node & (Xb[:, j] <= b)
                pos[go_left] = l_id
                pos[in_node & ~go_left] = r_id
                next_active += [l_id, r_id]
            active = next_active
            first_level = False

        internal = left[:n_nodes] >= 0
        leaf[:n_nodes] = np.where(
            internal, 0.0,
            -self.eta * node_g[:n_nodes] / (node_h[:n_nodes] + self.reg_lambda),
        )
        tree = {
            "feat": feat[:n_nodes].copy(), "cutv": cutv[:n_nodes].copy(),
            "cutb": cutb[:n_nodes].copy(), "left": left[:n_nodes].copy(),
            "right": right[:n_nodes].copy(), "leaf": leaf[:n_nodes].copy(),
        }
        self.trees.append(tree)
        # rebind, not in-place: the runtime round-trips actor state through
        # the object store, whose zero-copy reads come back READ-ONLY
        self._margin = self._margin + leaf[pos]

    def _best_split(self, hist_sfb, G, H):
        """Best (feature, bin) by gain over the merged histograms; None when
        no split clears min_child_weight / positive gain.  Deterministic
        tie-break: lowest feature, then lowest bin."""
        lam = self.reg_lambda
        parent = G * G / (H + lam)
        best = None
        best_gain = 1e-12
        for j in range(hist_sfb.shape[1]):
            GL = np.cumsum(hist_sfb[0, j, :-1])
            HL = np.cumsum(hist_sfb[1, j, :-1])
            GR, HR = G - GL, H - HL
            ok = (HL >= self.min_child_weight) & (HR >= self.min_child_weight)
            gain = np.where(
                ok, GL**2 / (HL + lam) + GR**2 / (HR + lam) - parent, -np.inf
            )
            b = int(np.argmax(gain))
            if gain[b] > best_gain:
                best_gain = float(gain[b])
                best = (j, b, float(GL[b]), float(HL[b]))
        return best

    # -- metrics over the CURRENT margins (global via allreduced sums) -------
    def local_metric_sums(self) -> Dict[str, float]:
        if self.is_classif:
            p = _sigmoid(self._margin)
            eps = 1e-7
            pc = np.clip(p, eps, 1 - eps)
            ll = -np.sum(self._y * np.log(pc) + (1 - self._y) * np.log(1 - pc))
            return {
                "n": float(len(self._y)),
                "ll_sum": float(ll),
                "err_sum": float(np.sum((p > 0.5) != self._y)),
            }
        return {
            "n": float(len(self._y)),
            "se_sum": float(np.sum((self._margin - self._y) ** 2)),
        }

    # -- scoring (raw feature values; no training state needed) --------------
    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float64)
        out = np.zeros(len(X), dtype=np.float64)
        for t in self.trees:
            node = np.zeros(len(X), dtype=np.int32)
            for _ in range(self.max_depth + 1):
                f = t["feat"][node]
                internal = f >= 0
                if not internal.any():
                    break
                fx = X[np.arange(len(X)), np.maximum(f, 0)]
                go_left = internal & (fx <= t["cutv"][node])
                node = np.where(
                    go_left, t["left"][node],
                    np.where(internal, t["right"][node], node),
                )
            out += t["leaf"][node]
        return out

    def _proba(self, X: np.ndarray) -> np.ndarray:
        p = _sigmoid(self.predict_margin(X))
        return np.stack([1.0 - p, p], axis=1)

    def __getattr__(self, name):
        # predict_proba exists ONLY on classifier boosters, so the
        # hasattr(model, "predict_proba") branch GBDTPredictor takes stays
        # honest for regression boosters
        if name == "predict_proba" and self.__dict__.get("is_classif"):
            return self._proba
        raise AttributeError(name)

    def scoring_copy(self) -> "HistGBDT":
        """A copy carrying only what scoring needs (trees + edges +
        hyperparams) — what checkpoints ship.  NOT ``__getstate__``: the
        runtime pickles live actor instances (and this model inside them)
        through the object store, and silently dropping training state in
        the pickle protocol would corrupt those."""
        m = HistGBDT.__new__(HistGBDT)
        m.__dict__.update({
            k: v for k, v in self.__dict__.items()
            if k not in ("_Xb", "_margin", "_y", "_comm")
        })
        m._Xb = m._margin = m._y = None
        m._comm = _NoComm()
        return m

    def predict(self, X: np.ndarray) -> np.ndarray:
        if self.is_classif:
            return (self.predict_margin(X) > 0.0).astype(np.int64)
        return self.predict_margin(X)

    def signature(self) -> bytes:
        """Stable byte serialization of the booster structure — equal across
        ranks iff the trees are bit-identical (the rabit-semantics test)."""
        import hashlib

        hsh = hashlib.sha256()
        for t in self.trees:
            for k in ("feat", "cutv", "cutb", "left", "right", "leaf"):
                hsh.update(k.encode())
                hsh.update(np.ascontiguousarray(t[k]).tobytes())
        return hsh.digest()
