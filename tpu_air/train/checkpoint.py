"""Checkpoint — the inter-stage currency of the framework.

SURVEY.md §5: "the checkpoint bundles *model + tokenizer + fitted
preprocessor*, which is what makes train→tune→predict→serve composable."
Parity surface: ``Checkpoint.from_dict/to_dict``
(Scaling_batch_inference.ipynb:cc-73,76), ``from_directory/to_directory``,
typed accessors ``get_model/get_tokenizer/get_preprocessor``
(predictor.py:63-70), ``from_model`` (cc-83), and dtype/placement-morphing
load (fp16/`device_map="auto"` analog: ``get_params(dtype=..., sharding=...)``,
Model_finetuning…ipynb:cc-64).

Layout on disk (directory checkpoints)::

    checkpoint/
      kind.json            # {"kind": "jax_model" | "dict" | "sklearn", ...}
      model_config.json    # T5Config etc.
      params.msgpack       # flax param tree (fp32)
      tokenizer/           # tokenizer assets
      preprocessor.pkl     # fitted preprocessor (cloudpickle)
      metrics.json
      extras.pkl           # anything else (e.g. sklearn model blob)
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional

import cloudpickle
import numpy as np


def _leaf_to_numpy(x):
    """Materialize one param leaf on this host.

    Multihost leaves (a mesh spanning processes) can't go through
    ``np.asarray`` — it rejects non-fully-addressable arrays.  Reconstruct
    from the ADDRESSABLE shards instead: with the lease-shape policy
    (model/sequence axes within a host, data across hosts —
    docs/MULTIHOST.md §2) every host holds a complete copy of each leaf,
    so no cross-host traffic is needed to checkpoint."""
    if not hasattr(x, "is_fully_addressable") or x.is_fully_addressable:
        return np.asarray(x)
    if x.is_fully_replicated:
        return np.asarray(x.addressable_shards[0].data)
    first = np.asarray(x.addressable_shards[0].data)
    out = np.zeros(x.shape, first.dtype)
    filled = np.zeros(x.shape, bool)
    for s in x.addressable_shards:
        out[s.index] = np.asarray(s.data)
        filled[s.index] = True
    if not filled.all():
        raise ValueError(
            "param leaf is not reconstructible from this host's shards — "
            "keep model/sequence mesh axes within one host (whole-host "
            "lease shapes) so each host owns a full model copy"
        )
    return out


def _params_to_msgpack(params) -> bytes:
    from flax import serialization

    return serialization.msgpack_serialize(
        __import__("jax").tree_util.tree_map(_leaf_to_numpy, params)
    )


def _params_from_msgpack(blob: bytes):
    from flax import serialization

    return serialization.msgpack_restore(blob)


class Checkpoint:
    """A directory- or dict-backed immutable training artifact."""

    def __init__(self, data: Optional[Dict[str, Any]] = None, path: Optional[str] = None):
        if (data is None) == (path is None):
            raise ValueError("provide exactly one of data= or path=")
        self._data = data
        self._path = path

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(data=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path=path)

    @classmethod
    def from_model(
        cls,
        model_config=None,
        params=None,
        tokenizer=None,
        preprocessor=None,
        metrics: Optional[Dict[str, Any]] = None,
        path: Optional[str] = None,
        extras: Optional[Dict[str, Any]] = None,
    ) -> "Checkpoint":
        """Bundle a jax model (+tokenizer+preprocessor) into a directory
        checkpoint (HuggingFaceCheckpoint.from_model analog, cc-83)."""
        path = path or tempfile.mkdtemp(prefix="tpu_air-ckpt-")
        os.makedirs(path, exist_ok=True)
        kind = {"kind": "jax_model"}
        with open(os.path.join(path, "kind.json"), "w") as f:
            json.dump(kind, f)
        if model_config is not None:
            with open(os.path.join(path, "model_config.json"), "w") as f:
                f.write(
                    model_config.to_json()
                    if hasattr(model_config, "to_json")
                    else json.dumps(model_config)
                )
        if params is not None:
            with open(os.path.join(path, "params.msgpack"), "wb") as f:
                f.write(_params_to_msgpack(params))
        if tokenizer is not None:
            tokenizer.save_pretrained(os.path.join(path, "tokenizer"))
        if preprocessor is not None:
            with open(os.path.join(path, "preprocessor.pkl"), "wb") as f:
                cloudpickle.dump(preprocessor, f)
        if metrics:
            with open(os.path.join(path, "metrics.json"), "w") as f:
                json.dump(metrics, f, default=float)
        if extras:
            with open(os.path.join(path, "extras.pkl"), "wb") as f:
                cloudpickle.dump(extras, f)
        return cls(path=path)

    # -- dict/directory interop -------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        if self._data is not None:
            return dict(self._data)
        out: Dict[str, Any] = {}
        for name, loader in (
            ("model_config", self._load_model_config),
            ("params", self.get_params),
            ("preprocessor", self.get_preprocessor),
            ("metrics", self.get_metrics),
            ("extras", self._load_extras),
        ):
            try:
                v = loader()
            except (FileNotFoundError, KeyError):
                v = None
            if v is not None:
                out[name] = v
        return out

    def to_directory(self, path: Optional[str] = None) -> str:
        if self._path is not None:
            if path and os.path.abspath(path) != os.path.abspath(self._path):
                shutil.copytree(self._path, path, dirs_exist_ok=True)
                return path
            return self._path
        path = path or tempfile.mkdtemp(prefix="tpu_air-ckpt-")
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "kind.json"), "w") as f:
            json.dump({"kind": "dict"}, f)
        with open(os.path.join(path, "data.pkl"), "wb") as f:
            cloudpickle.dump(self._data, f)
        return path

    @property
    def path(self) -> Optional[str]:
        return self._path

    def _dir_file(self, name: str) -> str:
        if self._path is None:
            raise KeyError(name)
        p = os.path.join(self._path, name)
        if not os.path.exists(p):
            raise FileNotFoundError(p)
        return p

    def _dict_backed(self) -> Optional[Dict[str, Any]]:
        if self._data is not None:
            return self._data
        try:
            with open(self._dir_file("data.pkl"), "rb") as f:
                return cloudpickle.load(f)
        except (FileNotFoundError, KeyError):
            return None

    # -- typed accessors (predictor.py:63-70 parity) ------------------------
    def _load_model_config(self):
        dd = self._dict_backed()
        if dd is not None and "model_config" in dd:
            return dd["model_config"]
        with open(self._dir_file("model_config.json")) as f:
            raw = f.read()
        d = json.loads(raw)
        if d.get("model_type") == "segformer" or "hidden_sizes" in d:
            from tpu_air.models.segformer import SegformerConfig

            return SegformerConfig.from_dict(d)
        if d.get("model_type") == "causal_lm":
            from tpu_air.models.lm import LMConfig

            return LMConfig.from_dict(d)
        from tpu_air.models.t5 import T5Config

        return T5Config.from_dict(d)

    def get_params(self, dtype: Optional[str] = None, sharding=None):
        """Load the param tree, optionally morphing dtype/placement at load
        time (the fp16/device_map analog, cc-64)."""
        if self._data is not None:
            params = self._data.get("params")
        else:
            try:
                with open(self._dir_file("params.msgpack"), "rb") as f:
                    params = _params_from_msgpack(f.read())
            except (FileNotFoundError, KeyError):
                # dict checkpoint serialized via to_directory() → data.pkl
                dd = self._dict_backed()
                params = dd.get("params") if dd else None
        if params is None:
            return None
        import jax
        import jax.numpy as jnp

        def conv(x):
            arr = jnp.asarray(x, dtype=jnp.dtype(dtype) if dtype else None)
            if sharding is not None:
                arr = jax.device_put(arr, sharding)
            return arr

        return jax.tree_util.tree_map(conv, params)

    def get_model(self, model_cls=None, dtype: Optional[str] = None, sharding=None):
        """Rebuild the model.  For jax checkpoints returns ``(model, params)``;
        for sklearn-backed checkpoints returns the estimator.  ``model_cls``
        defaults by config type — the reference passes the class explicitly
        (cc-64 model_cls=T5…)."""
        dd = self._dict_backed()
        if dd is not None and "model" in dd:
            return dd["model"]
        extras = self._load_extras()
        if isinstance(extras, dict) and "sklearn_model" in extras:
            return extras["sklearn_model"]
        config = self._load_model_config()
        if dtype:
            config.dtype = dtype
        if model_cls is None:
            from tpu_air.models.lm import CausalLM, LMConfig
            from tpu_air.models.segformer import (
                SegformerConfig,
                SegformerForSemanticSegmentation,
            )
            from tpu_air.models.t5 import T5ForConditionalGeneration

            if isinstance(config, SegformerConfig):
                model_cls = SegformerForSemanticSegmentation
            elif isinstance(config, LMConfig):
                model_cls = CausalLM
            else:
                model_cls = T5ForConditionalGeneration
        model = model_cls(config)
        return model, self.get_params(dtype=None, sharding=sharding)

    def get_tokenizer(self, tokenizer_cls=None):
        dd = self._dict_backed()
        if dd is not None and "tokenizer" in dd:
            return dd["tokenizer"]
        tok_dir = self._dir_file("tokenizer")
        if tokenizer_cls is not None:
            return tokenizer_cls.from_pretrained(tok_dir)
        from tpu_air.models.tokenizer import auto_tokenizer

        return auto_tokenizer(tok_dir)

    def get_preprocessor(self):
        dd = self._dict_backed()
        if dd is not None:
            return dd.get("preprocessor")
        try:
            with open(self._dir_file("preprocessor.pkl"), "rb") as f:
                return cloudpickle.load(f)
        except (FileNotFoundError, KeyError):
            return None

    def get_metrics(self) -> Dict[str, Any]:
        dd = self._dict_backed()
        if dd is not None:
            return dd.get("metrics", {})
        try:
            with open(self._dir_file("metrics.json")) as f:
                return json.load(f)
        except (FileNotFoundError, KeyError):
            return {}

    def _load_extras(self):
        dd = self._dict_backed()
        if dd is not None:
            return dd.get("extras")
        try:
            with open(self._dir_file("extras.pkl"), "rb") as f:
                return cloudpickle.load(f)
        except (FileNotFoundError, KeyError):
            return None

    def __repr__(self):
        src = self._path if self._path else f"dict[{list((self._data or {}).keys())}]"
        return f"Checkpoint({src})"
