"""LMTrainer — long-context causal-LM training through the Trainer API.

Sequence parallelism as a CONFIG CHANGE, not a bespoke script:
``ScalingConfig(num_workers=dp, sequence_parallel=sp)`` builds a
``(data, sequence)`` mesh and runs the shard_map SP step
(parallel/sequence_parallel.py — ring attention over the sequence axis,
chunked lm-head CE, replicated params with a single psum).  The reference
caps every sequence at 512 tokens (utils.py:23-28); this trainer's context
scales with the ``sequence`` axis, wrapped in the same fit() → Result →
Checkpoint contract as T5Trainer so Tune / BatchPredictor / resume compose
unchanged.

Datasets: rows with an ``input_ids`` column (fixed-length token lists).
Targets are the global next-token shift, computed BEFORE sequence sharding.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .t5_trainer import TrainingArguments, _make_optimizer, collate
from .trainer import BaseTrainer


def lm_train_loop(config: Dict[str, Any]) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_air.models.lm import LMConfig
    from tpu_air.parallel.sequence_parallel import (
        make_sp_mesh,
        make_sp_train_step,
        shard_batch,
        sp_local_loss,
    )
    from tpu_air.parallel.shardmap_compat import shard_map_unchecked
    from tpu_air.train import session

    args: TrainingArguments = config.get("training_args") or TrainingArguments()
    for k in ("learning_rate", "num_train_epochs", "weight_decay"):
        if k in config:
            setattr(args, k, config[k])

    model_config: LMConfig = config["model_config"]
    preprocessor = config.get("_preprocessor")

    sc = config.get("_scaling_config")
    sp = getattr(sc, "sequence_parallel", None) or 1
    mp = getattr(sc, "model_parallel", None) or 1
    if mp > 1 and sp > 1:
        raise ValueError(
            "LMTrainer: model_parallel and sequence_parallel cannot be "
            "combined yet — pick one axis per run (the SP step runs inside "
            "shard_map; TP rides pjit shardings)"
        )
    if mp > 1:
        _lm_tp_loop(config, args, model_config, preprocessor, mp)
        return
    mesh = make_sp_mesh(sp=sp)
    dp = mesh.shape["data"]
    ndev = dp * sp
    pad = model_config.pad_token_id

    train_ds = session.get_dataset_shard("train")
    if train_ds is None:
        raise ValueError("LMTrainer requires a 'train' dataset")
    eval_ds = session.get_dataset_shard("evaluation") or session.get_dataset_shard("eval")

    tx_total = train_ds.count()
    global_bs = args.per_device_train_batch_size * dp
    steps_per_epoch = max(1, tx_total // global_bs)
    if args.max_steps_per_epoch:
        steps_per_epoch = min(steps_per_epoch, args.max_steps_per_epoch)
    tx = _make_optimizer(args, steps_per_epoch * args.num_train_epochs)

    step, model = make_sp_train_step(model_config, mesh, tx)

    # eval: the SAME local-loss recipe the train step differentiates
    # (sp_local_loss — single source of truth), no update, psum'd sums
    def eval_local(params, input_ids, targets):
        s, c = sp_local_loss(model, params, input_ids, targets)
        return (jax.lax.psum(s, ("data", "sequence")),
                jax.lax.psum(c, ("data", "sequence")))

    repl, dsh = P(), P("data", "sequence")
    eval_step = jax.jit(shard_map_unchecked(
        eval_local, mesh=mesh, in_specs=(repl, dsh, dsh), out_specs=(repl, repl)
    ))

    resume_dir = config.get("resume_from_checkpoint")
    if resume_dir:
        params = Checkpoint.from_directory(resume_dir).get_params()
        params = jax.device_put(params, NamedSharding(mesh, P()))
    else:
        from tpu_air.parallel.sequence_parallel import init_sp_params

        params = init_sp_params(model_config, mesh, seed=args.seed)
    opt_state = jax.device_put(tx.init(params), NamedSharding(mesh, P()))

    def batches(ds, bs, drop_last=True):
        for df in ds.iter_batches(batch_size=bs, batch_format="pandas",
                                  drop_last=drop_last):
            ids = collate(df, ["input_ids"])["input_ids"]
            # global next-token shift BEFORE sequence sharding, on host
            # (shift_targets semantics, without a device round-trip)
            tgt = np.concatenate(
                [ids[:, 1:], np.full((ids.shape[0], 1), pad, ids.dtype)], axis=1
            )
            if len(ids) % bs:
                # partial eval batch: pad with all-pad rows — their targets
                # are fully masked, so they contribute (0, 0) to the sums
                need = bs - len(ids) % bs
                ids = np.concatenate(
                    [ids, np.full((need, ids.shape[1]), pad, ids.dtype)]
                )
                tgt = np.concatenate(
                    [tgt, np.full((need, tgt.shape[1]), pad, tgt.dtype)]
                )
            yield shard_batch(mesh, jnp.asarray(ids), jnp.asarray(tgt))

    for epoch in range(int(args.num_train_epochs)):
        t0 = time.time()
        losses, tokens, nsteps = [], 0, 0
        for ids, tgt in batches(train_ds, global_bs):
            params, opt_state, loss = step(params, opt_state, ids, tgt)
            losses.append(loss)  # device scalar; host sync deferred to epoch end
            tokens += ids.shape[0] * ids.shape[1]
            nsteps += 1
            if args.max_steps_per_epoch and nsteps >= args.max_steps_per_epoch:
                break
        dt = time.time() - t0
        metrics: Dict[str, Any] = {
            "epoch": epoch + 1,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "steps": nsteps,
            "train_tokens_per_sec": tokens / dt if dt > 0 else 0.0,
            "train_tokens_per_sec_per_chip": tokens / dt / ndev if dt > 0 else 0.0,
            "mesh_data": dp,
            "mesh_sequence": sp,
        }
        if eval_ds is not None and args.evaluation_strategy == "epoch":
            ebs = args.per_device_eval_batch_size * dp
            # keep eval results on device across the loop; one host sync
            # after it preserves async dispatch pipelining (airlint JX004)
            parts = [eval_step(params, ids, tgt)
                     for ids, tgt in batches(eval_ds, ebs, drop_last=False)]
            tot = sum(float(s) for s, _ in parts)  # airlint: disable=JX004 — epoch cadence, not the step path
            cnt = sum(int(c) for _, c in parts)  # airlint: disable=JX004 — epoch cadence, not the step path
            if cnt:
                metrics["eval_loss"] = tot / cnt
        ckpt = None
        if args.save_strategy == "epoch":
            ckpt = Checkpoint.from_model(
                model_config=model_config,
                params=params,
                preprocessor=preprocessor,
                metrics=metrics,
            )
        session.report(metrics, checkpoint=ckpt)


def _lm_tp_loop(config, args, model_config, preprocessor, mp) -> None:
    """Tensor-parallel LM training (``ScalingConfig(model_parallel=N)``):
    a (data, model) mesh with the LM sharding rules
    (parallel/sharding.lm_param_spec) — params and optimizer state live
    1/N-per-device on the ``model`` axis, XLA inserts the TP collectives.
    The param-sharding story for the LM family beyond replication
    (VERDICT r3 weak #7): the long-context SP axis scales CONTEXT, this
    axis scales the MODEL."""
    import jax
    import jax.numpy as jnp
    from functools import partial
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_air.models.lm import (
        CausalLM,
        head_weight,
        lm_chunked_loss_with_targets,
    )
    from tpu_air.parallel import make_mesh, visible_devices
    from tpu_air.parallel.sharding import lm_param_spec, shard_params
    from tpu_air.train import session

    devs = visible_devices()
    if mp > len(devs):
        raise ValueError(
            f"model_parallel={mp} exceeds the {len(devs)} visible devices"
        )
    dp = max(1, len(devs) // mp)
    mesh = make_mesh(("data", "model"), (dp, mp), devices=devs[: dp * mp])
    ndev = dp * mp
    pad = model_config.pad_token_id

    train_ds = session.get_dataset_shard("train")
    if train_ds is None:
        raise ValueError("LMTrainer requires a 'train' dataset")
    eval_ds = session.get_dataset_shard("evaluation") or session.get_dataset_shard("eval")

    global_bs = args.per_device_train_batch_size * dp
    steps_per_epoch = max(1, train_ds.count() // global_bs)
    if args.max_steps_per_epoch:
        steps_per_epoch = min(steps_per_epoch, args.max_steps_per_epoch)
    tx = _make_optimizer(args, steps_per_epoch * args.num_train_epochs)

    model = CausalLM(model_config)
    resume_dir = config.get("resume_from_checkpoint")
    if resume_dir:
        params = Checkpoint.from_directory(resume_dir).get_params()
    else:
        import jax.random as jrandom

        params = model.init(jrandom.PRNGKey(args.seed),
                            jnp.ones((1, 8), jnp.int32))["params"]
    params = shard_params(params, mesh, spec_fn=lm_param_spec)
    opt_state = tx.init(params)
    batch_sh = NamedSharding(mesh, P("data"))

    leaves = jax.tree_util.tree_leaves(params)
    params_bytes_total = int(sum(x.nbytes for x in leaves))
    params_bytes_per_device = int(sum(
        x.addressable_shards[0].data.nbytes
        if getattr(x, "addressable_shards", None) else x.nbytes
        for x in leaves
    ))

    def loss_fn(p, ids, tgt):
        hidden = model.apply({"params": p}, ids, return_hidden=True)
        s, c = lm_chunked_loss_with_targets(
            hidden, head_weight(p, model_config), tgt, pad
        )
        return s / jnp.maximum(c, 1.0), c

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, o, ids, tgt):
        import optax as _optax

        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, ids, tgt)
        updates, o = tx.update(grads, o, p)
        return _optax.apply_updates(p, updates), o, loss

    @jax.jit
    def eval_step(p, ids, tgt):
        loss, c = loss_fn(p, ids, tgt)
        return loss, c

    def batches(ds, bs, drop_last=True):
        for df in ds.iter_batches(batch_size=bs, batch_format="pandas",
                                  drop_last=drop_last):
            ids = collate(df, ["input_ids"])["input_ids"]
            tgt = np.concatenate(
                [ids[:, 1:], np.full((ids.shape[0], 1), pad, ids.dtype)], axis=1
            )
            if len(ids) % bs:
                need = bs - len(ids) % bs
                ids = np.concatenate(
                    [ids, np.full((need, ids.shape[1]), pad, ids.dtype)]
                )
                tgt = np.concatenate(
                    [tgt, np.full((need, tgt.shape[1]), pad, tgt.dtype)]
                )
            yield (jax.device_put(jnp.asarray(ids), batch_sh),
                   jax.device_put(jnp.asarray(tgt), batch_sh))

    for epoch in range(int(args.num_train_epochs)):
        t0 = time.time()
        losses, tokens, nsteps = [], 0, 0
        for ids, tgt in batches(train_ds, global_bs):
            params, opt_state, loss = train_step(params, opt_state, ids, tgt)
            losses.append(loss)  # device scalar; host sync deferred to epoch end
            tokens += ids.shape[0] * ids.shape[1]
            nsteps += 1
            if args.max_steps_per_epoch and nsteps >= args.max_steps_per_epoch:
                break
        dt = time.time() - t0
        metrics: Dict[str, Any] = {
            "epoch": epoch + 1,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "steps": nsteps,
            "train_tokens_per_sec": tokens / dt if dt > 0 else 0.0,
            "train_tokens_per_sec_per_chip": tokens / dt / ndev if dt > 0 else 0.0,
            "mesh_data": dp,
            "mesh_model": mp,
            "mesh_sequence": 1,
            "params_bytes_total": params_bytes_total,
            "params_bytes_per_device": params_bytes_per_device,
        }
        if eval_ds is not None and args.evaluation_strategy == "epoch":
            tot, cnt = 0.0, 0
            ebs = args.per_device_eval_batch_size * dp
            for ids, tgt in batches(eval_ds, ebs, drop_last=False):
                loss, c = eval_step(params, ids, tgt)
                tot += float(loss) * int(c)
                cnt += int(c)
            if cnt:
                metrics["eval_loss"] = tot / cnt
        ckpt = None
        if args.save_strategy == "epoch":
            ckpt = Checkpoint.from_model(
                model_config=model_config,
                params=params,
                preprocessor=preprocessor,
                metrics=metrics,
            )
        session.report(metrics, checkpoint=ckpt)


class LMTrainer(BaseTrainer):
    """Long-context causal-LM trainer: SP (long context) and TP (big
    models) are ScalingConfig fields."""

    _name_prefix = "LMTrainer"

    def __init__(
        self,
        *,
        model_config,
        training_args: Optional[TrainingArguments] = None,
        trainer_init_config: Optional[Dict[str, Any]] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.model_config = model_config
        self.training_args = training_args or TrainingArguments()
        self.trainer_init_config = trainer_init_config or {}

    def _training_fn(self):
        return lm_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        return {
            "model_config": self.model_config,
            "training_args": self.training_args,
            **self.trainer_init_config,
        }
