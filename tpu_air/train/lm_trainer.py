"""LMTrainer — long-context causal-LM training through the Trainer API.

Sequence parallelism as a CONFIG CHANGE, not a bespoke script:
``ScalingConfig(num_workers=dp, sequence_parallel=sp)`` builds a
``(data, sequence)`` mesh and runs the shard_map SP step
(parallel/sequence_parallel.py — ring attention over the sequence axis,
chunked lm-head CE, replicated params with a single psum).  The reference
caps every sequence at 512 tokens (utils.py:23-28); this trainer's context
scales with the ``sequence`` axis, wrapped in the same fit() → Result →
Checkpoint contract as T5Trainer so Tune / BatchPredictor / resume compose
unchanged.

Datasets: rows with an ``input_ids`` column (fixed-length token lists).
Targets are the global next-token shift, computed BEFORE sequence sharding.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .t5_trainer import TrainingArguments, _make_optimizer, collate
from .trainer import BaseTrainer


def lm_train_loop(config: Dict[str, Any]) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_air.models.lm import LMConfig
    from tpu_air.parallel.sequence_parallel import (
        make_sp_mesh,
        make_sp_train_step,
        shard_batch,
        sp_local_loss,
    )
    from tpu_air.parallel.shardmap_compat import shard_map_unchecked
    from tpu_air.train import session

    args: TrainingArguments = config.get("training_args") or TrainingArguments()
    for k in ("learning_rate", "num_train_epochs", "weight_decay"):
        if k in config:
            setattr(args, k, config[k])

    model_config: LMConfig = config["model_config"]
    preprocessor = config.get("_preprocessor")

    sc = config.get("_scaling_config")
    sp = getattr(sc, "sequence_parallel", None) or 1
    mesh = make_sp_mesh(sp=sp)
    dp = mesh.shape["data"]
    ndev = dp * sp
    pad = model_config.pad_token_id

    train_ds = session.get_dataset_shard("train")
    if train_ds is None:
        raise ValueError("LMTrainer requires a 'train' dataset")
    eval_ds = session.get_dataset_shard("evaluation") or session.get_dataset_shard("eval")

    tx_total = train_ds.count()
    global_bs = args.per_device_train_batch_size * dp
    steps_per_epoch = max(1, tx_total // global_bs)
    if args.max_steps_per_epoch:
        steps_per_epoch = min(steps_per_epoch, args.max_steps_per_epoch)
    tx = _make_optimizer(args, steps_per_epoch * args.num_train_epochs)

    step, model = make_sp_train_step(model_config, mesh, tx)

    # eval: the SAME local-loss recipe the train step differentiates
    # (sp_local_loss — single source of truth), no update, psum'd sums
    def eval_local(params, input_ids, targets):
        s, c = sp_local_loss(model, params, input_ids, targets)
        return (jax.lax.psum(s, ("data", "sequence")),
                jax.lax.psum(c, ("data", "sequence")))

    repl, dsh = P(), P("data", "sequence")
    eval_step = jax.jit(shard_map_unchecked(
        eval_local, mesh=mesh, in_specs=(repl, dsh, dsh), out_specs=(repl, repl)
    ))

    resume_dir = config.get("resume_from_checkpoint")
    if resume_dir:
        params = Checkpoint.from_directory(resume_dir).get_params()
        params = jax.device_put(params, NamedSharding(mesh, P()))
    else:
        from tpu_air.parallel.sequence_parallel import init_sp_params

        params = init_sp_params(model_config, mesh, seed=args.seed)
    opt_state = jax.device_put(tx.init(params), NamedSharding(mesh, P()))

    def batches(ds, bs, drop_last=True):
        for df in ds.iter_batches(batch_size=bs, batch_format="pandas",
                                  drop_last=drop_last):
            ids = collate(df, ["input_ids"])["input_ids"]
            # global next-token shift BEFORE sequence sharding, on host
            # (shift_targets semantics, without a device round-trip)
            tgt = np.concatenate(
                [ids[:, 1:], np.full((ids.shape[0], 1), pad, ids.dtype)], axis=1
            )
            if len(ids) % bs:
                # partial eval batch: pad with all-pad rows — their targets
                # are fully masked, so they contribute (0, 0) to the sums
                need = bs - len(ids) % bs
                ids = np.concatenate(
                    [ids, np.full((need, ids.shape[1]), pad, ids.dtype)]
                )
                tgt = np.concatenate(
                    [tgt, np.full((need, tgt.shape[1]), pad, tgt.dtype)]
                )
            yield shard_batch(mesh, jnp.asarray(ids), jnp.asarray(tgt))

    for epoch in range(int(args.num_train_epochs)):
        t0 = time.time()
        losses, tokens, nsteps = [], 0, 0
        for ids, tgt in batches(train_ds, global_bs):
            params, opt_state, loss = step(params, opt_state, ids, tgt)
            losses.append(float(loss))
            tokens += ids.shape[0] * ids.shape[1]
            nsteps += 1
            if args.max_steps_per_epoch and nsteps >= args.max_steps_per_epoch:
                break
        dt = time.time() - t0
        metrics: Dict[str, Any] = {
            "epoch": epoch + 1,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "steps": nsteps,
            "train_tokens_per_sec": tokens / dt if dt > 0 else 0.0,
            "train_tokens_per_sec_per_chip": tokens / dt / ndev if dt > 0 else 0.0,
            "mesh_data": dp,
            "mesh_sequence": sp,
        }
        if eval_ds is not None and args.evaluation_strategy == "epoch":
            tot, cnt = 0.0, 0
            ebs = args.per_device_eval_batch_size * dp
            for ids, tgt in batches(eval_ds, ebs, drop_last=False):
                s, c = eval_step(params, ids, tgt)
                tot += float(s)
                cnt += int(c)
            if cnt:
                metrics["eval_loss"] = tot / cnt
        ckpt = None
        if args.save_strategy == "epoch":
            ckpt = Checkpoint.from_model(
                model_config=model_config,
                params=params,
                preprocessor=preprocessor,
                metrics=metrics,
            )
        session.report(metrics, checkpoint=ckpt)


class LMTrainer(BaseTrainer):
    """Long-context causal-LM trainer: SP is a ScalingConfig field."""

    _name_prefix = "LMTrainer"

    def __init__(
        self,
        *,
        model_config,
        training_args: Optional[TrainingArguments] = None,
        trainer_init_config: Optional[Dict[str, Any]] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.model_config = model_config
        self.training_args = training_args or TrainingArguments()
        self.trainer_init_config = trainer_init_config or {}

    def _training_fn(self):
        return lm_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        return {
            "model_config": self.model_config,
            "training_args": self.training_args,
            **self.trainer_init_config,
        }
