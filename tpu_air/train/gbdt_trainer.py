"""GBDT trainer — the XGBoostTrainer capability (W8, Introduction…ipynb:cc-32).

The reference trains XGBoost (C++ + rabit allreduce) via
``XGBoostTrainer(label_column, num_boost_round, params, datasets,
preprocessor)``.  Per SURVEY.md §2B, GBDTs are out of the TPU north-star
scope but a required workshop capability, kept as host-CPU training behind
the same Trainer API.  This environment has no xgboost wheel, so the backend
is sklearn gradient boosting; the config surface accepts the XGBoost param
names the reference passes (objective, tree_method, eta, max_depth,
min_child_weight) and reports the reference's metric names
(``train-logloss``/``train-error``/``valid-error``, Introduction…ipynb:cc-40).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .trainer import BaseTrainer


def _logloss(y, p):
    eps = 1e-7
    p = np.clip(p, eps, 1 - eps)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


class BaggedGBDT:
    """Merged model from distributed training: each worker trained on its
    row shard; the ensemble averages their predictions (the bagging merge —
    the sklearn-backend analog of rabit's allreduce-merged boosters)."""

    def __init__(self, models, is_classif: bool):
        self.models = list(models)
        self._is_classif = is_classif

    def _bagged_proba(self, X):
        return np.mean([m.predict_proba(X) for m in self.models], axis=0)

    def __getattr__(self, name):
        # expose predict_proba ONLY for classifier ensembles, so
        # hasattr(model, "predict_proba") — the branch GBDTPredictor takes —
        # stays honest for bagged regressors
        if name == "predict_proba" and self.__dict__.get("_is_classif"):
            return self._bagged_proba
        raise AttributeError(name)

    def predict(self, X):
        if self._is_classif:
            return (self._bagged_proba(X)[:, 1] > 0.5).astype(np.int64)
        return np.mean([m.predict(X) for m in self.models], axis=0)


def _sk_params(params: Dict[str, Any], num_boost_round: int) -> Dict[str, Any]:
    sk: Dict[str, Any] = {
        "n_estimators": num_boost_round,
        "learning_rate": float(params.get("eta", 0.3)),
        "max_depth": int(params.get("max_depth", 6)),
        "random_state": int(params.get("seed", 0)),
    }
    if "min_child_weight" in params:
        sk["min_samples_leaf"] = max(1, int(params["min_child_weight"]))
    return sk


def _df_to_xy(df, label_column):
    y = df[label_column].to_numpy()
    X = df.drop(columns=[label_column]).to_numpy(dtype=np.float64)
    return X, y


def gbdt_train_loop(config: Dict[str, Any]) -> None:
    from sklearn.ensemble import GradientBoostingClassifier, GradientBoostingRegressor

    from tpu_air.train import session

    params = dict(config.get("params", {}))
    label_column = config["label_column"]
    num_boost_round = int(config.get("num_boost_round", 10))
    objective = params.get("objective", "binary:logistic")
    is_classif = "logistic" in objective or "binary" in objective

    world = int(getattr(config.get("_scaling_config"), "num_workers", 1) or 1)
    if world > 1:
        _distributed_gbdt_loop(
            config, world, label_column, num_boost_round, objective, is_classif
        )
        return

    sk_params = _sk_params(params, num_boost_round)

    train_ds = session.get_dataset_shard("train")
    valid_ds = session.get_dataset_shard("valid")
    if valid_ds is None:
        valid_ds = session.get_dataset_shard("evaluation")
    df = train_ds.to_pandas()
    y = df[label_column].to_numpy()
    X = df.drop(columns=[label_column]).to_numpy(dtype=np.float64)
    Xv = yv = None
    if valid_ds is not None:
        vdf = valid_ds.to_pandas()
        yv = vdf[label_column].to_numpy()
        Xv = vdf.drop(columns=[label_column]).to_numpy(dtype=np.float64)

    cls = GradientBoostingClassifier if is_classif else GradientBoostingRegressor
    # warm_start: each loop turn grows the ensemble by ONE round and reports
    # before fitting the next — an ASHA stop (session.report raises StopTrial)
    # therefore genuinely saves the remaining rounds' compute, matching
    # xgboost's per-iteration eval/prune contract (Introduction…ipynb:cc-40).
    model = cls(**sk_params, warm_start=True)

    preprocessor = config.get("_preprocessor")
    feature_columns = [c for c in df.columns if c != label_column]

    def ckpt(metrics):
        return Checkpoint.from_model(
            preprocessor=preprocessor,
            metrics=metrics,
            extras={
                "sklearn_model": model,
                "label_column": label_column,
                "feature_columns": feature_columns,
                "objective": objective,
                "rounds_fit": int(model.n_estimators),
            },
        )

    for i in range(1, num_boost_round + 1):
        model.n_estimators = i
        model.fit(X, y)
        if is_classif:
            p = model.predict_proba(X)[:, 1]
            metrics = {
                "train-logloss": _logloss(y, p),
                "train-error": float(np.mean((p > 0.5) != y)),
                "iteration": i,
            }
            if Xv is not None:
                pv = model.predict_proba(Xv)[:, 1]
                metrics["valid-error"] = float(np.mean((pv > 0.5) != yv))
                metrics["valid-logloss"] = _logloss(yv, pv)
        else:
            pred = model.predict(X)
            metrics = {
                "train-rmse": float(np.sqrt(np.mean((pred - y) ** 2))),
                "iteration": i,
            }
            if Xv is not None:
                pv = model.predict(Xv)
                metrics["valid-rmse"] = float(np.sqrt(np.mean((pv - yv) ** 2)))
        # checkpoint at a bounded stride (plus the final round) so an
        # ASHA-stopped trial hands a recent ensemble to ResultGrid without
        # retaining O(num_boost_round) full-model snapshots per trial
        stride = max(1, num_boost_round // 20)
        want_ckpt = (i % stride == 0) or (i == num_boost_round)
        session.report(metrics, checkpoint=ckpt(metrics) if want_ckpt else None)


def _make_gbdt_worker_cls():
    """Actor class for one distributed-GBDT worker (built lazily so module
    import never requires a live runtime)."""
    import tpu_air

    @tpu_air.remote
    class _GBDTWorker:
        """One rank of a distributed GBDT fit (the rabit-worker analog,
        Introduction…ipynb:cc-32: XGBoostTrainer with 5 workers).

        Holds ONLY its row shard of the training data; per round it fits one
        more stage locally, then allreduces (via the host-side collectives
        facade, SURVEY.md §2D) the train-metric sums and its validation
        probabilities so every rank — and the coordinating trial loop via
        rank 0's return — sees the merged ensemble's metrics."""

        def __init__(self, rank, world_size, shard, valid_ds, label_column,
                     sk_params, is_classif, run_name):
            from sklearn.ensemble import (
                GradientBoostingClassifier,
                GradientBoostingRegressor,
            )

            self.rank = rank
            self.world = world_size
            self.run_name = run_name
            self.is_classif = is_classif
            self.X, self.y = _df_to_xy(shard.to_pandas(), label_column)
            self.Xv = self.yv = None
            if valid_ds is not None:
                self.Xv, self.yv = _df_to_xy(valid_ds.to_pandas(), label_column)
            cls = GradientBoostingClassifier if is_classif else GradientBoostingRegressor
            sk = dict(sk_params)
            sk["random_state"] = int(sk.get("random_state", 0)) + rank
            self.model = cls(**sk, warm_start=True)

        def fit_round(self, i: int):
            from tpu_air.parallel.collectives import allreduce, gather

            self.model.n_estimators = i
            self.model.fit(self.X, self.y)
            n = len(self.y)
            rname = f"{self.run_name}-round-{i}"
            # exchange the per-rank stage models so TRAIN metrics are
            # computed against the same bagged ensemble the valid metrics
            # (and the shipped checkpoint) use — local-model train metrics
            # would shift with num_workers for identical params
            models = allreduce(
                self.model, name=f"{rname}-models", rank=self.rank,
                world_size=self.world, reduce_fn=list, timeout=3600.0,
            )
            if self.is_classif:
                p = np.mean([m.predict_proba(self.X)[:, 1] for m in models], axis=0)
                sums = {
                    "n": float(n),
                    "ll_sum": _logloss(self.y, p) * n,
                    "err_sum": float(np.sum((p > 0.5) != self.y)),
                }
                valid_local = (
                    self.model.predict_proba(self.Xv)[:, 1]
                    if self.Xv is not None else None
                )
            else:
                pred = np.mean([m.predict(self.X) for m in models], axis=0)
                sums = {
                    "n": float(n),
                    "se_sum": float(np.sum((pred - self.y) ** 2)),
                }
                valid_local = (
                    self.model.predict(self.Xv) if self.Xv is not None else None
                )

            def merge(vals):
                return {k: np.sum([v[k] for v in vals], axis=0) for k in vals[0]}

            # generous rendezvous deadline: one rank's fit on a big shard can
            # take minutes, and a timeout here aborts training that the
            # single-process path would complete
            merged = allreduce(
                sums, name=rname, rank=self.rank, world_size=self.world,
                reduce_fn=merge, timeout=3600.0,
            )
            # validation predictions are large and only rank 0 consumes them:
            # gather (O(N) store reads) instead of allreduce (O(N^2))
            vlist = gather(
                valid_local, name=rname, rank=self.rank,
                world_size=self.world, dst=0, timeout=3600.0,
            )
            if self.rank != 0:
                return None
            # rank 0 turns merged sums into the reference's metric names
            metrics: Dict[str, Any] = {"iteration": i}
            have_valid = vlist is not None and vlist[0] is not None
            if self.is_classif:
                metrics["train-logloss"] = float(merged["ll_sum"] / merged["n"])
                metrics["train-error"] = float(merged["err_sum"] / merged["n"])
                if have_valid:
                    pv = np.sum(vlist, axis=0) / self.world  # bagged mean proba
                    metrics["valid-error"] = float(np.mean((pv > 0.5) != self.yv))
                    metrics["valid-logloss"] = _logloss(self.yv, pv)
            else:
                metrics["train-rmse"] = float(np.sqrt(merged["se_sum"] / merged["n"]))
                if have_valid:
                    pv = np.sum(vlist, axis=0) / self.world
                    metrics["valid-rmse"] = float(np.sqrt(np.mean((pv - self.yv) ** 2)))
            return metrics

        def get_model(self):
            return self.model

    return _GBDTWorker


def _distributed_gbdt_loop(config, world, label_column, num_boost_round,
                           objective, is_classif) -> None:
    """ScalingConfig(num_workers=N) path: N worker actors, each seeing ONLY
    its row shard; per-round merged metrics; bagged merged model in the
    checkpoint (VERDICT r2 missing 4; reference trains 5 rabit workers)."""
    import tpu_air
    from tpu_air.train import session

    params = dict(config.get("params", {}))
    sk_params = _sk_params(params, num_boost_round)

    train_ds = session.get_dataset_shard("train")
    valid_ds = session.get_dataset_shard("valid")
    if valid_ds is None:
        valid_ds = session.get_dataset_shard("evaluation")
    # equal=False: every row trains somewhere — equal shards would silently
    # drop total % world rows that the single-process path does see
    shards = train_ds.split(world, equal=False)

    sample_df = next(train_ds.iter_batches(batch_size=1, batch_format="pandas"))
    feature_columns = [c for c in sample_df.columns if c != label_column]
    preprocessor = config.get("_preprocessor")
    # rendezvous namespace must be unique per run (NOT id(config): forkserver
    # children have near-deterministic heaps, so ids collide across runs and
    # a collision would replay a dead run's allreduce payloads)
    import secrets

    run_name = f"gbdt-{secrets.token_hex(8)}"

    worker_cls = _make_gbdt_worker_cls().options(num_cpus=0)
    workers = [
        worker_cls.remote(
            r, world, shards[r], valid_ds, label_column, sk_params,
            is_classif, run_name,
        )
        for r in range(world)
    ]

    def ckpt(metrics, i):
        models = tpu_air.get([w.get_model.remote() for w in workers])
        return Checkpoint.from_model(
            preprocessor=preprocessor,
            metrics=metrics,
            extras={
                "sklearn_model": BaggedGBDT(models, is_classif),
                "label_column": label_column,
                "feature_columns": feature_columns,
                "objective": objective,
                "rounds_fit": int(i),
                "num_workers": world,
            },
        )

    from tpu_air.core import runtime as _rt

    store = _rt.current_worker().store if _rt.current_worker() else _rt.get_runtime().store

    def cleanup_round(i):
        # all ranks have returned from round i's allreduce once the futures
        # resolve, so its rendezvous keys (incl. per-round proba arrays) can
        # be deleted — otherwise they accumulate for the driver's lifetime
        for r in range(world):
            for key in (f"ar-{run_name}-round-{i}-{r}",
                        f"ar-{run_name}-round-{i}-models-{r}",
                        f"g-{run_name}-round-{i}-{r}"):
                try:
                    store.delete(key)
                except Exception:
                    pass

    try:
        for i in range(1, num_boost_round + 1):
            try:
                outs = tpu_air.get([w.fit_round.remote(i) for w in workers])
            finally:
                # also on the error path: a crashed rank must not strand the
                # round's rendezvous payloads (incl. full validation-sized
                # arrays) in the store for the driver's lifetime
                cleanup_round(i)
            metrics = outs[0]
            stride = max(1, num_boost_round // 20)
            want_ckpt = (i % stride == 0) or (i == num_boost_round)
            session.report(metrics, checkpoint=ckpt(metrics, i) if want_ckpt else None)
    finally:
        for w in workers:
            tpu_air.kill(w)


class GBDTTrainer(BaseTrainer):
    _name_prefix = "GBDTTrainer"

    def __init__(
        self,
        *,
        label_column: str,
        params: Optional[Dict[str, Any]] = None,
        num_boost_round: int = 10,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.label_column = label_column
        self.params = params or {}
        self.num_boost_round = num_boost_round

    def _training_fn(self):
        return gbdt_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        return {
            "label_column": self.label_column,
            "params": self.params,
            "num_boost_round": self.num_boost_round,
        }


#: Drop-in alias matching the reference import name (Introduction…ipynb:cc-32)
XGBoostTrainer = GBDTTrainer
