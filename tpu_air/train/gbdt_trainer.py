"""GBDT trainer — the XGBoostTrainer capability (W8, Introduction…ipynb:cc-32).

The reference trains XGBoost (C++ + rabit allreduce) via
``XGBoostTrainer(label_column, num_boost_round, params, datasets,
preprocessor)``.  Per SURVEY.md §2B, GBDTs are out of the TPU north-star
scope but a required workshop capability, kept as host-CPU training behind
the same Trainer API.  This environment has no xgboost wheel, so the
default backend is the in-repo histogram booster (``hist_gbdt.HistGBDT``)
with RABIT SEMANTICS for distributed training: per-node gradient/hessian
histograms are allreduced over the collectives facade and every rank grows
the bit-identical tree — not a bagging approximation.  The config surface
accepts the XGBoost param names the reference passes (objective,
tree_method, eta, max_depth, min_child_weight, lambda) and reports the
reference's metric names (``train-logloss``/``train-error``/
``valid-error``, Introduction…ipynb:cc-40).  ``params={"backend":
"sklearn"}`` keeps the sklearn warm-start estimator (single-process only).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .hist_gbdt import CollectivesComm, HistGBDT
from .trainer import BaseTrainer


def _logloss(y, p):
    eps = 1e-7
    p = np.clip(p, eps, 1 - eps)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def _hist_model(params: Dict[str, Any], objective: str) -> HistGBDT:
    return HistGBDT(
        objective=objective,
        eta=float(params.get("eta", 0.3)),
        max_depth=int(params.get("max_depth", 6)),
        min_child_weight=float(params.get("min_child_weight", 1.0)),
        reg_lambda=float(params.get("lambda", 1.0)),
        max_bins=int(params.get("max_bin", 256)),
    )


def _hist_metrics_from_sums(merged: Dict[str, float], is_classif: bool,
                            i: int) -> Dict[str, Any]:
    metrics: Dict[str, Any] = {"iteration": i}
    if is_classif:
        metrics["train-logloss"] = float(merged["ll_sum"] / merged["n"])
        metrics["train-error"] = float(merged["err_sum"] / merged["n"])
    else:
        metrics["train-rmse"] = float(np.sqrt(merged["se_sum"] / merged["n"]))
    return metrics


def _valid_metrics(model, Xv, yv, is_classif: bool) -> Dict[str, float]:
    """Validation metrics in the reference's names, shared by the single-
    process and distributed paths."""
    if Xv is None:
        return {}
    if is_classif:
        pv = model.predict_proba(Xv)[:, 1]
        return {
            "valid-error": float(np.mean((pv > 0.5) != yv)),
            "valid-logloss": _logloss(yv, pv),
        }
    pv = model.predict(Xv)
    return {"valid-rmse": float(np.sqrt(np.mean((pv - yv) ** 2)))}


class BaggedGBDT:
    """Unpickle-compat shim for checkpoints written by the pre-round-4
    DISTRIBUTED sklearn backend (which bagged per-rank estimators).  New
    distributed training produces a single merged-histogram ``HistGBDT``;
    this class only keeps old extras.pkl artifacts loadable/scorable."""

    def __init__(self, models, is_classif: bool):
        self.models = list(models)
        self._is_classif = is_classif

    def _bagged_proba(self, X):
        return np.mean([m.predict_proba(X) for m in self.models], axis=0)

    def __getattr__(self, name):
        if name == "predict_proba" and self.__dict__.get("_is_classif"):
            return self._bagged_proba
        raise AttributeError(name)

    def predict(self, X):
        if self._is_classif:
            return (self._bagged_proba(X)[:, 1] > 0.5).astype(np.int64)
        return np.mean([m.predict(X) for m in self.models], axis=0)


def _sk_params(params: Dict[str, Any], num_boost_round: int) -> Dict[str, Any]:
    sk: Dict[str, Any] = {
        "n_estimators": num_boost_round,
        "learning_rate": float(params.get("eta", 0.3)),
        "max_depth": int(params.get("max_depth", 6)),
        "random_state": int(params.get("seed", 0)),
    }
    if "min_child_weight" in params:
        sk["min_samples_leaf"] = max(1, int(params["min_child_weight"]))
    return sk


def _df_to_xy(df, label_column):
    y = df[label_column].to_numpy()
    X = df.drop(columns=[label_column]).to_numpy(dtype=np.float64)
    return X, y


def gbdt_train_loop(config: Dict[str, Any]) -> None:
    from tpu_air.train import session

    params = dict(config.get("params", {}))
    label_column = config["label_column"]
    num_boost_round = int(config.get("num_boost_round", 10))
    objective = params.get("objective", "binary:logistic")
    is_classif = "logistic" in objective or "binary" in objective

    world = int(getattr(config.get("_scaling_config"), "num_workers", 1) or 1)
    if world > 1:
        if params.get("backend", "hist") == "sklearn":
            raise ValueError(
                'params={"backend": "sklearn"} supports single-process '
                "training only — distributed GBDT always uses the "
                "histogram-allreduce backend (rabit semantics)"
            )
        _distributed_gbdt_loop(
            config, world, label_column, num_boost_round, objective, is_classif
        )
        return

    train_ds = session.get_dataset_shard("train")
    valid_ds = session.get_dataset_shard("valid")
    if valid_ds is None:
        valid_ds = session.get_dataset_shard("evaluation")
    df = train_ds.to_pandas()
    y = df[label_column].to_numpy()
    X = df.drop(columns=[label_column]).to_numpy(dtype=np.float64)
    Xv = yv = None
    if valid_ds is not None:
        vdf = valid_ds.to_pandas()
        yv = vdf[label_column].to_numpy()
        Xv = vdf.drop(columns=[label_column]).to_numpy(dtype=np.float64)

    if params.get("backend", "hist") != "sklearn":
        _hist_single_loop(
            config, params, label_column, num_boost_round, objective,
            is_classif, df, X, y, Xv, yv,
        )
        return

    from sklearn.ensemble import GradientBoostingClassifier, GradientBoostingRegressor

    sk_params = _sk_params(params, num_boost_round)
    cls = GradientBoostingClassifier if is_classif else GradientBoostingRegressor
    # warm_start: each loop turn grows the ensemble by ONE round and reports
    # before fitting the next — an ASHA stop (session.report raises StopTrial)
    # therefore genuinely saves the remaining rounds' compute, matching
    # xgboost's per-iteration eval/prune contract (Introduction…ipynb:cc-40).
    model = cls(**sk_params, warm_start=True)

    preprocessor = config.get("_preprocessor")
    feature_columns = [c for c in df.columns if c != label_column]

    def ckpt(metrics):
        return Checkpoint.from_model(
            preprocessor=preprocessor,
            metrics=metrics,
            extras={
                "sklearn_model": model,
                "label_column": label_column,
                "feature_columns": feature_columns,
                "objective": objective,
                "rounds_fit": int(model.n_estimators),
            },
        )

    for i in range(1, num_boost_round + 1):
        model.n_estimators = i
        model.fit(X, y)
        if is_classif:
            p = model.predict_proba(X)[:, 1]
            metrics = {
                "train-logloss": _logloss(y, p),
                "train-error": float(np.mean((p > 0.5) != y)),
                "iteration": i,
            }
            if Xv is not None:
                pv = model.predict_proba(Xv)[:, 1]
                metrics["valid-error"] = float(np.mean((pv > 0.5) != yv))
                metrics["valid-logloss"] = _logloss(yv, pv)
        else:
            pred = model.predict(X)
            metrics = {
                "train-rmse": float(np.sqrt(np.mean((pred - y) ** 2))),
                "iteration": i,
            }
            if Xv is not None:
                pv = model.predict(Xv)
                metrics["valid-rmse"] = float(np.sqrt(np.mean((pv - yv) ** 2)))
        # checkpoint at a bounded stride (plus the final round) so an
        # ASHA-stopped trial hands a recent ensemble to ResultGrid without
        # retaining O(num_boost_round) full-model snapshots per trial
        stride = max(1, num_boost_round // 20)
        want_ckpt = (i % stride == 0) or (i == num_boost_round)
        session.report(metrics, checkpoint=ckpt(metrics) if want_ckpt else None)


def _hist_single_loop(config, params, label_column, num_boost_round,
                      objective, is_classif, df, X, y, Xv, yv) -> None:
    """Single-process histogram boosting — the world_size=1 case of the SAME
    algorithm the distributed path runs, so metrics agree in kind across
    num_workers."""
    from tpu_air.train import session

    model = _hist_model(params, objective)
    model.setup(X, y)
    preprocessor = config.get("_preprocessor")
    feature_columns = [c for c in df.columns if c != label_column]

    def ckpt(metrics, i):
        return Checkpoint.from_model(
            preprocessor=preprocessor,
            metrics=metrics,
            extras={
                "sklearn_model": model.scoring_copy(),
                "label_column": label_column,
                "feature_columns": feature_columns,
                "objective": objective,
                "rounds_fit": int(i),
                "backend": "hist",
            },
        )

    for i in range(1, num_boost_round + 1):
        model.fit_one_round()
        metrics = _hist_metrics_from_sums(
            model.local_metric_sums(), is_classif, i
        )
        metrics.update(_valid_metrics(model, Xv, yv, is_classif))
        stride = max(1, num_boost_round // 20)
        want_ckpt = (i % stride == 0) or (i == num_boost_round)
        session.report(metrics, checkpoint=ckpt(metrics, i) if want_ckpt else None)


def _make_gbdt_worker_cls():
    """Actor class for one distributed-GBDT worker (built lazily so module
    import never requires a live runtime)."""
    import tpu_air

    @tpu_air.remote
    class _GBDTWorker:
        """One rank of a distributed GBDT fit — the rabit-worker analog
        (Introduction…ipynb:cc-32: XGBoostTrainer with 5 workers).

        Holds ONLY its row shard; per round, per tree depth, the
        (node, feature, bin) gradient/hessian histograms are allreduced
        over the collectives facade (SURVEY.md §2D) and every rank grows
        the bit-identical tree from the merged statistics — true
        distributed BOOSTING, not bagging."""

        def __init__(self, rank, world_size, shard, valid_ds, label_column,
                     params, objective, is_classif, run_name):
            self.rank = rank
            self.world = world_size
            self.is_classif = is_classif
            self.comm = CollectivesComm(rank, world_size, run_name)
            X, y = _df_to_xy(shard.to_pandas(), label_column)
            self.Xv = self.yv = None
            if valid_ds is not None:
                self.Xv, self.yv = _df_to_xy(valid_ds.to_pandas(), label_column)
            self.model = _hist_model(params, objective)
            # merged bin edges: an allgather — every rank ends with the
            # identical binning
            self.model.setup(X, y, comm=self.comm)

        def fit_round(self, i: int):
            self.model.fit_one_round()
            sums = self.model.local_metric_sums()
            keys = sorted(sums)
            merged_arr = self.comm.allreduce_sum(
                np.array([sums[k] for k in keys]), f"metrics-{i}"
            )
            # the round's collective store keys ride along in the return so
            # the trial loop can delete them without another (blockable)
            # actor round-trip; every rank reports the same names
            used = self.comm.drain_store_keys()
            if self.rank != 0:
                return {"metrics": None, "used_keys": used}
            merged = dict(zip(keys, merged_arr))
            metrics = _hist_metrics_from_sums(merged, self.is_classif, i)
            # every rank's model is identical — rank 0 scores validation
            metrics.update(
                _valid_metrics(self.model, self.Xv, self.yv, self.is_classif)
            )
            return {"metrics": metrics, "used_keys": used}

        def get_model(self):
            return self.model.scoring_copy()

        def get_signature(self):
            return self.model.signature()

    return _GBDTWorker


def _distributed_gbdt_loop(config, world, label_column, num_boost_round,
                           objective, is_classif) -> None:
    """ScalingConfig(num_workers=N) path: N worker actors, each seeing ONLY
    its row shard, growing IDENTICAL trees from allreduce-merged histograms
    (rabit semantics — VERDICT r3 weak #4; reference trains 5 rabit
    workers).  Rank identity is asserted at every checkpoint round, so
    divergence is a hard training error, not silent skew."""
    import tpu_air
    from tpu_air.train import session

    params = dict(config.get("params", {}))

    train_ds = session.get_dataset_shard("train")
    valid_ds = session.get_dataset_shard("valid")
    if valid_ds is None:
        valid_ds = session.get_dataset_shard("evaluation")
    # equal=False: every row trains somewhere — equal shards would silently
    # drop total % world rows that the single-process path does see
    shards = train_ds.split(world, equal=False)

    sample_df = next(train_ds.iter_batches(batch_size=1, batch_format="pandas"))
    feature_columns = [c for c in sample_df.columns if c != label_column]
    preprocessor = config.get("_preprocessor")
    # rendezvous namespace must be unique per run (NOT id(config): forkserver
    # children have near-deterministic heaps, so ids collide across runs and
    # a collision would replay a dead run's allreduce payloads)
    import secrets

    run_name = f"gbdt-{secrets.token_hex(8)}"

    worker_cls = _make_gbdt_worker_cls().options(num_cpus=0)
    workers = [
        worker_cls.remote(
            r, world, shards[r],
            valid_ds if r == 0 else None,  # only rank 0 scores validation
            label_column, params, objective, is_classif, run_name,
        )
        for r in range(world)
    ]

    def ckpt(metrics, i):
        # every rank holds the identical booster — assert it (cheap hash),
        # then ship rank 0's
        sigs = tpu_air.get([w.get_signature.remote() for w in workers])
        if len(set(sigs)) != 1:
            raise RuntimeError(
                "distributed GBDT ranks diverged — allreduced histograms "
                "should make every rank's booster bit-identical"
            )
        metrics["ranks_identical"] = True
        model = tpu_air.get(workers[0].get_model.remote())
        return Checkpoint.from_model(
            preprocessor=preprocessor,
            metrics=metrics,
            extras={
                "sklearn_model": model,
                "label_column": label_column,
                "feature_columns": feature_columns,
                "objective": objective,
                "rounds_fit": int(i),
                "num_workers": world,
                "backend": "hist",
            },
        )

    from tpu_air.core import runtime as _rt

    store = _rt.current_worker().store if _rt.current_worker() else _rt.get_runtime().store

    def delete_keys(keys):
        # all ranks have returned from the round's collectives (the futures
        # resolved), so the rendezvous keys can be deleted — otherwise they
        # accumulate for the driver's lifetime.  On a crashed-rank round no
        # keys are returned; that one round's payloads leak (bounded) rather
        # than stalling the error path behind another actor round-trip.
        for key in set(keys):
            try:
                store.delete(key)
            except Exception:  # noqa: BLE001 — best-effort cleanup; key may already be gone
                pass

    try:
        for i in range(1, num_boost_round + 1):
            outs = tpu_air.get([w.fit_round.remote(i) for w in workers])
            delete_keys([k for o in outs for k in o["used_keys"]])
            metrics = outs[0]["metrics"]
            stride = max(1, num_boost_round // 20)
            want_ckpt = (i % stride == 0) or (i == num_boost_round)
            session.report(metrics, checkpoint=ckpt(metrics, i) if want_ckpt else None)
    finally:
        for w in workers:
            tpu_air.kill(w)


class GBDTTrainer(BaseTrainer):
    _name_prefix = "GBDTTrainer"

    def __init__(
        self,
        *,
        label_column: str,
        params: Optional[Dict[str, Any]] = None,
        num_boost_round: int = 10,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.label_column = label_column
        self.params = params or {}
        self.num_boost_round = num_boost_round

    def _training_fn(self):
        return gbdt_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        return {
            "label_column": self.label_column,
            "params": self.params,
            "num_boost_round": self.num_boost_round,
        }


#: Drop-in alias matching the reference import name (Introduction…ipynb:cc-32)
XGBoostTrainer = GBDTTrainer
