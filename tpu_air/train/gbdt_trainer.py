"""GBDT trainer — the XGBoostTrainer capability (W8, Introduction…ipynb:cc-32).

The reference trains XGBoost (C++ + rabit allreduce) via
``XGBoostTrainer(label_column, num_boost_round, params, datasets,
preprocessor)``.  Per SURVEY.md §2B, GBDTs are out of the TPU north-star
scope but a required workshop capability, kept as host-CPU training behind
the same Trainer API.  This environment has no xgboost wheel, so the backend
is sklearn gradient boosting; the config surface accepts the XGBoost param
names the reference passes (objective, tree_method, eta, max_depth,
min_child_weight) and reports the reference's metric names
(``train-logloss``/``train-error``/``valid-error``, Introduction…ipynb:cc-40).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .checkpoint import Checkpoint
from .trainer import BaseTrainer


def _logloss(y, p):
    eps = 1e-7
    p = np.clip(p, eps, 1 - eps)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def gbdt_train_loop(config: Dict[str, Any]) -> None:
    from sklearn.ensemble import GradientBoostingClassifier, GradientBoostingRegressor

    from tpu_air.train import session

    params = dict(config.get("params", {}))
    label_column = config["label_column"]
    num_boost_round = int(config.get("num_boost_round", 10))
    objective = params.get("objective", "binary:logistic")
    is_classif = "logistic" in objective or "binary" in objective

    sk_params: Dict[str, Any] = {
        "n_estimators": num_boost_round,
        "learning_rate": float(params.get("eta", 0.3)),
        "max_depth": int(params.get("max_depth", 6)),
        "random_state": int(params.get("seed", 0)),
    }
    if "min_child_weight" in params:
        sk_params["min_samples_leaf"] = max(1, int(params["min_child_weight"]))

    train_ds = session.get_dataset_shard("train")
    valid_ds = session.get_dataset_shard("valid")
    if valid_ds is None:
        valid_ds = session.get_dataset_shard("evaluation")
    df = train_ds.to_pandas()
    y = df[label_column].to_numpy()
    X = df.drop(columns=[label_column]).to_numpy(dtype=np.float64)
    Xv = yv = None
    if valid_ds is not None:
        vdf = valid_ds.to_pandas()
        yv = vdf[label_column].to_numpy()
        Xv = vdf.drop(columns=[label_column]).to_numpy(dtype=np.float64)

    cls = GradientBoostingClassifier if is_classif else GradientBoostingRegressor
    # warm_start: each loop turn grows the ensemble by ONE round and reports
    # before fitting the next — an ASHA stop (session.report raises StopTrial)
    # therefore genuinely saves the remaining rounds' compute, matching
    # xgboost's per-iteration eval/prune contract (Introduction…ipynb:cc-40).
    model = cls(**sk_params, warm_start=True)

    preprocessor = config.get("_preprocessor")
    feature_columns = [c for c in df.columns if c != label_column]

    def ckpt(metrics):
        return Checkpoint.from_model(
            preprocessor=preprocessor,
            metrics=metrics,
            extras={
                "sklearn_model": model,
                "label_column": label_column,
                "feature_columns": feature_columns,
                "objective": objective,
                "rounds_fit": int(model.n_estimators),
            },
        )

    for i in range(1, num_boost_round + 1):
        model.n_estimators = i
        model.fit(X, y)
        if is_classif:
            p = model.predict_proba(X)[:, 1]
            metrics = {
                "train-logloss": _logloss(y, p),
                "train-error": float(np.mean((p > 0.5) != y)),
                "iteration": i,
            }
            if Xv is not None:
                pv = model.predict_proba(Xv)[:, 1]
                metrics["valid-error"] = float(np.mean((pv > 0.5) != yv))
                metrics["valid-logloss"] = _logloss(yv, pv)
        else:
            pred = model.predict(X)
            metrics = {
                "train-rmse": float(np.sqrt(np.mean((pred - y) ** 2))),
                "iteration": i,
            }
            if Xv is not None:
                pv = model.predict(Xv)
                metrics["valid-rmse"] = float(np.sqrt(np.mean((pv - yv) ** 2)))
        # checkpoint at a bounded stride (plus the final round) so an
        # ASHA-stopped trial hands a recent ensemble to ResultGrid without
        # retaining O(num_boost_round) full-model snapshots per trial
        stride = max(1, num_boost_round // 20)
        want_ckpt = (i % stride == 0) or (i == num_boost_round)
        session.report(metrics, checkpoint=ckpt(metrics) if want_ckpt else None)


class GBDTTrainer(BaseTrainer):
    _name_prefix = "GBDTTrainer"

    def __init__(
        self,
        *,
        label_column: str,
        params: Optional[Dict[str, Any]] = None,
        num_boost_round: int = 10,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.label_column = label_column
        self.params = params or {}
        self.num_boost_round = num_boost_round

    def _training_fn(self):
        return gbdt_train_loop

    def _train_loop_config(self) -> Dict[str, Any]:
        return {
            "label_column": self.label_column,
            "params": self.params,
            "num_boost_round": self.num_boost_round,
        }


#: Drop-in alias matching the reference import name (Introduction…ipynb:cc-32)
XGBoostTrainer = GBDTTrainer
